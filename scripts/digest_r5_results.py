#!/usr/bin/env python
"""Digest the r5 banked chip results into the facts BASELINE.md needs.

Reads whichever of the r5 evidence files exist and prints a compact
summary: the flash-vs-dense verdicts (model rows, kernel A/B, block
ladder), the deep-vs-wide story (LM rows, roofline fit via
``scripts/fit_roofline.py``), the MoE rows, and the b512 bisection
rungs.  Purely read-only - the human writes the conclusions.
"""

import json
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def _load(name):
    p = REPO / name
    if not p.exists():
        return None
    try:
        return json.loads(p.read_text())
    except json.JSONDecodeError:
        return None


def _fmt(v):
    return json.dumps(v) if not isinstance(v, dict) else ", ".join(
        f"{k}={v[k]}" for k in sorted(v))


def section(title):
    print(f"\n=== {title} ===")


def main():
    attn = _load("results_bench_chip_r5_attn.json")
    if attn:
        section("attention (results_bench_chip_r5_attn.json)")
        em = attn.get("extra_metrics", {})
        for k in sorted(em):
            if k.startswith("attention"):
                print(f"{k}: {_fmt(em[k])}")
        ab = em.get("attention_kernel_ab_seq1024_d128")
        if isinstance(ab, dict) and isinstance(ab.get("flash_speedup"),
                                               (int, float)):
            verdict = ("FLASH WINS" if ab["flash_speedup"] >= 1.5
                       else "below the 1.5x target")
            print(f"-> kernel A/B seq1024: {ab['flash_speedup']}x "
                  f"({verdict})")

    rnn = _load("results_bench_chip_r5.json")
    if rnn:
        section("rnn/LM (results_bench_chip_r5.json)")
        em = rnn.get("extra_metrics", {})
        for k in sorted(em):
            if k.startswith(("char_", "motion_")):
                print(f"{k}: {json.dumps(em[k])[:240]}")
        if isinstance(em.get("char_rnn_recurrent_roofline"), dict):
            print("-> run: python scripts/fit_roofline.py "
                  "results_bench_chip_r5.json")

    moe = _load("results_bench_chip_r5_moe.json")
    if moe:
        section("moe (results_bench_chip_r5_moe.json)")
        em = moe.get("extra_metrics", {})
        for k in sorted(em):
            if k.startswith("moe_"):
                print(f"{k}: {json.dumps(em[k])[:240]}")

    b512 = REPO / "results_b512_repro.json"
    if b512.exists():
        section("b512 bisection (results_b512_repro.json)")
        for line in b512.read_text().splitlines():
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                continue
            # placeholders, not KeyError: one malformed rung line must
            # degrade the report, not kill it (ADVICE r5)
            err = f" {r['error'][:80]}" if r.get("error") else ""
            print(f"{r.get('rung', '?')}: {r.get('status', '?')} "
                  f"({r.get('seconds', '?')}s){err}")

    if not any((attn, rnn, moe, b512.exists())):
        print("no r5 chip evidence banked yet (tunnel has not opened)")


if __name__ == "__main__":
    main()
