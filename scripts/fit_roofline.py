#!/usr/bin/env python
"""Fit the recurrent-scan roofline from banked bench rows.

Reads the ``char_rnn_recurrent_roofline`` grid out of a banked bench
line (default: ``results_bench_chip_r5.json``) and fits, per batch size,

    t_pass = flops / eff_peak + (2 * seq) * tau

across the hidden sizes measured - two unknowns (effective peak
throughput and per-sequential-step overhead tau), two H points per B.
The tau estimate is the deep-vs-wide MFU gap's explanation candidate:
deep (4 x 1280) runs 2x the sequential steps of wide (2 x 2048) per
token at ~2.56x smaller per-step matmuls, so a fixed tau taxes it twice.

Usage: python scripts/fit_roofline.py [results_bench_chip_r5.json]
"""

import json
import sys
from pathlib import Path


def fit(rows):
    """rows: list of roofline row dicts sharing a batch size.  Least
    squares over ALL rows (exact at two points; overdetermined when the
    grid grows a third H), solving t = f/P + s*tau with t in seconds,
    f = training FLOPs, s = sequential steps (2*seq)."""
    if len(rows) < 2:
        return None
    import numpy as np

    def f(r):
        return 3.0 * r["seq"] * 2 * r["batch"] * r["hidden"] * 4 * r["hidden"]

    a = np.array([[f(r), 2 * r["seq"]] for r in rows])
    t = np.array([r["ms_per_pass"] / 1e3 for r in rows])
    (inv_p, tau), *_ = np.linalg.lstsq(a, t, rcond=None)
    if inv_p == 0:
        return None
    return {"eff_peak_tflops": round(1e-12 / inv_p, 1),
            "tau_us_per_step": round(tau * 1e6, 3)}


def main():
    path = Path(sys.argv[1] if len(sys.argv) > 1
                else "results_bench_chip_r5.json")
    line = json.loads(path.read_text())
    grid = line["extra_metrics"]["char_rnn_recurrent_roofline"]
    cells = [v for v in grid.values() if isinstance(v, dict)]
    for batch in sorted({c["batch"] for c in cells}):
        sub = sorted((c for c in cells if c["batch"] == batch),
                     key=lambda c: c["hidden"])
        out = fit(sub)
        print(f"B={batch}: cells="
              + ", ".join(f"H{c['hidden']}={c['ms_per_pass']}ms"
                          f"({c['mfu_vs_v5e_bf16_peak']:.1%})"
                          for c in sub)
              + (f" -> eff_peak={out['eff_peak_tflops']} TF/s, "
                 f"tau={out['tau_us_per_step']} us/step" if out else
                 " -> not enough cells to fit"))


if __name__ == "__main__":
    main()
