#!/bin/bash
# Chip-window watcher (r5): probe the axon tunnel every ~4 min; the
# moment a probe sees a real TPU, run every queued chip-gated runner
# that has not yet produced committed evidence this round.  Tunnel
# windows are scarce (r4: one ~25-min window in ~22 h) - measurements
# must fire the moment one opens, not when a human notices.
#
# Flap-safe: the watcher only exits once ALL queued runners have
# succeeded; a tunnel drop mid-run leaves it looping for the next
# window.  Ordered by value, never-measured work first:
#   1. ATTN   - the dim-512/head_dim-128 dense-vs-flash rows, the
#               seq-4096 point, and the block_q x block_k ladder
#               (--suite attention; per-row append keeps partial
#               evidence if the window dies mid-suite)
#   2. B512   - the batch-512 bisection rung ladder (repro_batch512.py
#               appends one JSON line per rung to results_b512_repro)
#   3. MOE    - the EP family's first on-chip throughput rows
#               (--suite moe: 3 routers + dense A/B)
#   4. RNN    - the RNN/LM family rows only (--suite rnn: the LM ladder
#               now auto-rescues b512 via grad-accum instead of
#               skipping, plus the recurrent roofline grid and the
#               deep-shape lever rows).  NOT --suite stress: that would
#               re-measure the attention+moe rows the dedicated runners
#               above just banked, blowing the window budget.
#   5. CHIP   - the long resumable run-chip CLI sweep (fused +
#               dropout-0 rows).  Before each attempt, FAILED rows are
#               pruned from the results file - the sweep's
#               resume-by-skip filters on command-string presence
#               regardless of returncode, so a row that failed in a
#               dead window would otherwise be skipped forever.
# The watcher does NOT git-commit (it would race the foreground
# session's index); freshly-banked files are picked up and committed by
# the session.
cd /root/repo || exit 1
ATTN_DONE=0
B512_DONE=0
MOE_DONE=0
RNN_DONE=0
CHIP_DONE=0
bank_bench() {
  # $1 = log file, $2 = destination results file.  Same predicate for
  # the done-gate and the extraction: the single JSON contract line,
  # which carries the backend field (bench.py falls back to CPU when
  # the probe dies - a CPU line must not count).
  local line
  line=$(grep '"metric"' "$1" | tail -1)
  if [ -n "$line" ] && echo "$line" | grep -q '"backend": "tpu"'; then
    echo "$line" > "$2"
    return 0
  fi
  return 1
}
while true; do
  if timeout 90 python -c "
import jax
assert jax.default_backend() == 'tpu'
" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel LIVE - running queued chip runners" >> /tmp/chip_watcher.log
    if [ "$ATTN_DONE" != 1 ]; then
      timeout 2100 python bench.py --suite attention \
        --append-rows results_bench_attn_rows_r5.jsonl > /tmp/bench_attn.log 2>&1
      bank_bench /tmp/bench_attn.log results_bench_chip_r5_attn.json && ATTN_DONE=1
      echo "$(date -u +%FT%TZ) attention bench done=$ATTN_DONE" >> /tmp/chip_watcher.log
    fi
    if [ "$B512_DONE" != 1 ]; then
      timeout 900 python repro_batch512.py >> /tmp/chip_watcher.log 2>&1 \
        && B512_DONE=1
      echo "$(date -u +%FT%TZ) repro_batch512 done=$B512_DONE" >> /tmp/chip_watcher.log
    fi
    if [ "$MOE_DONE" != 1 ]; then
      timeout 900 python bench.py --suite moe \
        --append-rows results_bench_moe_rows_r5.jsonl > /tmp/bench_moe.log 2>&1
      bank_bench /tmp/bench_moe.log results_bench_chip_r5_moe.json && MOE_DONE=1
      echo "$(date -u +%FT%TZ) moe bench done=$MOE_DONE" >> /tmp/chip_watcher.log
    fi
    if [ "$RNN_DONE" != 1 ]; then
      timeout 2400 python bench.py --suite rnn \
        --append-rows results_bench_rows_r5.jsonl > /tmp/bench_rnn.log 2>&1
      bank_bench /tmp/bench_rnn.log results_bench_chip_r5.json && RNN_DONE=1
      echo "$(date -u +%FT%TZ) rnn bench done=$RNN_DONE" >> /tmp/chip_watcher.log
    fi
    if [ "$CHIP_DONE" != 1 ]; then
      python - <<'EOF' >> /tmp/chip_watcher.log 2>&1
import json, os
path = "results_tpu_chip_r4.json"
if os.path.exists(path):
    rows = json.load(open(path))
    kept = [r for r in rows if r.get("returncode") == 0]
    if len(kept) != len(rows):
        json.dump(kept, open(path, "w"), indent=1)
        print(f"pruned {len(rows) - len(kept)} FAILED row(s) from {path}")
EOF
      timeout 1800 python -m pytorch_distributed_rnn_tpu.launcher run-chip \
        --backend native --results results_tpu_chip_r4.json --timeout 300 \
        >> /tmp/chip_watcher.log 2>&1 && CHIP_DONE=1
      echo "$(date -u +%FT%TZ) run-chip done=$CHIP_DONE" >> /tmp/chip_watcher.log
    fi
    if [ "$ATTN_DONE" = 1 ] && [ "$B512_DONE" = 1 ] && [ "$MOE_DONE" = 1 ] \
       && [ "$RNN_DONE" = 1 ] && [ "$CHIP_DONE" = 1 ]; then
      echo "$(date -u +%FT%TZ) all queued runners complete" >> /tmp/chip_watcher.log
      exit 0
    fi
  else
    echo "$(date -u +%FT%TZ) tunnel down" >> /tmp/chip_watcher.log
  fi
  sleep 240
done
