#!/bin/bash
# Chip-window watcher: probe the axon tunnel every ~4 min; the moment a
# probe sees a real TPU, run every queued chip-gated runner that has not
# yet produced committed evidence this round.  Tunnel windows are scarce
# (r4: one ~25-min window in ~13 h) - measurements must fire the moment
# one opens, not when a human notices.
#
# Flap-safe: the watcher only exits once ALL THREE queued runners have
# succeeded (ATTN bench rows, batch-512 bisection, run-chip sweep); a
# tunnel drop mid-run leaves it looping for the next window.  Ordered by
# value: never-measured work first (the dim-512/seq-4096 attention rows
# via the fast `--suite attention` path with per-row append, then the
# batch-512 bisection with its own per-rung append), the long resumable
# run-chip sweep last.  Before each run-chip attempt, FAILED rows are
# pruned from the results file - the sweep's resume-by-skip filters on
# command-string presence regardless of returncode, so a row that failed
# in a dead window would otherwise be skipped forever.
cd /root/repo || exit 1
ATTN_DONE=0
B512_DONE=0
CHIP_DONE=0
while true; do
  if timeout 90 python -c "
import jax
assert jax.default_backend() == 'tpu'
" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel LIVE - running queued chip runners" >> /tmp/chip_watcher.log
    if [ "$ATTN_DONE" != 1 ]; then
      timeout 1500 python bench.py --suite attention \
        --append-rows results_bench_attn_rows.jsonl > /tmp/bench_attn.log 2>&1
      # same predicate for the done-gate and the extraction: the single
      # JSON contract line, which carries the backend field (bench.py
      # falls back to CPU when the probe dies - a CPU line must not
      # count); per-row evidence is already on disk via --append-rows
      # even when the final emit never happens
      line=$(grep '"metric"' /tmp/bench_attn.log | tail -1)
      if [ -n "$line" ] && echo "$line" | grep -q '"backend": "tpu"'; then
        echo "$line" > results_bench_chip_r4_attn.json
        ATTN_DONE=1
      fi
      echo "$(date -u +%FT%TZ) attention bench done=$ATTN_DONE" >> /tmp/chip_watcher.log
    fi
    if [ "$B512_DONE" != 1 ]; then
      timeout 900 python repro_batch512.py >> /tmp/chip_watcher.log 2>&1 \
        && B512_DONE=1
      echo "$(date -u +%FT%TZ) repro_batch512 done=$B512_DONE" >> /tmp/chip_watcher.log
    fi
    if [ "$CHIP_DONE" != 1 ]; then
      python - <<'EOF' >> /tmp/chip_watcher.log 2>&1
import json, os
path = "results_tpu_chip_r4.json"
if os.path.exists(path):
    rows = json.load(open(path))
    kept = [r for r in rows if r.get("returncode") == 0]
    if len(kept) != len(rows):
        json.dump(kept, open(path, "w"), indent=1)
        print(f"pruned {len(rows) - len(kept)} FAILED row(s) from {path}")
EOF
      timeout 1800 python -m pytorch_distributed_rnn_tpu.launcher run-chip \
        --backend native --results results_tpu_chip_r4.json --timeout 300 \
        >> /tmp/chip_watcher.log 2>&1 && CHIP_DONE=1
      echo "$(date -u +%FT%TZ) run-chip done=$CHIP_DONE" >> /tmp/chip_watcher.log
    fi
    if [ "$ATTN_DONE" = 1 ] && [ "$B512_DONE" = 1 ] && [ "$CHIP_DONE" = 1 ]; then
      echo "$(date -u +%FT%TZ) all queued runners complete" >> /tmp/chip_watcher.log
      exit 0
    fi
  else
    echo "$(date -u +%FT%TZ) tunnel down" >> /tmp/chip_watcher.log
  fi
  sleep 240
done
