#!/usr/bin/env python
"""Single-process smoke test: one forward/backward/update, print params.

Capability parity with ``/root/reference/src/example/example_single.py``:
a lone Linear(10,10), MSE loss, SGD step, parameters printed at the end.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from pytorch_distributed_rnn_tpu.utils import apply_platform_overrides

apply_platform_overrides()

import jax
import jax.numpy as jnp
import optax

from pytorch_distributed_rnn_tpu.ops import linear_init, mse_loss


def run(rank=0):
    key = jax.random.PRNGKey(0)
    pkey, xkey, ykey = jax.random.split(key, 3)
    params = linear_init(pkey, 10, 10)
    x = jax.random.normal(xkey, (20, 10))
    labels = jax.random.normal(ykey, (20, 10))

    def loss_fn(p):
        pred = x @ p["weight"].T + p["bias"]
        return mse_loss(pred, labels)

    grads = jax.grad(loss_fn)(params)
    params = optax.apply_updates(
        params, jax.tree.map(lambda g: -0.001 * g, grads)
    )
    print(jax.tree.map(lambda p: p, params))


if __name__ == "__main__":
    run(0)
