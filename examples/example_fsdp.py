#!/usr/bin/env python
"""ZeRO/FSDP example: train the 50M char-LM with sharded state.

New capability over the reference (which holds a full replica per rank,
``/root/reference/src/motion/trainer/ddp.py:19``): parameters AND Adam
state are constructed directly into a sharded layout over the ``dp`` axis
— per-chip state bytes ~ 1/n — and the train step is plain jit with those
shardings pinned; XLA inserts the all-gather/reduce-scatter schedule.

Run on 8 virtual CPU devices:
    PDRNN_PLATFORM=cpu PDRNN_NUM_CPU_DEVICES=8 python examples/example_fsdp.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from pytorch_distributed_rnn_tpu.utils import apply_platform_overrides

apply_platform_overrides()

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pytorch_distributed_rnn_tpu.models import CharRNN, num_params
from pytorch_distributed_rnn_tpu.parallel import make_mesh
from pytorch_distributed_rnn_tpu.parallel.zero import (
    init_sharded,
    init_sharded_opt_state,
    make_fsdp_train_step,
    per_device_bytes,
)


def run():
    mesh = make_mesh()  # one dp axis over every visible device
    n = mesh.devices.size
    # small preset off-TPU; swap in char_rnn_50m() on a real slice
    model = CharRNN(vocab_size=64, embed_dim=64, hidden_dim=128,
                    layer_dim=2, impl="scan")

    params, p_shard = init_sharded(model, jax.random.PRNGKey(0), mesh)
    opt = optax.adam(1e-2)
    opt_state, o_shard = init_sharded_opt_state(opt, params, mesh)

    total_mb = sum(
        int(np.prod(p.shape)) * p.dtype.itemsize
        for p in jax.tree.leaves(params)
    ) / 1e6
    print(f"{num_params(params) / 1e6:.1f}M params, "
          f"replicated {total_mb:.1f}MB -> per-device "
          f"{per_device_bytes(params) / 1e6:.1f}MB over {n} devices")

    step = make_fsdp_train_step(model.loss, opt, mesh, p_shard, o_shard)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 64, size=(16, 32)), jnp.int32)
    for i in range(20):
        params, opt_state, loss = step(params, opt_state, tokens)
        if i % 5 == 0:
            print(f"step {i}: loss {float(loss):.4f}")
    print(f"final loss {float(loss):.4f}")


if __name__ == "__main__":
    run()
