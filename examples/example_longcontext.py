#!/usr/bin/env python
"""Long-context tour: ring attention over a sequence-sharded mesh.

New capability beyond the reference (it has no attention at all -
SURVEY.md checklist; long context is this framework's first-class
extension).  A sequence of length T shards into T/n chunks over the
``sp`` axis; each device holds its chunk's queries while K/V blocks
rotate around the ring (``lax.ppermute``), folding into a running
online-softmax - O(T/n) activation memory per device instead of O(T^2)
scores, which is what makes million-token contexts reachable on a real
slice.  This example:

1. runs ring attention on an 8-way sp mesh and checks it against plain
   full-sequence attention - exact to float tolerance;
2. does the same through Ulysses (all_to_all head-scatter) - the other
   sequence-parallel layout, better when heads >> devices;
3. runs the causal variant (the LM case: each position attends to its
   prefix ONLY, across chunk boundaries - a traced per-shard offset
   drives the mask);
4. trains one step of the attention classifier over the composed
   dp x sp x tp mesh to show the ring inside a real training program.

Demos 1-3 use the dense XLA online-softmax inner directly (the numerics
reference); demo 4 resolves the model's attention impl like the CLI
does, which on a TPU selects the fused Pallas flash kernel as the
per-shard inner (``ops/pallas_attention.py``) - the numerics contract
is identical either way.

Run on an 8-way virtual CPU mesh:
  PDRNN_PLATFORM=cpu PDRNN_NUM_CPU_DEVICES=8 \
      python examples/example_longcontext.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from pytorch_distributed_rnn_tpu.utils import apply_platform_overrides

apply_platform_overrides()

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
from pytorch_distributed_rnn_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from pytorch_distributed_rnn_tpu.models import AttentionClassifier
from pytorch_distributed_rnn_tpu.ops.attention import (
    mha_attention,
    ring_attention,
    ulysses_attention,
)
from pytorch_distributed_rnn_tpu.parallel import make_mesh
from pytorch_distributed_rnn_tpu.parallel.combined import make_3d_train_step

SP = 8
B, H, T, D = 2, 8, 256, 32  # T shards into 8 chunks of 32


def main():
    if len(jax.devices()) < SP:
        raise SystemExit(
            f"needs {SP} devices (set PDRNN_PLATFORM=cpu "
            f"PDRNN_NUM_CPU_DEVICES={SP})"
        )
    mesh = make_mesh({"sp": SP})
    rng = np.random.RandomState(0)
    q, k, v = (
        jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
        for _ in range(3)
    )

    # 1. ring attention == full attention (time sharded over sp)
    @partial(shard_map, mesh=mesh, in_specs=(P(None, None, "sp"),) * 3,
             out_specs=P(None, None, "sp"), check_vma=False)
    def ring(q, k, v):
        return ring_attention(q, k, v, "sp")

    out_ring = jax.jit(ring)(q, k, v)
    out_full = mha_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_full),
                               rtol=2e-5, atol=2e-5)
    print(f"ring == full attention over sp={SP}: "
          f"max|diff| = {float(jnp.abs(out_ring - out_full).max()):.2e}")

    # 2. Ulysses (all_to_all head scatter) == full attention
    @partial(shard_map, mesh=mesh, in_specs=(P(None, None, "sp"),) * 3,
             out_specs=P(None, None, "sp"), check_vma=False)
    def ulysses(q, k, v):
        return ulysses_attention(q, k, v, "sp")

    out_u = jax.jit(ulysses)(q, k, v)
    np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_full),
                               rtol=2e-5, atol=2e-5)
    print(f"ulysses == full attention over sp={SP}: "
          f"max|diff| = {float(jnp.abs(out_u - out_full).max()):.2e}")

    # 3. causal ring: each position attends to its global prefix only -
    # chunk boundaries included (the per-shard offset is traced)
    @partial(shard_map, mesh=mesh, in_specs=(P(None, None, "sp"),) * 3,
             out_specs=P(None, None, "sp"), check_vma=False)
    def ring_causal(q, k, v):
        return ring_attention(q, k, v, "sp", causal=True)

    out_rc = jax.jit(ring_causal)(q, k, v)
    out_fc = mha_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_rc), np.asarray(out_fc),
                               rtol=2e-5, atol=2e-5)
    print(f"causal ring == causal full over sp={SP}: "
          f"max|diff| = {float(jnp.abs(out_rc - out_fc).max()):.2e}")

    # 4. the CAUSAL ring inside a real training step: dp x sp x tp with
    # causal=True - the LM framing of demo 3 threaded through the whole
    # composed program (the plain non-causal composition is demo 1 of
    # examples/example_4d.py; this one is the long-context variant)
    axes = {"dp": 2, "sp": 2, "tp": 2}
    mesh3d = make_mesh(axes)
    model = AttentionClassifier(input_dim=9, dim=32, depth=2, num_heads=4,
                                output_dim=6, max_len=64)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adam(1e-3)
    state = opt.init(params)
    step = make_3d_train_step(model, opt, mesh3d, causal=True,
                              donate=False)
    x = jnp.asarray(rng.randn(4, 64, 9).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 6, size=4))
    losses = []
    for _ in range(5):
        params, state, loss = step(params, state, (x, y))
        losses.append(float(loss))
    assert losses[-1] < losses[0], "training did not reduce loss"
    print(f"causal dp x sp x tp training {axes}: "
          f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    print("long-context example OK")


if __name__ == "__main__":
    main()
