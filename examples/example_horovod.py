#!/usr/bin/env python
"""Horovod-flavor data-parallel smoke test: broadcast, then allreduce-in-step.

Capability parity with ``/root/reference/src/example/example_horovod.py``:
parameters are explicitly broadcast from rank 0 before training
(``hvd.broadcast_parameters`` analogue), each rank trains on its OWN shard
of the 24-sample dataset via the distributed sampler (the reference enables
it here, unlike example_ddp), and gradient averaging happens inside the
optimizer step (``hvd.DistributedOptimizer`` analogue).
"""
import pathlib
import sys
from functools import partial

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from pytorch_distributed_rnn_tpu.utils import apply_platform_overrides

apply_platform_overrides()

import jax
import jax.numpy as jnp
import numpy as np
from pytorch_distributed_rnn_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from pytorch_distributed_rnn_tpu.data import DistributedSampler
from pytorch_distributed_rnn_tpu.models import ToyModel
from pytorch_distributed_rnn_tpu.ops import mse_loss
from pytorch_distributed_rnn_tpu.parallel import make_mesh, broadcast_params
from pytorch_distributed_rnn_tpu.parallel.collectives import pmean_tree


def param_sum(tree):
    return sum(float(jnp.sum(l)) for l in jax.tree.leaves(tree))


def run(mesh):
    world = mesh.shape["dp"]
    if world > 12:
        raise SystemExit(
            f"this example's 24-sample dataset supports at most 12 ranks "
            f"(per-rank batch = 12 // world); got world={world}"
        )
    model = ToyModel()

    base = model.init(jax.random.PRNGKey(0))
    params = jax.tree.map(lambda l: jnp.broadcast_to(l, (world,) + l.shape), base)
    for rank in range(world):
        print("rank ", rank, "initial:", param_sum(jax.tree.map(lambda l: l[rank], params)))

    params = broadcast_params(params, mesh)  # hvd.broadcast_parameters
    for rank in range(world):
        print("rank", rank, "synced:", param_sum(jax.tree.map(lambda l: l[rank], params)))

    rng = np.random.RandomState(0)
    features = rng.randn(24, 10).astype(np.float32)
    labels = rng.randn(24, 5).astype(np.float32)
    batch_size = 12 // world
    lr = 0.001

    # per-rank shards from the sampler (shuffle like the reference's default)
    shard_indices = np.stack(
        [DistributedSampler(24, world, r, seed=0).indices() for r in range(world)]
    )  # (world, 24 // world)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp")),
        check_vma=False,
    )
    def train_step(stacked_params, x, y):
        p = jax.tree.map(lambda l: l[0], stacked_params)
        x, y = x[0], y[0]

        loss, grads = jax.value_and_grad(
            lambda q: mse_loss(model.apply(q, x), y)
        )(p)
        # hvd.DistributedOptimizer: allreduce happens inside step()
        grads = pmean_tree(grads, "dp")
        p = jax.tree.map(lambda a, g: a - lr * g, p, grads)
        return jax.tree.map(lambda l: l[None], p), loss[None]

    step = jax.jit(train_step)

    samples_per_rank = 24 // world
    for start in range(0, samples_per_rank, batch_size):
        idx = shard_indices[:, start : start + batch_size]  # (world, bs)
        x = jnp.asarray(features[idx])  # (world, bs, 10)
        y = jnp.asarray(labels[idx])
        for rank in range(world):
            print("rank", rank, "inputs:", float(jnp.sum(x[rank])))
            print("rank", rank, "labels:", float(jnp.sum(y[rank])))
        params, losses = step(params, x, y)
        for rank in range(world):
            print(
                "rank", rank,
                "parameters:",
                param_sum(jax.tree.map(lambda l: l[rank], params)),
            )

    final = [
        param_sum(jax.tree.map(lambda l: l[rank], params)) for rank in range(world)
    ]
    assert all(abs(f - final[0]) < 1e-6 for f in final), f"rank divergence: {final}"
    print("PARITY-OK", final[0])
    return final[0]


if __name__ == "__main__":
    run(make_mesh())
