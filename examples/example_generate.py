#!/usr/bin/env python
"""Train a tiny char-LM and sample from it (new capability; the reference
has no LM or inference path - its only model is the HAR classifier,
``/root/reference/src/motion/model.py:4-17``).

Trains ``CharRNN`` for a few hundred steps on a synthetic
deterministic-successor token stream (each token's successor is fixed, so
the LM can drive next-token loss to ~0), then decodes greedily and with
temperature sampling from the learned model.  Greedy decoding must
reproduce the successor chain - the printed check asserts it.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from pytorch_distributed_rnn_tpu.utils import apply_platform_overrides

apply_platform_overrides()

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pytorch_distributed_rnn_tpu.models import CharRNN

VOCAB = 32
SEED = 0


def successor(tok):
    """The ground-truth next token: a fixed permutation of the vocab."""
    return (7 * tok + 3) % VOCAB


def main():
    model = CharRNN(vocab_size=VOCAB, embed_dim=16, hidden_dim=64,
                    layer_dim=1, impl="scan")
    params = model.init(jax.random.PRNGKey(SEED))
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(model.loss)(params, tokens)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.RandomState(SEED)
    loss = None
    for i in range(300):
        start = rng.randint(0, VOCAB, size=(16, 1)).astype(np.int32)
        seq = [start]
        for _ in range(24):
            seq.append(successor(seq[-1]))
        tokens = jnp.asarray(np.concatenate(seq, axis=1))
        params, opt_state, loss = step(params, opt_state, tokens)
    print(f"final next-token loss after 300 steps: {float(loss):.4f}")

    prompt = jnp.asarray([[1, int(successor(1))]], jnp.int32)
    greedy = np.asarray(model.generate(params, prompt, length=8,
                                       temperature=0.0))[0]
    expected = [1, successor(1)]
    for _ in range(8):
        expected.append(int(successor(expected[-1])))
    print(f"greedy decode:   {greedy.tolist()}")
    print(f"successor chain: {expected}")
    assert greedy.tolist() == expected, "greedy decode diverged from the chain"

    sampled = np.asarray(
        model.generate(params, prompt, length=8,
                       key=jax.random.PRNGKey(42), temperature=1.0)
    )[0]
    print(f"temperature 1.0: {sampled.tolist()}")
    print("generation ok")


if __name__ == "__main__":
    main()
