#!/usr/bin/env python
"""Data-parallel rank-parity smoke test - the framework's north-star check.

Capability parity with ``/root/reference/src/example/example_ddp.py``: every
"rank" (mesh position along ``dp``) holds its own replica of a seeded
ToyModel, trains with SGD lr=0.001 on a 24-sample dataset at per-rank batch
size 12 // world_size, gradients are averaged across ranks each step (XLA
AllReduce via ``pmean`` - the DDP allreduce analogue), and the script prints
the same per-rank quantities (initial/synced/grad/batch/loss/parameters
sums).  Success criterion: the final ``parameters:`` sums are identical on
every rank (reference ``README.md:9``).

Preserved reference quirk: the sampler is disabled
(``example_ddp.py:62`` comments it out), so every rank iterates the FULL
dataset - ranks process identical batches.

Run on an 8-way virtual CPU mesh:
  PDRNN_PLATFORM=cpu PDRNN_NUM_CPU_DEVICES=8 python examples/example_ddp.py
or on a TPU slice (world = number of chips).
"""
import pathlib
import sys
from functools import partial

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from pytorch_distributed_rnn_tpu.utils import apply_platform_overrides

apply_platform_overrides()

import jax
import jax.numpy as jnp
import numpy as np
from pytorch_distributed_rnn_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from pytorch_distributed_rnn_tpu.models import ToyModel
from pytorch_distributed_rnn_tpu.ops import mse_loss
from pytorch_distributed_rnn_tpu.parallel import broadcast_params, make_mesh
from pytorch_distributed_rnn_tpu.parallel.collectives import pmean_tree


def param_sum(tree):
    """sum(parameter.sum() for parameter in model.parameters()) analogue."""
    return sum(float(jnp.sum(l)) for l in jax.tree.leaves(tree))


def run(mesh):
    world = mesh.shape["dp"]
    if world > 12:
        raise SystemExit(
            f"this example's 24-sample dataset supports at most 12 ranks "
            f"(per-rank batch = 12 // world); got world={world}"
        )
    model = ToyModel()

    # seeded identical init on every rank (reference seeds torch+numpy to 0)
    base = model.init(jax.random.PRNGKey(0))
    # each rank owns a replica: stack along a leading rank axis, shard on dp
    params = jax.tree.map(
        lambda l: jnp.broadcast_to(l, (world,) + l.shape), base
    )
    for rank in range(world):
        print("rank", rank, "initial:", param_sum(jax.tree.map(lambda l: l[rank], params)))

    # DDP-wrap analogue: broadcast rank 0's replica to everyone.  With seeded
    # init this is a no-op numerically, exactly as in the reference.
    params = broadcast_params(params, mesh)
    for rank in range(world):
        print("rank", rank, "synced:", param_sum(jax.tree.map(lambda l: l[rank], params)))

    # dataset: 24 samples, torch.randn analogue with fixed numpy seed
    rng = np.random.RandomState(0)
    features = rng.randn(24, 10).astype(np.float32)
    labels = rng.randn(24, 5).astype(np.float32)
    batch_size = 12 // world

    lr = 0.001

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("dp"), P(None), P(None)),
        out_specs=(P("dp"), P("dp"), P("dp")),
        check_vma=False,
    )
    def train_step(stacked_params, x, y):
        p = jax.tree.map(lambda l: l[0], stacked_params)  # this rank's replica

        def loss_fn(q):
            return mse_loss(model.apply(q, x), y)

        loss, grads = jax.value_and_grad(loss_fn)(p)
        grads = pmean_tree(grads, "dp")  # DDP reducer analogue
        p = jax.tree.map(lambda a, g: a - lr * g, p, grads)
        stacked = jax.tree.map(lambda l: l[None], p)
        grad_sum = sum(jnp.sum(g) for g in jax.tree.leaves(grads))
        return stacked, loss[None], grad_sum[None]

    step = jax.jit(train_step)

    last_grad = {rank: None for rank in range(world)}
    for start in range(0, 24, batch_size):
        x = jnp.asarray(features[start : start + batch_size])
        y = jnp.asarray(labels[start : start + batch_size])
        for rank in range(world):
            print("rank", rank, "grad:", last_grad[rank])
            print("rank", rank, "batch:", float(jnp.sum(x) + jnp.sum(y)))
        params, losses, grad_sums = step(params, x, y)
        for rank in range(world):
            print("rank", rank, "loss:", float(losses[rank]))
            print(
                "rank", rank,
                "parameters:",
                param_sum(jax.tree.map(lambda l: l[rank], params)),
            )
            last_grad[rank] = float(grad_sums[rank])

    # the success criterion: identical final parameters on every rank
    final = [
        param_sum(jax.tree.map(lambda l: l[rank], params)) for rank in range(world)
    ]
    assert all(abs(f - final[0]) < 1e-6 for f in final), f"rank divergence: {final}"
    print("PARITY-OK", final[0])
    return final[0]


if __name__ == "__main__":
    run(make_mesh())
