#!/usr/bin/env python
"""Point-to-point primitive smoke test.

Capability parity with ``/root/reference/src/example/example_distributed.py``:
rank 0's tensor (value 1.0) reaches every other rank; each rank prints
``Rank  i  has data  1.0``.  TPU-native transport: ``lax.ppermute`` ring
relay (XLA CollectivePermute over ICI) instead of MPI send/recv.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from pytorch_distributed_rnn_tpu.utils import apply_platform_overrides

apply_platform_overrides()

import jax
import jax.numpy as jnp

from pytorch_distributed_rnn_tpu.parallel import make_mesh, ring_relay_from_root


def run(mesh):
    world = mesh.shape["dp"]
    # rank 0 holds 1.0, everyone else 0.0 (the "tensor += 1 on rank 0")
    values = jnp.where(jnp.arange(world)[:, None] == 0, 1.0, 0.0)
    received = ring_relay_from_root(values, mesh)
    for rank in range(world):
        print("Rank ", rank, " has data ", float(received[rank, 0]))
    assert bool(jnp.all(received == 1.0))
    return received


if __name__ == "__main__":
    run(make_mesh())
