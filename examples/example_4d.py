#!/usr/bin/env python
"""Multi-axis parallelism tour: dp x sp x tp on one mesh, then ep.

New capability beyond the reference (its only axis was data parallelism
over MPI, SURVEY.md parallelism checklist).  This example runs:

1. a composed dp x sp x tp training step on the AttentionClassifier -
   batch sharded over ``dp``, ring attention over the time-sharded ``sp``
   axis, heads/MLP Megatron-sharded over ``tp``;
2. a sequence-parallel LSTM forward (wavefront schedule) on the motion
   model over ``sp``;
3. an expert-parallel MoE step over ``ep`` (all_to_all dispatch/combine),

and checks each against its single-device reference - the rank-parity idea
of ``example_ddp.py``, extended to every axis.

Run on an 8-way virtual CPU mesh:
  PDRNN_PLATFORM=cpu PDRNN_NUM_CPU_DEVICES=8 python examples/example_4d.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from pytorch_distributed_rnn_tpu.utils import apply_platform_overrides

apply_platform_overrides()

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pytorch_distributed_rnn_tpu.models import (
    AttentionClassifier,
    MotionModel,
)
from pytorch_distributed_rnn_tpu.ops import cross_entropy_loss
from pytorch_distributed_rnn_tpu.ops.moe import init_moe_ffn, moe_ffn_dense
from pytorch_distributed_rnn_tpu.parallel import (
    make_ep_moe_forward,
    make_mesh,
    make_sp_forward,
)
from pytorch_distributed_rnn_tpu.parallel.combined import (
    make_3d_loss_fn,
    make_3d_train_step,
)


def main():
    if len(jax.devices()) < 8:
        raise SystemExit("needs 8 devices (set PDRNN_NUM_CPU_DEVICES=8)")
    rng = np.random.RandomState(0)

    # ---- 1. composed dp x sp x tp training ------------------------------
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    model = AttentionClassifier(input_dim=9, dim=32, depth=2, num_heads=4,
                                output_dim=6, max_len=64)
    params = model.init(jax.random.PRNGKey(0))
    x = jnp.asarray(rng.randn(8, 64, 9).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 6, size=8))

    loss_3d = jax.jit(make_3d_loss_fn(model, mesh))(params, x, y)
    loss_ref = cross_entropy_loss(model.apply(params, x), y)
    print(f"dp x sp x tp loss {float(loss_3d):.6f} "
          f"(single-device {float(loss_ref):.6f})")
    assert abs(float(loss_3d) - float(loss_ref)) < 1e-4

    opt = optax.adam(1e-3)
    step = make_3d_train_step(model, opt, mesh, donate=False)
    opt_state = opt.init(params)
    for i in range(10):
        params, opt_state, loss = step(params, opt_state, (x, y))
    print(f"after 10 composed steps: loss {float(loss):.4f}")

    # ---- 2. sequence-parallel LSTM (wavefront) --------------------------
    sp_mesh = make_mesh({"sp": 8})
    motion = MotionModel(input_dim=9, hidden_dim=32, layer_dim=2,
                         output_dim=6, impl="scan")
    mparams = motion.init(jax.random.PRNGKey(1))
    xm = jnp.asarray(rng.randn(4, 128, 9).astype(np.float32))
    logits_sp = make_sp_forward(sp_mesh)(mparams, xm)
    logits_ref = motion.apply(mparams, xm)
    np.testing.assert_allclose(logits_sp, logits_ref, rtol=1e-4, atol=1e-5)
    print("sequence-parallel LSTM (8-way wavefront) matches single-device")

    # ---- 3. expert parallelism ------------------------------------------
    ep_mesh = make_mesh({"ep": 8})
    eparams = init_moe_ffn(jax.random.PRNGKey(2), 16, 8, 32)
    xt = jnp.asarray(rng.randn(64, 16).astype(np.float32))
    out_ep, aux = make_ep_moe_forward(ep_mesh, capacity_factor=8.0)(
        eparams, xt)
    out_ref, _ = moe_ffn_dense(eparams, xt)
    np.testing.assert_allclose(out_ep, out_ref, rtol=1e-4, atol=1e-5)
    print(f"expert-parallel MoE (8 experts / 8 shards) matches dense "
          f"(aux={float(aux):.3f})")
    print("OK")


if __name__ == "__main__":
    main()
