# Reproducible runtime for pytorch_distributed_rnn_tpu (CPU image).
#
# The reference captured its environment as a 2-stage Docker build
# (/root/reference/Dockerfile:8-38: torch compiled USE_MPI=ON, then a slim
# runtime with OpenMPI + sshd).  Its TPU-native analogue needs no MPI and
# no sshd: ranks rendezvous over env (MASTER_ADDR/RANK/WORLD_SIZE for the
# native TCP transport, PDRNN_COORDINATOR/... for jax.distributed worlds),
# so the image is single-stage - pinned Python deps + the C++ toolchain
# that builds the collectives transport.
#
# Build:  docker build -t pdrnn-tpu .
# Smoke:  docker run pdrnn-tpu            (2-rank DDP parity check,
#         the reference's `mpirun ... example_ddp.py` analogue,
#         /root/reference/README.md:8-9)
# Tests:  docker run pdrnn-tpu python -m pytest tests/ -q
#
# On TPU VMs, swap the jax pin for the libtpu wheel
# (pip install 'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html)
# and run the same entrypoints; nothing else changes.
#
# NOTE: not buildable inside the zero-egress development image this repo
# is authored in - it is the environment-capture artifact for CI/real
# deployments (verified recipe: the same pip pins + g++ path the in-tree
# suite exercises).

FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/pdrnn
COPY requirements.txt pyproject.toml ./
RUN pip install --no-cache-dir -r requirements.txt

COPY pytorch_distributed_rnn_tpu ./pytorch_distributed_rnn_tpu
COPY examples ./examples
COPY tests ./tests
COPY bench.py pytest.ini README.md ./

# Pre-build the C++ TCP collectives library (runtime/native.py rebuilds on
# demand; baking it keeps first-run latency out of rank startup).
RUN python -c "from pytorch_distributed_rnn_tpu.runtime.native import build_native_library; build_native_library()"

ENV PDRNN_PLATFORM=cpu
# The always-runnable 2-rank parity check (identical final params on every
# rank) - the reference's smoke test, no cluster required.
CMD ["python", "-m", "pytorch_distributed_rnn_tpu.launcher", "preflight", "--world-size", "2"]
