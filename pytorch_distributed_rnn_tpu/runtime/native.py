"""ctypes bindings to the native TCP collectives library.

Builds ``libpdrnn_collectives.so`` from ``csrc/collectives.cpp`` on first
use (g++, no external deps) and exposes a ``Communicator`` with numpy-array
collectives: send/recv, broadcast, ring allreduce, allgather, barrier, plus
netem-analogue fault injection (delay/loss).

This is the framework's Gloo/MPI analogue (SURVEY.md §2.8): rendezvous uses
``MASTER_ADDR``/``MASTER_PORT``-style coordinates exactly like the
reference's torch RPC path (``/root/reference/src/motion/param_server/
__init__.py:41-42``), and the primitive set mirrors what the reference
exercises over MPI/Horovod (broadcast, allreduce, send/recv - SURVEY §5
"Distributed communication backend").
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

import numpy as np

_CSRC = Path(__file__).parent / "csrc" / "collectives.cpp"
_LIB_PATH = Path(__file__).parent / "csrc" / "libpdrnn_collectives.so"

_lib = None


def _allreduce_dtypes():
    """Wire dtypes the native ring supports (codes match collectives.cpp
    pdrnn_allreduce).  bf16 comes from ml_dtypes (jax's numpy extension
    dtypes package, always present alongside jax)."""
    codes = {"float32": 0, "float64": 1}
    try:
        import ml_dtypes  # noqa: F401

        codes[np.dtype(ml_dtypes.bfloat16).name] = 2
    except ImportError:  # pragma: no cover - ml_dtypes ships with jax
        pass
    return codes


_ALLREDUCE_DTYPES = _allreduce_dtypes()


def build_native_library(force: bool = False) -> Path:
    """Compile the .so if missing or stale; returns its path."""
    if (
        not force
        and _LIB_PATH.exists()
        and _LIB_PATH.stat().st_mtime >= _CSRC.stat().st_mtime
    ):
        return _LIB_PATH
    # compile to a process-unique temp path then rename: rename is atomic,
    # so concurrently-spawned ranks never dlopen a half-written .so
    tmp_path = _LIB_PATH.with_suffix(f".so.tmp.{os.getpid()}")
    cmd = [
        "g++",
        "-O2",
        "-shared",
        "-fPIC",
        "-std=c++17",
        "-pthread",
        str(_CSRC),
        "-o",
        str(tmp_path),
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp_path, _LIB_PATH)
    return _LIB_PATH


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(str(build_native_library()))
    lib.pdrnn_init.restype = ctypes.c_void_p
    lib.pdrnn_init.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.pdrnn_init_star.restype = ctypes.c_void_p
    lib.pdrnn_init_star.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.pdrnn_init_listener.restype = ctypes.c_void_p
    lib.pdrnn_init_listener.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.pdrnn_rank.argtypes = [ctypes.c_void_p]
    lib.pdrnn_world.argtypes = [ctypes.c_void_p]
    lib.pdrnn_reserve.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.pdrnn_accept_peer.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.pdrnn_close_peer.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.pdrnn_set_fault.argtypes = [ctypes.c_void_p, ctypes.c_double, ctypes.c_double]
    lib.pdrnn_send.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_void_p,
        ctypes.c_int64,
    ]
    lib.pdrnn_recv.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_void_p,
        ctypes.c_int64,
    ]
    lib.pdrnn_broadcast.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_void_p,
        ctypes.c_int64,
    ]
    lib.pdrnn_allreduce_f32.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int,
    ]
    lib.pdrnn_allreduce.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.pdrnn_reduce_scatter.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_void_p,
    ]
    lib.pdrnn_allgather.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_void_p,
    ]
    lib.pdrnn_barrier.argtypes = [ctypes.c_void_p]
    lib.pdrnn_reduce_scatter_async.restype = ctypes.c_int64
    lib.pdrnn_reduce_scatter_async.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_void_p,
    ]
    lib.pdrnn_allgather_async.restype = ctypes.c_int64
    lib.pdrnn_allgather_async.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_void_p,
    ]
    lib.pdrnn_allreduce_async.restype = ctypes.c_int64
    lib.pdrnn_allreduce_async.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.pdrnn_wait.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.pdrnn_thread_count.argtypes = [ctypes.c_void_p]
    lib.pdrnn_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class CollectiveHandle:
    """Nonblocking-collective handle from :meth:`Communicator.reduce_scatter_async`
    / :meth:`Communicator.allgather_async`.

    Holds the wire buffers alive while the persistent comm worker runs the
    collective (the C side borrows the pointers), plus bookkeeping the
    overlap telemetry reads after :meth:`Communicator.wait`:

    - ``result``    - the output array (valid only after wait)
    - ``comm_seconds`` - the collective's exclusive execution time on the
      comm worker (what the wire cost WOULD be with zero overlap); set by
      wait from the C-side job clock.
    """

    __slots__ = ("id", "op", "result", "comm_seconds", "_keepalive", "_done")

    def __init__(self, handle_id: int, op: str, result, keepalive):
        self.id = handle_id
        self.op = op
        self.result = result
        self.comm_seconds = 0.0
        self._keepalive = keepalive
        self._done = False


class Communicator:
    """Rank-addressed collectives over TCP (host-side transport)."""

    def __init__(
        self,
        master_addr: str = "127.0.0.1",
        master_port: int = 29500,
        rank: int = 0,
        world_size: int = 1,
        star: bool = False,
    ):
        lib = _load()
        self._lib = lib
        if star:
            # elastic (re)join: dial rank 0 only - the star topology the
            # parameter server actually uses.  The master must be running
            # an elastic acceptor (`accept_peer`) for the dial to be
            # installed as a peer; no mesh or port table is exchanged.
            if rank < 1:
                raise ValueError("star join is for worker ranks (>= 1)")
            self._handle = lib.pdrnn_init_star(
                master_addr.encode(), master_port, rank, world_size
            )
        else:
            self._handle = lib.pdrnn_init(
                master_addr.encode(), master_port, rank, world_size
            )
        if not self._handle:
            raise RuntimeError(
                f"rendezvous failed (rank {rank}/{world_size} via "
                f"{master_addr}:{master_port}"
                f"{', star join' if star else ''})"
            )
        self.rank = rank
        self.world_size = world_size
        # netem analogue: the launcher's network-perturbation sweep exports
        # these before spawning ranks, mirroring how the reference applies
        # `tc qdisc ... netem` per host around a run (fabfile.py:130-191)
        delay_ms = float(os.environ.get("PDRNN_FAULT_DELAY_MS", "0") or 0)
        loss_prob = float(os.environ.get("PDRNN_FAULT_LOSS_PROB", "0") or 0)
        if delay_ms or loss_prob:
            self.set_fault(delay_ms, loss_prob)

    @classmethod
    def listener(cls, port: int, capacity: int = 2) -> "Communicator":
        """Listener-only world: rank 0 bound to a KNOWN ``port`` with an
        empty ``capacity``-slot peer table - peers arrive later via
        :meth:`accept_peer` star joins.  The host end of an MPMD
        pipeline link (``runtime/stage.py``): the fixed port is what
        lets a respawned downstream stage re-dial without a rendezvous
        exchange."""
        lib = _load()
        self = cls.__new__(cls)
        self._lib = lib
        self._handle = lib.pdrnn_init_listener(int(port), int(capacity))
        if not self._handle:
            raise RuntimeError(f"listener world failed to bind port {port}")
        self.rank = 0
        self.world_size = 1
        delay_ms = float(os.environ.get("PDRNN_FAULT_DELAY_MS", "0") or 0)
        loss_prob = float(os.environ.get("PDRNN_FAULT_LOSS_PROB", "0") or 0)
        if delay_ms or loss_prob:
            self.set_fault(delay_ms, loss_prob)
        return self

    # -- fault injection (netem analogue) -----------------------------------

    def set_fault(self, delay_ms: float = 0.0, loss_prob: float = 0.0):
        self._lib.pdrnn_set_fault(self._handle, delay_ms, loss_prob)

    # -- elastic membership (master side) ------------------------------------

    def reserve(self, capacity: int):
        """Grow the peer table to ``capacity`` rank slots so elastic
        accepts of brand-new ranks never reallocate it under concurrent
        send/recv.  Call once, before the acceptor thread starts."""
        self._lib.pdrnn_reserve(self._handle, int(capacity))

    def accept_peer(self, timeout_s: float = 0.5) -> int | None:
        """Accept one elastic (re)join on the rendezvous listener (rank 0
        only).  Returns the joining rank, or ``None`` on timeout or a
        rejected stray connection.  ``world_size`` grows when a brand-new
        rank joins."""
        rank = self._lib.pdrnn_accept_peer(
            self._handle, int(timeout_s * 1000)
        )
        if rank < 0:
            return None
        self.world_size = max(self.world_size, rank + 1)
        return rank

    def close_peer(self, rank: int):
        """Shut down and close one peer's socket (drain/death cleanup);
        a later elastic accept of the same rank installs a fresh one."""
        self._lib.pdrnn_close_peer(self._handle, int(rank))

    # -- primitives ----------------------------------------------------------

    def _check(self, status: int, op: str):
        if status != 0:
            raise RuntimeError(f"{op} failed (rank {self.rank})")

    def send(self, dst: int, array: np.ndarray):
        array = np.ascontiguousarray(array)
        self._check(
            self._lib.pdrnn_send(
                self._handle, dst, array.ctypes.data, array.nbytes
            ),
            "send",
        )

    def recv(self, src: int, shape, dtype=np.float32) -> np.ndarray:
        out = np.empty(shape, dtype=dtype)
        self._check(
            self._lib.pdrnn_recv(self._handle, src, out.ctypes.data, out.nbytes),
            "recv",
        )
        return out

    def broadcast(self, array: np.ndarray, root: int = 0) -> np.ndarray:
        array = np.ascontiguousarray(array)
        self._check(
            self._lib.pdrnn_broadcast(
                self._handle, root, array.ctypes.data, array.nbytes
            ),
            "broadcast",
        )
        return array

    def allreduce(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        """In-place ring allreduce.  Supports f32, f64, and bf16 wire
        dtypes (bf16 rides at 2 bytes/element - half the gradient traffic
        of f32, the point of ``--precision bf16`` over a slow link; each
        ring hop accumulates in f32 and rounds back to bf16)."""
        dtype_code = _ALLREDUCE_DTYPES.get(array.dtype.name)
        if dtype_code is None:
            raise TypeError(
                f"allreduce supports {sorted(_ALLREDUCE_DTYPES)}, "
                f"got {array.dtype.name}"
            )
        array = np.ascontiguousarray(array)
        self._check(
            self._lib.pdrnn_allreduce(
                self._handle, array.ctypes.data, array.size, dtype_code,
                {"sum": 0, "mean": 1}[op],
            ),
            "allreduce",
        )
        return array

    def reduce_scatter(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        """Ring reduce-scatter: returns this rank's ``size // world_size``
        chunk (chunk ``rank``) of the elementwise reduction as a 1-D
        array.  ``array.size`` must divide evenly by ``world_size`` -
        callers pad (the sharded weight update's padded-ravel
        bookkeeping).  The input is treated as scratch: a private copy is
        reduced in place, the caller's array is never mutated.

        The reduce phase reuses the allreduce ring's exact accumulation
        order, so each chunk is bitwise-equal to the same slice of
        :meth:`allreduce` - the property the sharded-vs-replicated
        update-parity tests pin."""
        dtype_code = _ALLREDUCE_DTYPES.get(array.dtype.name)
        if dtype_code is None:
            raise TypeError(
                f"reduce_scatter supports {sorted(_ALLREDUCE_DTYPES)}, "
                f"got {array.dtype.name}"
            )
        if array.size % self.world_size:
            raise ValueError(
                f"reduce_scatter needs size % world == 0, got "
                f"{array.size} % {self.world_size}"
            )
        scratch = np.ascontiguousarray(array).reshape(-1).copy()
        out = np.empty(array.size // self.world_size, dtype=array.dtype)
        self._check(
            self._lib.pdrnn_reduce_scatter(
                self._handle, scratch.ctypes.data, scratch.size,
                dtype_code, {"sum": 0, "mean": 1}[op], out.ctypes.data,
            ),
            "reduce_scatter",
        )
        return out

    def allgather(self, array: np.ndarray) -> np.ndarray:
        array = np.ascontiguousarray(array)
        out = np.empty((self.world_size,) + array.shape, dtype=array.dtype)
        self._check(
            self._lib.pdrnn_allgather(
                self._handle, array.ctypes.data, array.nbytes, out.ctypes.data
            ),
            "allgather",
        )
        return out

    # -- nonblocking collectives --------------------------------------------
    #
    # Collectives (sync and async) run FIFO on one persistent comm worker
    # per communicator, so async handles stay matched across ranks as
    # long as every rank posts them in the same program order.  wait()
    # blocks only until ITS job finished; later queued collectives keep
    # streaming - the overlap the bucketed gradient path exploits.

    def reduce_scatter_async(
        self, array: np.ndarray, op: str = "sum"
    ) -> CollectiveHandle:
        """Nonblocking :meth:`reduce_scatter`.  Returns a handle whose
        ``result`` (this rank's reduced chunk) is valid after
        :meth:`wait`.  Same dtype/divisibility contract and the same
        bitwise accumulation order as the blocking form."""
        dtype_code = _ALLREDUCE_DTYPES.get(array.dtype.name)
        if dtype_code is None:
            raise TypeError(
                f"reduce_scatter supports {sorted(_ALLREDUCE_DTYPES)}, "
                f"got {array.dtype.name}"
            )
        if array.size % self.world_size:
            raise ValueError(
                f"reduce_scatter needs size % world == 0, got "
                f"{array.size} % {self.world_size}"
            )
        scratch = np.ascontiguousarray(array).reshape(-1).copy()
        out = np.empty(array.size // self.world_size, dtype=array.dtype)
        handle_id = self._lib.pdrnn_reduce_scatter_async(
            self._handle, scratch.ctypes.data, scratch.size,
            dtype_code, {"sum": 0, "mean": 1}[op], out.ctypes.data,
        )
        return CollectiveHandle(handle_id, "reduce_scatter", out, scratch)

    def allgather_async(self, array: np.ndarray) -> CollectiveHandle:
        """Nonblocking :meth:`allgather`; ``result`` has shape
        ``(world,) + array.shape`` after :meth:`wait`."""
        array = np.ascontiguousarray(array)
        out = np.empty((self.world_size,) + array.shape, dtype=array.dtype)
        handle_id = self._lib.pdrnn_allgather_async(
            self._handle, array.ctypes.data, array.nbytes, out.ctypes.data
        )
        return CollectiveHandle(handle_id, "allgather", out, array)

    def wait(self, handle: CollectiveHandle) -> np.ndarray:
        """Block until ``handle``'s collective completed; returns its
        result array.  Idempotent: waiting a finished handle returns the
        cached result.  ``handle.comm_seconds`` is filled with the job's
        exclusive execution time on the comm worker."""
        if not handle._done:
            seconds = ctypes.c_double(0.0)
            status = self._lib.pdrnn_wait(
                self._handle, handle.id, ctypes.byref(seconds)
            )
            handle.comm_seconds = float(seconds.value)
            handle._done = True
            handle._keepalive = None
            self._check(status, handle.op)
        return handle.result

    def thread_count(self) -> int:
        """Lifetime count of worker threads the native library created
        for this communicator: 0 until the first world>1 collective,
        exactly 2 from then on (persistent sender + collective worker).
        The no-thread-spawn-per-step regression test pins this."""
        return int(self._lib.pdrnn_thread_count(self._handle))

    def barrier(self):
        self._check(self._lib.pdrnn_barrier(self._handle), "barrier")

    def close(self):
        if self._handle:
            self._lib.pdrnn_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def init_from_env() -> Communicator:
    """Build a communicator from MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE -
    the same rendezvous contract the reference's RPC path uses."""
    return Communicator(
        master_addr=os.environ.get("MASTER_ADDR", "127.0.0.1"),
        master_port=int(os.environ.get("MASTER_PORT", "29500")),
        rank=int(os.environ.get("RANK", "0")),
        world_size=int(os.environ.get("WORLD_SIZE", "1")),
    )
