from pytorch_distributed_rnn_tpu.runtime.native import (
    Communicator,
    build_native_library,
    init_from_env,
)

__all__ = ["Communicator", "build_native_library", "init_from_env"]
