"""MPMD stage links: framed, replayable p2p transport for
process-per-stage pipelines (``parallel/mpmd.py``).

Each edge of the pipeline chain (stage k <-> k+1) is its OWN tiny
native-TCP world, not a slice of one big mesh: stage k hosts a
listener-only world (:meth:`Communicator.listener`, fixed per-link
port) and stage k+1 star-joins it as rank 1 - the star-accept/reserve
machinery the elastic PS world added (PR 7), reapplied per link.
Because no global world exists, a stage death breaks exactly its two
adjacent links; every other edge - and every surviving stage's
compiled programs - is untouched.  That is the whole MPMD bet
(PAPERS.md arxiv 2412.14374): restart means re-dial, never recompile.

Frames and exactly-once replay
------------------------------
Every tensor crossing a link is framed ``[seq, nbytes] + payload``
with ``seq = step * microbatches + mb`` - a dense, deterministic
sequence per direction.  Each end keeps:

- a SEND BUFFER of the last two steps' frames (a restarted stage
  resumes at most one step behind its neighbors - it cannot fall
  further back, because a neighbor needs the dead stage's traffic to
  finish its own step - so two steps bound the in-flight window);
- a RECV WATERMARK ``recv_next``: the next fresh sequence number.
  TCP is FIFO per link, so any frame below the watermark is a replay
  duplicate and is dropped (counted, never recomputed).

Sender-side replay + receiver-side dedupe = exactly-once delivery to
the compute loop.  On any transport error the end reconnects (host:
re-accept on the surviving listener; dialer: re-dial the fixed port)
under the deadline-budgeted ``resilience/retry.py`` contract - a loud
error past the budget, never a silent hang - then runs the WATERMARK
HANDSHAKE: both ends exchange ``recv_next`` and each replays every
buffered frame the peer has not seen.  A peer watermark older than
the buffer window is unrecoverable loss and raises
:class:`LinkBroken` loudly.

A restarted stage derives its watermarks from its own checkpoint
(``resume_step * microbatches``) instead of persisting transport
state: the checkpoint already IS the replay cursor.

Concurrency contract (PD3xx): a :class:`LinkEnd` is SINGLE-OWNER - it
is constructed, driven, and reconnected by exactly one stage thread,
so it holds no locks at all and never appears in the lock-order graph
(``lint/concurrency.py``).  Anyone adding a second thread here (an
async prefetcher, a heartbeat) must add a lock via
``utils/threadcheck.lock`` and declare its order against the
recorder's, not bolt on bare state.
"""

from __future__ import annotations

import logging

import numpy as np

from pytorch_distributed_rnn_tpu.resilience.retry import retry_transport
from pytorch_distributed_rnn_tpu.runtime.native import Communicator

log = logging.getLogger(__name__)


class LinkBroken(RuntimeError):
    """The link could not be (re)established within the retry budget,
    the peer's watermark fell outside the replay window, or the frame
    stream violated the protocol (shape or sequence mismatch)."""


class _TransientLinkError(RuntimeError):
    """A (re)connection attempt that is worth retrying: no join yet, a
    refused dial, or a socket that died between establish and
    handshake.  Distinct from :class:`LinkBroken` (also a RuntimeError)
    precisely so the retry loop can retry one and not the other."""


class LinkEnd:
    """One end of a stage<->stage pipeline link.

    ``HOST`` (the upstream stage) owns the link's listener world on a
    fixed port; ``DIAL`` (the downstream stage) star-joins it as rank
    1.  Both ends speak the same framed protocol; the asymmetry is
    only in how a broken socket is re-established.  Callers that
    resume from a checkpoint must set :attr:`recv_next` BEFORE
    :meth:`connect` so the handshake advertises the true watermark.
    """

    HOST = "host"
    DIAL = "dial"

    def __init__(self, mode: str, *, port: int, addr: str = "127.0.0.1",
                 window: int, name: str = "link", seed: int = 0,
                 reconnect_deadline_s: float = 120.0, on_event=None,
                 comm=None):
        if mode not in (self.HOST, self.DIAL):
            raise ValueError(f"mode must be 'host' or 'dial', got {mode!r}")
        self.mode = mode
        self.addr = addr
        self.port = int(port)
        self.window = int(window)
        self.name = name
        self.seed = int(seed)
        self.reconnect_deadline_s = float(reconnect_deadline_s)
        self.on_event = on_event
        self.peer = 1 if mode == self.HOST else 0
        self.recv_next = 0
        self.stats = {"reconnects": 0, "replayed": 0, "dup_drops": 0,
                      "recv_failures": 0}
        self._buf: dict[int, np.ndarray] = {}
        self._sent_next = 0  # highest seq handed to send() + 1
        # the host end binds its listener at construction time, before
        # any dial can land - a (re)started stage builds its downstream
        # LinkEnd FIRST so the neighbor's dial retries have a target
        if comm is not None:
            self._comm = comm
        elif mode == self.HOST:
            self._comm = Communicator.listener(self.port, capacity=2)
        else:
            self._comm = None

    # -- connection management -----------------------------------------------

    def _establish(self):
        """One (re)connection attempt; raises ``RuntimeError`` on a
        transient miss so ``retry_transport`` owns the backoff."""
        if self.mode == self.HOST:
            self._comm.close_peer(1)
            if self._comm.accept_peer(timeout_s=1.0) is None:
                raise RuntimeError(
                    f"{self.name}: no star join on port {self.port} yet"
                )
        else:
            if self._comm is not None:
                self._comm.close()
                self._comm = None
            # the constructor dials with its own bounded retry (~30 s)
            self._comm = Communicator(
                self.addr, self.port, rank=1, world_size=2, star=True
            )

    def connect(self, initial: bool = False) -> int:
        """(Re)establish the peer socket under the deadline-budgeted
        retry contract, then run the watermark handshake.  Returns the
        number of frames replayed to the peer.

        Establish + handshake retry as ONE unit: a dial can land on the
        half-dead socket of a just-killed peer and only fail at the
        handshake, so a handshake transport error is the same transient
        condition as a refused dial.  :class:`LinkBroken` (a protocol
        violation, not a transient) is never retried."""

        def attempt() -> int:
            try:
                self._establish()
                return self._handshake()
            except LinkBroken:
                raise
            except (RuntimeError, OSError) as exc:
                raise _TransientLinkError(str(exc)) from exc

        replayed = retry_transport(
            attempt,
            retries=512, base_delay=0.05, max_delay=0.5, seed=self.seed,
            retryable=(_TransientLinkError,),
            what=f"{self.name} {'connect' if initial else 'reconnect'}",
            deadline_s=self.reconnect_deadline_s,
        )
        if not initial:
            self.stats["reconnects"] += 1
        return replayed

    def _handshake(self) -> int:
        # The link wire contract (PD401 registry, lint/lifecycle.py):
        # a watermark HANDSHAKE exchange, then FRAME fire-and-forget
        # (loss is repaired by the next handshake's replay, not by a
        # per-frame ack).
        # protocol: link op HANDSHAKE
        # protocol: link op FRAME oneway
        # protocol: link request HANDSHAKE
        # protocol: link reply HANDSHAKE - the peer's watermark below
        # protocol: link handles HANDSHAKE
        mine = np.array([self.recv_next], dtype=np.int64)
        self._comm.send(self.peer, mine)
        peer_next = int(self._comm.recv(self.peer, (1,), np.int64)[0])
        replay = sorted(s for s in self._buf if s >= peer_next)
        # every frame in [peer_next, sent_next) must still be buffered;
        # anything already pruned is unrecoverable loss, so fail loudly
        expect = peer_next
        for s in replay:
            if s != expect:
                break
            expect = s + 1
        if expect < self._sent_next:
            raise LinkBroken(
                f"{self.name}: peer watermark {peer_next} is outside the "
                f"replay window (frame {expect} already pruned; "
                f"window={self.window})"
            )
        for s in replay:
            self._wire_send(s, self._buf[s])
        if replay:
            self.stats["replayed"] += len(replay)
            if self.on_event is not None:
                self.on_event(
                    "replay", link=self.name, count=len(replay),
                    from_seq=int(replay[0]), to_seq=int(replay[-1]),
                )
        return len(replay)

    # -- framed exchange -----------------------------------------------------

    def _wire_send(self, seq: int, array: np.ndarray):
        # protocol: link request FRAME
        header = np.array([seq, array.nbytes], dtype=np.int64)
        self._comm.send(self.peer, header)
        self._comm.send(self.peer, array)

    def send(self, seq: int, array: np.ndarray):
        """Buffer then wire-send frame ``seq``.  On a dead peer the end
        reconnects; the handshake's replay delivers this frame, so the
        send never silently vanishes."""
        array = np.ascontiguousarray(array)
        self._buf[seq] = array.copy()
        self._sent_next = max(self._sent_next, seq + 1)
        try:
            self._wire_send(seq, array)
        except RuntimeError:
            log.warning(
                f"{self.name}: send({seq}) hit a dead peer; reconnecting"
            )
            self.connect()

    def recv(self, shape, dtype=np.float32) -> tuple[int, np.ndarray]:
        """Next FRESH frame as ``(seq, array)``; replay duplicates are
        consumed and dropped, transport errors trigger a reconnect."""
        expected_nbytes = (
            int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        )
        while True:
            try:
                # protocol: link handles FRAME
                header = self._comm.recv(self.peer, (2,), np.int64)
                seq, nbytes = int(header[0]), int(header[1])
                if nbytes != expected_nbytes:
                    raise LinkBroken(
                        f"{self.name}: frame {seq} carries {nbytes} bytes, "
                        f"expected {expected_nbytes} - the stages disagree "
                        "on this link's tensor shape"
                    )
                payload = self._comm.recv(self.peer, shape, dtype)
            except LinkBroken:
                raise
            except RuntimeError:
                self.stats["recv_failures"] += 1
                log.warning(f"{self.name}: recv hit a dead peer; reconnecting")
                self.connect()
                continue
            if seq < self.recv_next:
                self.stats["dup_drops"] += 1
                continue
            if seq != self.recv_next:
                raise LinkBroken(
                    f"{self.name}: got frame {seq} while expecting "
                    f"{self.recv_next} (sequence gap - sender skipped "
                    "or replay window desynchronized)"
                )
            self.recv_next = seq + 1
            return seq, payload

    def prune(self, min_seq: int):
        """Drop buffered frames below ``min_seq`` (the stage calls this
        at step boundaries with ``(step - 1) * microbatches``, keeping
        exactly the two-step in-flight window alive)."""
        for s in [s for s in self._buf if s < min_seq]:
            del self._buf[s]

    def buffered(self) -> int:
        return len(self._buf)

    def close(self):
        if self._comm is not None:
            self._comm.close()
            self._comm = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
