// Native TCP collective/communicator library: the framework's host-side
// transport layer.
//
// Role (capability parity with the reference's native layer, SURVEY.md
// §2.8): the reference leans on source-built OpenMPI + torch c10d
// ProcessGroupMPI for broadcast/allreduce/send-recv between processes, and
// on torch RPC over TCP for its parameter server.  On-TPU collectives in
// this framework ride XLA (psum/ppermute over ICI); THIS library is the
// CPU/host-side analogue of Gloo/MPI - it lets every distributed test,
// multi-process launch, and the parameter-server strategy run on plain
// sockets with no accelerator or MPI install, and doubles as the wire
// transport for coordinator RPC.
//
// Design:
//  - rendezvous: rank 0 listens on (addr, port); every other rank dials in
//    and identifies itself; rank 0 then shares each rank's listen port so
//    all pairs connect full-mesh (send/recv between arbitrary ranks).
//  - ring allreduce (reduce-scatter + allgather over the rank ring), the
//    same algorithm family Horovod's engine uses; binomial-free broadcast
//    from an arbitrary root; allgather; barrier via tiny token exchange.
//  - fault injection built in (netem analogue, reference fabfile.py:130-191):
//    per-communicator delay (ms) before every send and a simulated
//    loss probability that imposes a retransmit-timeout penalty - TCP
//    never actually drops, so loss manifests as latency, matching how the
//    reference's tc-netem loss shows up as slowdown.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <arpa/inet.h>
#include <algorithm>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <random>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kMaxRetries = 300;      // rendezvous connect retries (x100ms)
constexpr double kRtoPenaltyMs = 200; // simulated retransmit timeout
// elastic (re)join handshake marker: a star joiner announces itself with
// this magic so the master's acceptor can reject stray connections
// (port scanners, half-open dials) instead of installing them as peers
constexpr int32_t kElasticMagic = 0x70647273;  // 'pdrs'
// pipeline segment for the ring legs: the incoming chunk is received in
// segments of this many bytes so accumulate of segment i overlaps the
// wire time of segment i+1 (adjacent-chunk overlap within a ring step)
constexpr size_t kPipelineBytes = 256 * 1024;

// One queued collective for the persistent comm worker.  Buffers are
// borrowed from the caller, which must keep them alive until the job is
// waited (the Python layer parks them on the handle object).
struct CollJob {
  int type = 0;  // 0 = allreduce, 1 = reduce_scatter, 2 = allgather
  void* data = nullptr;
  int64_t count = 0;
  int dtype = 0;
  int op = 0;
  void* out = nullptr;
  int64_t nbytes = 0;
  int status = -1;
  double seconds = 0.0;  // exclusive execution time on the worker
  bool done = false;
};

struct Comm {
  int rank = 0;
  int world = 1;
  std::vector<int> peer_fd;  // peer_fd[r] = socket to rank r (-1 for self)
  int listen_fd = -1;
  double delay_ms = 0.0;
  double loss_prob = 0.0;
  std::mt19937 rng{12345};
  std::string error;

  // persistent sender leg: replaces the former per-ring-step
  // std::thread spawn.  Driven only by the collective worker, so a
  // single pending-send slot suffices.
  std::thread send_thread;
  std::mutex send_mu;
  std::condition_variable send_cv;
  bool send_stop = false;
  bool send_pending = false;
  bool send_done = false;
  bool send_ok = false;
  int send_fd = -1;
  const void* send_buf = nullptr;
  size_t send_len = 0;

  // persistent collective worker: runs queued collectives FIFO so every
  // rank executes them in the same (program) order and async handles
  // stay matched across the ring.
  std::thread coll_thread;
  std::mutex coll_mu;
  std::condition_variable coll_cv;       // wakes the worker
  std::condition_variable coll_done_cv;  // wakes waiters
  bool coll_stop = false;
  int64_t next_handle = 1;
  std::deque<int64_t> coll_queue;
  std::unordered_map<int64_t, std::shared_ptr<CollJob>> coll_jobs;
  int threads_created = 0;  // lifetime total; stays <= 2 by construction
};

void set_sockopts(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

bool send_all(Comm* c, int fd, const void* buf, size_t n) {
  if (c->delay_ms > 0 || c->loss_prob > 0) {
    double penalty = c->delay_ms;
    if (c->loss_prob > 0) {
      std::uniform_real_distribution<double> u(0.0, 1.0);
      // a "lost" packet costs one RTO; repeated losses compound
      while (u(c->rng) < c->loss_prob) penalty += kRtoPenaltyMs;
    }
    if (penalty > 0)
      std::this_thread::sleep_for(
          std::chrono::microseconds(static_cast<int64_t>(penalty * 1000)));
  }
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t sent = ::send(fd, p, n, MSG_NOSIGNAL);
    if (sent <= 0) {
      if (sent < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += sent;
    n -= static_cast<size_t>(sent);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t got = ::recv(fd, p, n, 0);
    if (got <= 0) {
      if (got < 0 && errno == EINTR) continue;
      return false;
    }
    p += got;
    n -= static_cast<size_t>(got);
  }
  return true;
}

// -- persistent sender worker ------------------------------------------------
//
// The ring legs used to spawn a std::thread per step purely to run the
// send concurrently with the recv.  The loop below is that thread made
// persistent: post_send hands it one (fd, buf, len), wait_send blocks
// until the transfer finished.  Every post_send MUST be paired with a
// wait_send before the next post (the ring code always joins the leg
// even on recv failure, exactly like the old sender.join()).

void sender_loop(Comm* c) {
  std::unique_lock<std::mutex> lk(c->send_mu);
  for (;;) {
    c->send_cv.wait(lk, [c] { return c->send_stop || c->send_pending; });
    if (c->send_stop) return;
    const int fd = c->send_fd;
    const void* buf = c->send_buf;
    const size_t len = c->send_len;
    c->send_pending = false;
    lk.unlock();
    const bool ok = send_all(c, fd, buf, len);
    lk.lock();
    c->send_ok = ok;
    c->send_done = true;
    c->send_cv.notify_all();
  }
}

void post_send(Comm* c, int fd, const void* buf, size_t len) {
  std::lock_guard<std::mutex> lk(c->send_mu);
  c->send_fd = fd;
  c->send_buf = buf;
  c->send_len = len;
  c->send_pending = true;
  c->send_done = false;
  c->send_cv.notify_all();
}

bool wait_send(Comm* c) {
  std::unique_lock<std::mutex> lk(c->send_mu);
  c->send_cv.wait(lk, [c] { return c->send_done; });
  return c->send_ok;
}

int make_listener(uint16_t* port_inout) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(*port_inout);
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    return -1;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  *port_inout = ntohs(addr.sin_port);
  return fd;
}

bool resolve(const char* host, sockaddr_in* out) {
  // numeric fast path, then DNS (so hostnames like "localhost"/"node0" work)
  if (inet_pton(AF_INET, host, &out->sin_addr) == 1) return true;
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(host, nullptr, &hints, &res) != 0 || res == nullptr)
    return false;
  out->sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
  freeaddrinfo(res);
  return true;
}

int dial_addr(sockaddr_in addr) {
  for (int attempt = 0; attempt < kMaxRetries; ++attempt) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      set_sockopts(fd);
      return fd;
    }
    close(fd);
    usleep(100 * 1000);
  }
  return -1;
}

int dial(const char* host, uint16_t port) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (!resolve(host, &addr)) return -1;
  return dial_addr(addr);
}

int dial_ip(uint32_t addr_be, uint16_t port) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  addr.sin_addr.s_addr = addr_be;
  return dial_addr(addr);
}

// -- element types for the dtype-generic ring allreduce ----------------------

inline float bf16_to_f32(uint16_t v) {
  uint32_t u = static_cast<uint32_t>(v) << 16;
  float f;
  std::memcpy(&f, &u, 4);
  return f;
}

inline uint16_t f32_to_bf16(float f) {
  uint32_t u;
  std::memcpy(&u, &f, 4);
  u += 0x7FFFu + ((u >> 16) & 1u);  // round to nearest even
  return static_cast<uint16_t>(u >> 16);
}

template <typename T>
struct Elem {
  static void accumulate(T* dst, const T* src, int64_t n) {
    for (int64_t i = 0; i < n; ++i) dst[i] += src[i];
  }
  static void scale(T* dst, int64_t n, double s) {
    for (int64_t i = 0; i < n; ++i)
      dst[i] = static_cast<T>(dst[i] * s);
  }
};

// bf16 rides the wire at 2 bytes/element (half the gradient traffic of
// f32 - the point of --precision bf16 over a slow link); each hop's
// accumulate runs in f32 and rounds back, the same per-hop rounding a
// bf16 ring in Horovod/NCCL performs.
struct Bf16 {
  uint16_t bits;
};

template <>
struct Elem<Bf16> {
  static void accumulate(Bf16* dst, const Bf16* src, int64_t n) {
    for (int64_t i = 0; i < n; ++i)
      dst[i].bits =
          f32_to_bf16(bf16_to_f32(dst[i].bits) + bf16_to_f32(src[i].bits));
  }
  static void scale(Bf16* dst, int64_t n, double s) {
    for (int64_t i = 0; i < n; ++i)
      dst[i].bits = f32_to_bf16(
          static_cast<float>(bf16_to_f32(dst[i].bits) * s));
  }
};

}  // namespace

extern "C" {

void pdrnn_destroy(Comm* c);

// Rendezvous and build the full mesh.  Returns an opaque handle or null.
Comm* pdrnn_init(const char* master_addr, int master_port, int rank,
                 int world) {
  auto* c = new Comm();
  c->rank = rank;
  c->world = world;
  c->peer_fd.assign(world, -1);
  if (world == 1) return c;

  if (rank == 0) {
    uint16_t port = static_cast<uint16_t>(master_port);
    c->listen_fd = make_listener(&port);
    if (c->listen_fd < 0) {
      pdrnn_destroy(c);
      return nullptr;
    }
    // collect every worker's (rank, listen_port); the worker's address is
    // read off the accepted connection (getpeername), so the table works
    // across hosts - a worker need not know its own externally-visible
    // address (the reference's mpirun host file plays this role,
    // fabfile.py:218-223)
    std::vector<uint16_t> ports(world, 0);
    std::vector<uint32_t> addrs(world, 0);  // network byte order
    for (int i = 1; i < world; ++i) {
      sockaddr_in peer_sa{};
      socklen_t sa_len = sizeof(peer_sa);
      int fd = accept(c->listen_fd,
                      reinterpret_cast<sockaddr*>(&peer_sa), &sa_len);
      if (fd < 0) {
        pdrnn_destroy(c);
        return nullptr;
      }
      set_sockopts(fd);
      int32_t peer_rank;
      uint16_t peer_port;
      if (!recv_all(fd, &peer_rank, 4) || !recv_all(fd, &peer_port, 2)) {
        pdrnn_destroy(c);
        return nullptr;
      }
      c->peer_fd[peer_rank] = fd;
      ports[peer_rank] = peer_port;
      // a loopback peer address means the worker shares rank 0's host:
      // advertise sentinel 0, and dialers fall back to master_addr (which
      // reaches this host from anywhere) - otherwise a remote worker
      // would dial ITS OWN loopback
      uint32_t a = peer_sa.sin_addr.s_addr;
      addrs[peer_rank] =
          ((ntohl(a) >> 24) == 127) ? 0 : a;
    }
    // share the port + address tables with everyone
    for (int r = 1; r < world; ++r)
      if (!send_all(c, c->peer_fd[r], ports.data(), ports.size() * 2) ||
          !send_all(c, c->peer_fd[r], addrs.data(), addrs.size() * 4)) {
        pdrnn_destroy(c);
        return nullptr;
      }
  } else {
    // listen for higher ranks first so the port is in the table
    uint16_t my_port = 0;
    c->listen_fd = make_listener(&my_port);
    if (c->listen_fd < 0) {
      pdrnn_destroy(c);
      return nullptr;
    }
    int fd = dial(master_addr, static_cast<uint16_t>(master_port));
    if (fd < 0) {
      pdrnn_destroy(c);
      return nullptr;
    }
    int32_t r32 = rank;
    if (!send_all(c, fd, &r32, 4) || !send_all(c, fd, &my_port, 2)) {
      pdrnn_destroy(c);
      return nullptr;
    }
    c->peer_fd[0] = fd;
    std::vector<uint16_t> ports(world, 0);
    std::vector<uint32_t> addrs(world, 0);
    if (!recv_all(fd, ports.data(), ports.size() * 2) ||
        !recv_all(fd, addrs.data(), addrs.size() * 4)) {
      pdrnn_destroy(c);
      return nullptr;
    }
    // full mesh among workers: lower rank dials higher rank's listener at
    // the address rank 0 observed for it - spans hosts.  Sentinel 0 =
    // peer is on rank 0's host, reachable via master_addr.
    for (int r = 1; r < rank; ++r) {
      int pfd = addrs[r] == 0 ? dial(master_addr, ports[r])
                              : dial_ip(addrs[r], ports[r]);
      if (pfd < 0) {
        pdrnn_destroy(c);
        return nullptr;
      }
      int32_t me = rank;
      if (!send_all(c, pfd, &me, 4)) {
        pdrnn_destroy(c);
        return nullptr;
      }
      c->peer_fd[r] = pfd;
    }
    for (int r = rank + 1; r < world; ++r) {
      int pfd = accept(c->listen_fd, nullptr, nullptr);
      if (pfd < 0) {
        pdrnn_destroy(c);
        return nullptr;
      }
      set_sockopts(pfd);
      int32_t peer_rank;
      if (!recv_all(pfd, &peer_rank, 4)) {
        pdrnn_destroy(c);
        return nullptr;
      }
      c->peer_fd[peer_rank] = pfd;
    }
  }
  return c;
}

int pdrnn_rank(Comm* c) { return c->rank; }
int pdrnn_world(Comm* c) { return c->world; }

void pdrnn_set_fault(Comm* c, double delay_ms, double loss_prob) {
  c->delay_ms = delay_ms;
  c->loss_prob = loss_prob;
}

int pdrnn_send(Comm* c, int dst, const void* data, int64_t nbytes) {
  if (dst == c->rank || dst < 0 || dst >= c->world) return -1;
  return send_all(c, c->peer_fd[dst], data, static_cast<size_t>(nbytes)) ? 0
                                                                         : -1;
}

int pdrnn_recv(Comm* c, int src, void* data, int64_t nbytes) {
  if (src == c->rank || src < 0 || src >= c->world) return -1;
  return recv_all(c->peer_fd[src], data, static_cast<size_t>(nbytes)) ? 0 : -1;
}

int pdrnn_broadcast(Comm* c, int root, void* data, int64_t nbytes) {
  if (c->world == 1) return 0;
  if (c->rank == root) {
    for (int r = 0; r < c->world; ++r)
      if (r != root && pdrnn_send(c, r, data, nbytes) != 0) return -1;
    return 0;
  }
  return pdrnn_recv(c, root, data, nbytes);
}

// -- elastic membership (parameter-server star topology) ---------------------
//
// The initial rendezvous builds a fixed-world full mesh; the functions
// below let the PS world change membership afterwards.  They are
// star-only by design: PS traffic is strictly master<->worker, so a
// (re)joining worker dials rank 0 and nothing else - no table
// re-exchange, no mesh rebuild, no recompile of anything.

// Grow the peer table to `capacity` slots.  Must be called BEFORE any
// concurrent use of the communicator (the resize reallocates the
// vector): the master reserves its elastic headroom right after init,
// before the acceptor thread starts, so accepts never reallocate under
// in-flight send/recv.
int pdrnn_reserve(Comm* c, int capacity) {
  if (capacity <= static_cast<int>(c->peer_fd.size())) return 0;
  c->peer_fd.resize(capacity, -1);
  return 0;
}

// Master side: accept one elastic (re)join on the rendezvous listener.
// Waits up to timeout_ms; returns the joining rank, -1 on timeout, -2
// on a handshake/validity error (the stray connection is closed).  A
// rank whose slot is occupied (a respawn racing its predecessor's
// death) has the old socket shut down and replaced - the old service
// thread's blocked recv wakes with an error and takes the death path.
int pdrnn_accept_peer(Comm* c, int timeout_ms) {
  if (c->listen_fd < 0) return -2;
  pollfd pfd{c->listen_fd, POLLIN, 0};
  int ready = poll(&pfd, 1, timeout_ms);
  if (ready == 0) return -1;
  if (ready < 0) return errno == EINTR ? -1 : -2;
  int fd = accept(c->listen_fd, nullptr, nullptr);
  if (fd < 0) return -2;
  set_sockopts(fd);
  // bound the handshake read: a connection that never identifies
  // itself must not wedge the acceptor thread
  timeval tv{2, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  int32_t magic = 0, peer_rank = -1;
  if (!recv_all(fd, &magic, 4) || magic != kElasticMagic ||
      !recv_all(fd, &peer_rank, 4) || peer_rank < 1 ||
      peer_rank >= static_cast<int>(c->peer_fd.size())) {
    close(fd);
    return -2;
  }
  timeval off{0, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &off, sizeof(off));
  if (c->peer_fd[peer_rank] >= 0) {
    shutdown(c->peer_fd[peer_rank], SHUT_RDWR);
    close(c->peer_fd[peer_rank]);
  }
  c->peer_fd[peer_rank] = fd;
  if (peer_rank >= c->world) c->world = peer_rank + 1;
  return peer_rank;
}

// Close one peer's socket (drain/death cleanup).  A later elastic
// accept of the same rank installs a fresh socket in the slot.
int pdrnn_close_peer(Comm* c, int rank) {
  if (rank < 0 || rank >= static_cast<int>(c->peer_fd.size())) return -1;
  if (c->peer_fd[rank] >= 0) {
    shutdown(c->peer_fd[rank], SHUT_RDWR);
    close(c->peer_fd[rank]);
    c->peer_fd[rank] = -1;
  }
  return 0;
}

// Worker side: star-join a running world as `rank` - dial the master
// only and identify via the elastic handshake.  No listener, no mesh,
// no port-table exchange; only peer 0 is reachable afterwards.
Comm* pdrnn_init_star(const char* master_addr, int master_port, int rank,
                      int world) {
  if (rank < 1) return nullptr;
  auto* c = new Comm();
  c->rank = rank;
  c->world = world > rank ? world : rank + 1;
  c->peer_fd.assign(c->world, -1);
  int fd = dial(master_addr, static_cast<uint16_t>(master_port));
  if (fd < 0) {
    pdrnn_destroy(c);
    return nullptr;
  }
  int32_t magic = kElasticMagic, r32 = rank;
  if (!send_all(c, fd, &magic, 4) || !send_all(c, fd, &r32, 4)) {
    pdrnn_destroy(c);
    return nullptr;
  }
  c->peer_fd[0] = fd;
  return c;
}

// Listener-only world: rank 0 with the rendezvous listener bound to a
// KNOWN port and an empty peer table of `capacity` slots - every peer
// arrives later through `pdrnn_accept_peer` star joins.  This is the
// host end of an MPMD pipeline link: stage k listens here, stage k+1
// star-joins as rank 1, and a respawned downstream re-dials the same
// port.  Neither existing entry point can serve this role:
// `pdrnn_init(world=1)` returns without a listener, and the full-mesh
// accept loop would misread the star handshake's magic word as a peer
// rank.  The fixed port is the point - respawned dialers must find the
// listener again without a rendezvous exchange.
Comm* pdrnn_init_listener(int port, int capacity) {
  if (port <= 0 || port > 65535 || capacity < 2) return nullptr;
  auto* c = new Comm();
  c->rank = 0;
  c->world = 1;
  c->peer_fd.assign(capacity, -1);
  uint16_t p = static_cast<uint16_t>(port);
  c->listen_fd = make_listener(&p);
  if (c->listen_fd < 0) {
    delete c;
    return nullptr;
  }
  return c;
}

}  // extern "C"

namespace {

// Receive an incoming ring chunk in pipeline segments, accumulating
// each segment while later segments are still on the wire.  Element
// order within the chunk is unchanged (ascending, same adds as a
// recv-then-accumulate), so the reduction stays bitwise identical.
template <typename T>
bool recv_accumulate(Comm* c, int fd, T* dst, int64_t n, T* inbox) {
  (void)c;
  const int64_t seg =
      std::max<int64_t>(1, static_cast<int64_t>(kPipelineBytes / sizeof(T)));
  for (int64_t off = 0; off < n; off += seg) {
    const int64_t m = std::min(seg, n - off);
    if (!recv_all(fd, inbox + off, static_cast<size_t>(m) * sizeof(T)))
      return false;
    Elem<T>::accumulate(dst + off, inbox + off, m);
  }
  return true;
}

// Ring allreduce (reduce-scatter then allgather), generic over the wire
// element type.  op: 0 = sum, 1 = mean.  Runs on the persistent
// collective worker; the send leg rides the persistent sender thread
// (post_send/wait_send) instead of a per-step std::thread.
template <typename T>
int ring_allreduce(Comm* c, T* data, int64_t count, int op) {
  const int world = c->world;
  if (world == 1) return 0;
  const int next = (c->rank + 1) % world;
  const int prev = (c->rank - 1 + world) % world;

  // chunk boundaries (world chunks, last chunks may be smaller)
  std::vector<int64_t> begin(world + 1);
  const int64_t base = count / world, rem = count % world;
  begin[0] = 0;
  for (int i = 0; i < world; ++i)
    begin[i + 1] = begin[i] + base + (i < rem ? 1 : 0);
  auto chunk_len = [&](int i) { return begin[i + 1] - begin[i]; };

  std::vector<T> inbox(static_cast<size_t>(base + 1));

  // reduce-scatter: after step s, rank r owns the fully-reduced chunk
  // (r+1) mod world ... progressing so rank r ends owning chunk (r+1).
  for (int step = 0; step < world - 1; ++step) {
    const int send_idx = (c->rank - step + world) % world;
    const int recv_idx = (c->rank - step - 1 + world) % world;
    post_send(c, c->peer_fd[next], data + begin[send_idx],
              chunk_len(send_idx) * sizeof(T));
    const bool ok_recv = recv_accumulate(c, c->peer_fd[prev],
                                         data + begin[recv_idx],
                                         chunk_len(recv_idx), inbox.data());
    const bool ok_send = wait_send(c);
    if (!ok_send || !ok_recv) return -1;
  }

  // allgather: circulate the reduced chunks
  for (int step = 0; step < world - 1; ++step) {
    const int send_idx = (c->rank + 1 - step + world) % world;
    const int recv_idx = (c->rank - step + world) % world;
    post_send(c, c->peer_fd[next], data + begin[send_idx],
              chunk_len(send_idx) * sizeof(T));
    const bool ok_recv = recv_all(c->peer_fd[prev], data + begin[recv_idx],
                                  chunk_len(recv_idx) * sizeof(T));
    const bool ok_send = wait_send(c);
    if (!ok_send || !ok_recv) return -1;
  }

  if (op == 1) Elem<T>::scale(data, count, 1.0 / world);
  return 0;
}

// Ring reduce-scatter: rank r returns chunk r of the elementwise
// reduction in `out`; `data` is scratch (clobbered in place).  Equal
// chunks only (count % world == 0; the Python layer pads) - the sharded
// weight update owes every rank an equal optimizer shard anyway.
//
// The reduce phase is BIT-IDENTICAL to ring_allreduce's: same indices,
// same per-chunk accumulation order, so a sharded update's reduced
// gradient shard equals the corresponding slice of a full allreduce
// exactly (the bitwise-parity bar of the sharded-update tests).  That
// phase leaves rank r holding chunk (r+1) mod world; one extra ring
// hop hands each chunk to its owner.
template <typename T>
int ring_reduce_scatter(Comm* c, T* data, int64_t count, int op, T* out) {
  const int world = c->world;
  if (count % world != 0) return -1;
  const int64_t shard = count / world;
  if (world == 1) {
    std::memcpy(out, data, static_cast<size_t>(shard) * sizeof(T));
    return 0;
  }
  const int next = (c->rank + 1) % world;
  const int prev = (c->rank - 1 + world) % world;

  std::vector<T> inbox(static_cast<size_t>(shard));
  for (int step = 0; step < world - 1; ++step) {
    const int send_idx = (c->rank - step + world) % world;
    const int recv_idx = (c->rank - step - 1 + world) % world;
    post_send(c, c->peer_fd[next], data + send_idx * shard,
              static_cast<size_t>(shard) * sizeof(T));
    const bool ok_recv = recv_accumulate(c, c->peer_fd[prev],
                                         data + recv_idx * shard, shard,
                                         inbox.data());
    const bool ok_send = wait_send(c);
    if (!ok_send || !ok_recv) return -1;
  }

  // rotation hop: rank r holds reduced chunk (r+1) mod world; sending it
  // to `next` delivers chunk r to every rank directly into `out`
  const int held = (c->rank + 1) % world;
  post_send(c, c->peer_fd[next], data + held * shard,
            static_cast<size_t>(shard) * sizeof(T));
  const bool ok_recv = recv_all(c->peer_fd[prev], out,
                                static_cast<size_t>(shard) * sizeof(T));
  const bool ok_send = wait_send(c);
  if (!ok_send || !ok_recv) return -1;
  if (op == 1) Elem<T>::scale(out, shard, 1.0 / world);
  return 0;
}

// Allgather ring body (formerly pdrnn_allgather): output must hold
// world * nbytes; rank r's contribution lands at slot r.
int allgather_core(Comm* c, const void* input, int64_t nbytes, void* output) {
  char* out = static_cast<char*>(output);
  std::memcpy(out + c->rank * nbytes, input, static_cast<size_t>(nbytes));
  if (c->world == 1) return 0;
  const int next = (c->rank + 1) % c->world;
  const int prev = (c->rank - 1 + c->world) % c->world;
  for (int step = 0; step < c->world - 1; ++step) {
    const int send_idx = (c->rank - step + c->world) % c->world;
    const int recv_idx = (c->rank - step - 1 + c->world) % c->world;
    post_send(c, c->peer_fd[next], out + send_idx * nbytes,
              static_cast<size_t>(nbytes));
    const bool ok_recv = recv_all(c->peer_fd[prev], out + recv_idx * nbytes,
                                  static_cast<size_t>(nbytes));
    const bool ok_send = wait_send(c);
    if (!ok_send || !ok_recv) return -1;
  }
  return 0;
}

// -- persistent collective worker --------------------------------------------
//
// Collectives (sync AND async) are queued FIFO onto one worker thread
// per communicator.  Every rank enqueues in identical program order, so
// collective k on rank A always meets collective k on rank B even when
// several async handles are outstanding.  wait() unblocks as soon as
// its own job finishes while later jobs keep streaming - that gap is
// the overlap the bucketed trainer exploits.

int run_job(Comm* c, CollJob& j) {
  switch (j.type) {
    case 0:  // allreduce
      switch (j.dtype) {
        case 0:
          return ring_allreduce(c, static_cast<float*>(j.data), j.count, j.op);
        case 1:
          return ring_allreduce(c, static_cast<double*>(j.data), j.count,
                                j.op);
        case 2:
          return ring_allreduce(c, static_cast<Bf16*>(j.data), j.count, j.op);
      }
      return -1;
    case 1:  // reduce_scatter
      switch (j.dtype) {
        case 0:
          return ring_reduce_scatter(c, static_cast<float*>(j.data), j.count,
                                     j.op, static_cast<float*>(j.out));
        case 1:
          return ring_reduce_scatter(c, static_cast<double*>(j.data), j.count,
                                     j.op, static_cast<double*>(j.out));
        case 2:
          return ring_reduce_scatter(c, static_cast<Bf16*>(j.data), j.count,
                                     j.op, static_cast<Bf16*>(j.out));
      }
      return -1;
    case 2:  // allgather
      return allgather_core(c, j.data, j.nbytes, j.out);
  }
  return -1;
}

void coll_loop(Comm* c) {
  std::unique_lock<std::mutex> lk(c->coll_mu);
  for (;;) {
    c->coll_cv.wait(lk, [c] { return c->coll_stop || !c->coll_queue.empty(); });
    if (c->coll_stop) {
      // fail whatever is still queued so waiters unblock
      for (int64_t id : c->coll_queue) {
        auto it = c->coll_jobs.find(id);
        if (it != c->coll_jobs.end()) {
          it->second->status = -1;
          it->second->done = true;
        }
      }
      c->coll_queue.clear();
      c->coll_done_cv.notify_all();
      return;
    }
    const int64_t id = c->coll_queue.front();
    c->coll_queue.pop_front();
    auto job = c->coll_jobs[id];
    lk.unlock();
    const auto t0 = std::chrono::steady_clock::now();
    const int status = run_job(c, *job);
    const double secs =
        std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
            .count();
    lk.lock();
    job->status = status;
    job->seconds = secs;
    job->done = true;
    c->coll_done_cv.notify_all();
  }
}

void ensure_workers(Comm* c) {
  std::lock_guard<std::mutex> lk(c->coll_mu);
  if (!c->coll_thread.joinable()) {
    c->threads_created += 2;
    c->send_thread = std::thread(sender_loop, c);
    c->coll_thread = std::thread(coll_loop, c);
  }
}

int64_t enqueue_job(Comm* c, std::shared_ptr<CollJob> job) {
  if (c->world == 1) {
    // single-rank collectives are memcpy-only: run inline and park the
    // completed job for wait() - no worker threads needed, ever
    const auto t0 = std::chrono::steady_clock::now();
    job->status = run_job(c, *job);
    job->seconds =
        std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
            .count();
    job->done = true;
    std::lock_guard<std::mutex> lk(c->coll_mu);
    const int64_t id = c->next_handle++;
    c->coll_jobs.emplace(id, std::move(job));
    return id;
  }
  ensure_workers(c);
  std::lock_guard<std::mutex> lk(c->coll_mu);
  const int64_t id = c->next_handle++;
  c->coll_jobs.emplace(id, std::move(job));
  c->coll_queue.push_back(id);
  c->coll_cv.notify_all();
  return id;
}

int wait_job(Comm* c, int64_t id, double* seconds_out) {
  std::unique_lock<std::mutex> lk(c->coll_mu);
  auto it = c->coll_jobs.find(id);
  if (it == c->coll_jobs.end()) return -1;
  auto job = it->second;
  c->coll_done_cv.wait(lk, [&] { return job->done; });
  if (seconds_out) *seconds_out = job->seconds;
  const int status = job->status;
  c->coll_jobs.erase(id);
  return status;
}

}  // namespace

extern "C" {

// Nonblocking collectives: enqueue onto the persistent comm worker and
// return a handle immediately.  pdrnn_wait blocks until that handle's
// job completed, writes its exclusive worker-execution time (seconds)
// into `seconds_out` when non-null, and returns the job status.  The
// caller owns the buffers until the wait returns.

int64_t pdrnn_allreduce_async(Comm* c, void* data, int64_t count, int dtype,
                              int op) {
  auto job = std::make_shared<CollJob>();
  job->type = 0;
  job->data = data;
  job->count = count;
  job->dtype = dtype;
  job->op = op;
  return enqueue_job(c, std::move(job));
}

int64_t pdrnn_reduce_scatter_async(Comm* c, void* data, int64_t count,
                                   int dtype, int op, void* output) {
  auto job = std::make_shared<CollJob>();
  job->type = 1;
  job->data = data;
  job->count = count;
  job->dtype = dtype;
  job->op = op;
  job->out = output;
  return enqueue_job(c, std::move(job));
}

int64_t pdrnn_allgather_async(Comm* c, const void* input, int64_t nbytes,
                              void* output) {
  auto job = std::make_shared<CollJob>();
  job->type = 2;
  job->data = const_cast<void*>(input);
  job->nbytes = nbytes;
  job->out = output;
  return enqueue_job(c, std::move(job));
}

int pdrnn_wait(Comm* c, int64_t handle, double* seconds_out) {
  return wait_job(c, handle, seconds_out);
}

// Lifetime count of worker threads this communicator ever created:
// 0 before the first world>1 collective, then exactly 2 (sender +
// collective worker) forever - the no-thread-spawn-per-step regression
// pin reads this.
int pdrnn_thread_count(Comm* c) {
  std::lock_guard<std::mutex> lk(c->coll_mu);
  return c->threads_created;
}

// dtype: 0 = f32, 1 = f64, 2 = bf16 (raw uint16 bits).  Synchronous
// collectives are enqueue+wait on the same worker queue, so they stay
// ordered with any outstanding async handles.
int pdrnn_allreduce(Comm* c, void* data, int64_t count, int dtype, int op) {
  return wait_job(c, pdrnn_allreduce_async(c, data, count, dtype, op),
                  nullptr);
}

// kept for ABI stability with existing callers
int pdrnn_allreduce_f32(Comm* c, float* data, int64_t count, int op) {
  return pdrnn_allreduce(c, data, count, 0, op);
}

// Reduce-scatter: `output` receives rank's count/world-element chunk of
// the reduction; `data` is scratch (clobbered).  count % world must be 0.
// dtype/op codes as pdrnn_allreduce.
int pdrnn_reduce_scatter(Comm* c, void* data, int64_t count, int dtype,
                         int op, void* output) {
  return wait_job(
      c, pdrnn_reduce_scatter_async(c, data, count, dtype, op, output),
      nullptr);
}

int pdrnn_allgather(Comm* c, const void* input, int64_t nbytes, void* output) {
  return wait_job(c, pdrnn_allgather_async(c, input, nbytes, output), nullptr);
}

int pdrnn_barrier(Comm* c) {
  uint8_t token = 0;
  std::vector<uint8_t> all(static_cast<size_t>(c->world));
  return pdrnn_allgather(c, &token, 1, all.data());
}

void pdrnn_destroy(Comm* c) {
  if (!c) return;
  {
    std::lock_guard<std::mutex> lk(c->coll_mu);
    c->coll_stop = true;
    c->coll_cv.notify_all();
  }
  if (c->coll_thread.joinable()) c->coll_thread.join();
  {
    std::lock_guard<std::mutex> lk(c->send_mu);
    c->send_stop = true;
    c->send_cv.notify_all();
  }
  if (c->send_thread.joinable()) c->send_thread.join();
  for (int fd : c->peer_fd)
    if (fd >= 0) close(fd);
  if (c->listen_fd >= 0) close(c->listen_fd);
  delete c;
}

}  // extern "C"
