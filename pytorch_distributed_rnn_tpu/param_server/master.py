"""Parameter-server master: owns parameters and optimizer state.

Capability parity with the reference master
(``/root/reference/src/motion/param_server/master.py:15-59``): a single
process holds the authoritative model parameters and the optimizer; workers
never talk to each other (call-stack §3.3 asymmetry preserved).  The
reference reached this shape with RPC-remote forward + distributed autograd
+ a remote ``DistributedOptimizer``; here the contract is explicit
state transfer - workers push local gradients, the master applies the
update and returns fresh params ("grad-push" PS, the standard design the
reference's remote-forward machinery approximates).

Concurrency: one service thread per worker (each worker owns a dedicated
socket); optimizer updates run under a lock, so gradient application is
serialized but arrival order is free - the same effectively-asynchronous
semantics as the reference's per-worker RPC contexts.  ``sync_mode=True``
instead gathers one gradient from every worker, averages, and applies a
single update (DDP-equivalent math, useful for equivalence tests).

The reference's in-run assertion that gradients actually arrived
(``worker.py:55-58``) maps to the finite-gradient check before every
update.
"""

from __future__ import annotations

import logging
import math
import threading
import time

import numpy as np

from pytorch_distributed_rnn_tpu.param_server import protocol

log = logging.getLogger(__name__)


class ParameterServerMaster:
    def __init__(self, comm, flat_params: np.ndarray, apply_update,
                 sync_mode=False, sync_timeout: float = 300.0,
                 quorum: float = 1.0, recorder=None):
        """``apply_update(flat_grads) -> flat_params`` advances the owned
        state by one optimizer step and returns the new flat params.
        ``sync_timeout`` bounds how long a sync-mode round waits for
        stragglers (the reference's RPC timeout analogue,
        ``/root/reference/src/motion/param_server/master.py:56``).

        ``quorum`` is the fraction of workers whose gradients suffice to
        close a sync round once ``sync_timeout`` expires: at the default
        1.0 a straggler past the timeout is fatal (strict DDP-equivalent
        rounds), while e.g. 0.5 lets the round DEGRADE - average what
        arrived, apply, release the waiters - so a preempted worker slows
        the world instead of killing it (the Podracer/pjit preemptible-
        worker baseline).  A straggler's late gradient joins the next
        round as an ordinary (stale) contribution."""
        if not 0.0 < quorum <= 1.0:
            raise ValueError(f"quorum must be in (0, 1], got {quorum}")
        # structured telemetry (obs/recorder.py): degraded rounds, dead
        # workers and the serve() summary become events the CLI can
        # summarize - quorum degradations were previously log-only
        from pytorch_distributed_rnn_tpu.obs.recorder import NULL_RECORDER

        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self.comm = comm
        self.params = flat_params.astype(np.float32)
        self.apply_update = apply_update
        self.sync_mode = sync_mode
        self.sync_timeout = float(sync_timeout)
        self.quorum = float(quorum)
        self.lock = threading.Lock()
        self.num_params = int(flat_params.size)
        self.updates_applied = 0
        self.degraded_rounds = 0
        # sync-mode rendezvous state
        self._pending: dict[int, np.ndarray] = {}
        self._sync_cv = threading.Condition(self.lock)
        self._waiting: set[int] = set()
        # trace timeline: a sync round SPANS from its first gathered
        # gradient to the update that closes it (obs/timeline.py renders
        # one ps_round span per round; its close edge is also a clock-
        # alignment sync point against the workers' push-reply edges).
        # _round_seqs records WHICH push seq each worker contributed, so
        # the aligner can pair edges by id even when a degraded round or
        # a retried push shifts the ordinals.
        self._round_tm0: float | None = None
        self._round_seqs: dict[int, int] = {}
        # workers whose transport died (quorum mode tolerates them):
        # excluded from later rounds so the world shrinks instead of
        # timing out on a corpse every round
        self._dead: set[int] = set()

    def serve(self):
        """Block until every worker sends DONE.  A failure in a worker's
        service thread (socket error, integrity assertion) is re-raised
        here so the master process reports failure instead of silently
        finishing on a reduced worker set - EXCEPT in quorum-degraded
        sync mode (``quorum < 1``), where a dying worker is marked dead,
        dropped from later rounds, and only a quorum-breaking loss of
        workers is fatal (the preemptible-worker contract)."""
        num_workers = self.comm.world_size - 1
        errors: dict[int, BaseException] = {}
        tolerated: dict[int, BaseException] = {}

        def guarded(worker):
            try:
                self._serve_worker(worker)
            except BaseException as exc:  # noqa: BLE001 - propagated below
                if self.sync_mode and self.quorum < 1.0:
                    tolerated[worker] = exc
                    self._mark_dead(worker, exc)
                else:
                    errors[worker] = exc

        threads = [
            threading.Thread(target=guarded, args=(w,))
            for w in range(1, self.comm.world_size)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            worker, exc = next(iter(errors.items()))
            raise RuntimeError(
                f"parameter-server worker thread(s) failed: "
                f"{sorted(errors)} (first: worker {worker})"
            ) from exc
        survivors = num_workers - len(tolerated)
        if tolerated and survivors < self._quorum_count(num_workers):
            worker, exc = next(iter(tolerated.items()))
            raise RuntimeError(
                f"parameter server lost quorum: {sorted(tolerated)} "
                f"worker(s) died, {survivors} survivor(s) < quorum "
                f"{self._quorum_count(num_workers)}"
            ) from exc
        log.info(
            f"parameter server done: {self.updates_applied} updates applied"
            + (f", {self.degraded_rounds} degraded round(s), "
               f"{len(tolerated)} worker(s) lost" if tolerated
               or self.degraded_rounds else "")
        )
        self.recorder.record(
            "ps_summary", updates=self.updates_applied,
            degraded_rounds=self.degraded_rounds,
            workers_lost=len(tolerated),
        )
        self.recorder.flush()
        return self.params

    def _mark_dead(self, worker: int, exc: BaseException):
        """Quorum mode: drop a dead worker from the rendezvous so later
        rounds close over the survivors instead of timing out on a
        corpse; if the in-flight round now has every live worker's
        gradient, close it here."""
        log.warning(
            f"worker {worker} dropped from the sync rendezvous "
            f"({type(exc).__name__}: {exc}); degrading to survivors"
        )
        self.recorder.record(
            "ps_worker_dead", worker=worker,
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        with self._sync_cv:
            self._dead.add(worker)
            self._pending.pop(worker, None)
            self._round_seqs.pop(worker, None)
            self._waiting.discard(worker)
            live = self.comm.world_size - 1 - len(self._dead)
            if self._pending and len(self._pending) >= max(1, live):
                self._close_round()

    def _serve_worker(self, worker: int):
        last_push_seq = None
        while True:
            opcode, grads, seq = protocol.recv_request(
                self.comm, worker, self.num_params
            )
            if opcode == protocol.OP_DONE:
                return
            if opcode == protocol.OP_PULL:
                with self.lock:
                    protocol.send_params(self.comm, worker, self.params)
                continue
            # OP_PUSH
            if seq == last_push_seq:
                # a retried push whose ORIGINAL made it through but whose
                # reply leg failed (resilience/retry.py retries the whole
                # exchange): the gradient is already in an update - do
                # not average it in twice, just resend current params
                log.warning(
                    f"worker {worker} re-sent push seq {seq}; replying "
                    "with current params without re-applying"
                )
                with self.lock:
                    protocol.send_params(self.comm, worker, self.params)
                continue
            last_push_seq = seq
            assert grads is not None and grads.size == self.num_params, (
                f"worker {worker} pushed a malformed gradient"
            )
            assert np.isfinite(grads).all(), (
                f"worker {worker} pushed non-finite gradients "
                "(the reference asserts gradient presence per batch; "
                "we assert integrity)"
            )
            if self.sync_mode:
                self._push_sync(worker, grads, seq=seq)
            else:
                with self.lock:
                    # span measured INSIDE the lock: the lock serializes
                    # updates, so per-thread spans on the shared ps
                    # timeline row stay disjoint (lock WAIT would overlap)
                    t0 = time.perf_counter()
                    self.params = self.apply_update(grads)
                    self.updates_applied += 1
                    protocol.send_params(self.comm, worker, self.params)
                    applied = self.updates_applied
                    if self.recorder.enabled:
                        self.recorder.emit_span(
                            "ps_round", t0, time.perf_counter() - t0,
                            cat="ps", round=applied, worker=worker,
                            seq=seq, mode="async",
                        )

    def _close_round(self, degraded: bool = False):
        """Average the gathered gradients, apply ONE update, reply to
        every worker owed fresh params, wake the waiters.  Caller holds
        the lock."""
        gathered = len(self._pending)
        expected = self.comm.world_size - 1 - len(self._dead)
        tm0 = self._round_tm0
        self._round_tm0 = None
        seqs = {str(w): s for w, s in self._round_seqs.items()
                if s is not None}
        self._round_seqs = {}
        mean_grad = np.mean(list(self._pending.values()), axis=0)
        self.params = self.apply_update(mean_grad)
        self.updates_applied += 1
        if self.recorder.enabled:
            now = time.perf_counter()
            if tm0 is None:
                tm0 = now
            self.recorder.emit_span(
                "ps_round", tm0, now - tm0, cat="ps",
                round=self.updates_applied, gathered=gathered,
                expected=expected, degraded=degraded, mode="sync",
                # which push seq each worker contributed: the id the
                # clock aligner pairs against worker push-reply edges
                # (ordinal pairing breaks under degradation/retries)
                seqs=seqs,
            )
        for w in sorted(self._pending):
            try:
                protocol.send_params(self.comm, w, self.params)
            except Exception as exc:
                if self.quorum >= 1.0:
                    raise
                # a worker that died between push and reply: its service
                # thread will also fail and _mark_dead it; do not let the
                # broken reply socket kill the worker thread CLOSING the
                # round on everyone else's behalf
                log.warning(
                    f"reply to worker {w} failed ({exc}); leaving it to "
                    "the rendezvous death path"
                )
        self._pending.clear()
        self._waiting.clear()
        self._sync_cv.notify_all()

    def _quorum_count(self, num_workers: int) -> int:
        return max(1, math.ceil(self.quorum * num_workers))

    def _push_sync(self, worker: int, grads: np.ndarray,
                   seq: int | None = None):
        """Gather one gradient per worker, average, apply once, release.

        On straggler timeout the round degrades to the configured quorum
        (``quorum < 1`` and enough gradients arrived) or fails loudly
        (strict mode, or not even a quorum delivered)."""
        with self._sync_cv:
            num_workers = self.comm.world_size - 1 - len(self._dead)
            if not self._pending:
                self._round_tm0 = time.perf_counter()  # round opens here
            self._pending[worker] = grads
            self._round_seqs[worker] = seq
            if len(self._pending) >= num_workers:
                self._close_round()
                return
            self._waiting.add(worker)
            generation = self.updates_applied
            completed = self._sync_cv.wait_for(
                lambda: self.updates_applied > generation,
                timeout=self.sync_timeout,
            )
            if completed:
                return
            # wait_for re-checks under the lock, so exactly one waiter
            # observes the still-open round and owns the timeout decision;
            # later waiters see updates_applied advanced and return above
            missing = num_workers - len(self._pending)
            if self.quorum < 1.0 and len(self._pending) >= self._quorum_count(
                num_workers
            ):
                self.degraded_rounds += 1
                log.warning(
                    f"sync round degraded to quorum: {len(self._pending)}/"
                    f"{num_workers} gradient(s) after {self.sync_timeout}s "
                    f"({missing} straggler(s)); applying partial average "
                    f"(degraded rounds so far: {self.degraded_rounds})"
                )
                # the degradation rides the round's span event (emitted
                # by _close_round with degraded=True), so the timeline
                # and the summary read one record, not two
                self._close_round(degraded=True)
                return
            # a straggler never delivered and no quorum covers it: fail
            # loudly instead of silently proceeding with stale parameters
            raise RuntimeError(
                f"sync-mode round timed out after {self.sync_timeout}s "
                f"waiting on {missing} missing gradient(s) (worker "
                f"{worker} was waiting; quorum "
                f"{self._quorum_count(num_workers)}/{num_workers} "
                f"{'not met' if self.quorum < 1.0 else 'disabled'})"
            )
