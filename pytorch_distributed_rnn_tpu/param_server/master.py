"""Parameter-server master: owns parameters and optimizer state.

Capability parity with the reference master
(``/root/reference/src/motion/param_server/master.py:15-59``): a single
process holds the authoritative model parameters and the optimizer; workers
never talk to each other (call-stack §3.3 asymmetry preserved).  The
reference reached this shape with RPC-remote forward + distributed autograd
+ a remote ``DistributedOptimizer``; here the contract is explicit
state transfer - workers push local gradients, the master applies the
update and returns fresh params ("grad-push" PS, the standard design the
reference's remote-forward machinery approximates).

Concurrency: one service thread per worker (each worker owns a dedicated
socket); optimizer updates run under a lock, so gradient application is
serialized but arrival order is free - the same effectively-asynchronous
semantics as the reference's per-worker RPC contexts.  ``sync_mode=True``
instead gathers one gradient from every worker, averages, and applies a
single update (DDP-equivalent math, useful for equivalence tests).

The reference's in-run assertion that gradients actually arrived
(``worker.py:55-58``) maps to the finite-gradient check before every
update.
"""

from __future__ import annotations

import logging
import threading

import numpy as np

from pytorch_distributed_rnn_tpu.param_server import protocol

log = logging.getLogger(__name__)


class ParameterServerMaster:
    def __init__(self, comm, flat_params: np.ndarray, apply_update,
                 sync_mode=False, sync_timeout: float = 300.0):
        """``apply_update(flat_grads) -> flat_params`` advances the owned
        state by one optimizer step and returns the new flat params.
        ``sync_timeout`` bounds how long a sync-mode round waits for
        stragglers before erroring (the reference's RPC timeout analogue,
        ``/root/reference/src/motion/param_server/master.py:56``)."""
        self.comm = comm
        self.params = flat_params.astype(np.float32)
        self.apply_update = apply_update
        self.sync_mode = sync_mode
        self.sync_timeout = float(sync_timeout)
        self.lock = threading.Lock()
        self.num_params = int(flat_params.size)
        self.updates_applied = 0
        # sync-mode rendezvous state
        self._pending: dict[int, np.ndarray] = {}
        self._sync_cv = threading.Condition(self.lock)
        self._waiting: set[int] = set()

    def serve(self):
        """Block until every worker sends DONE.  A failure in any worker's
        service thread (socket error, integrity assertion) is re-raised
        here so the master process reports failure instead of silently
        finishing on a reduced worker set."""
        errors: dict[int, BaseException] = {}

        def guarded(worker):
            try:
                self._serve_worker(worker)
            except BaseException as exc:  # noqa: BLE001 - propagated below
                errors[worker] = exc

        threads = [
            threading.Thread(target=guarded, args=(w,))
            for w in range(1, self.comm.world_size)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            worker, exc = next(iter(errors.items()))
            raise RuntimeError(
                f"parameter-server worker thread(s) failed: "
                f"{sorted(errors)} (first: worker {worker})"
            ) from exc
        log.info(
            f"parameter server done: {self.updates_applied} updates applied"
        )
        return self.params

    def _serve_worker(self, worker: int):
        while True:
            opcode, grads = protocol.recv_request(
                self.comm, worker, self.num_params
            )
            if opcode == protocol.OP_DONE:
                return
            if opcode == protocol.OP_PULL:
                with self.lock:
                    protocol.send_params(self.comm, worker, self.params)
                continue
            # OP_PUSH
            assert grads is not None and grads.size == self.num_params, (
                f"worker {worker} pushed a malformed gradient"
            )
            assert np.isfinite(grads).all(), (
                f"worker {worker} pushed non-finite gradients "
                "(the reference asserts gradient presence per batch; "
                "we assert integrity)"
            )
            if self.sync_mode:
                self._push_sync(worker, grads)
            else:
                with self.lock:
                    self.params = self.apply_update(grads)
                    self.updates_applied += 1
                    protocol.send_params(self.comm, worker, self.params)

    def _push_sync(self, worker: int, grads: np.ndarray):
        """Gather one gradient per worker, average, apply once, release."""
        num_workers = self.comm.world_size - 1
        with self._sync_cv:
            self._pending[worker] = grads
            if len(self._pending) == num_workers:
                mean_grad = np.mean(list(self._pending.values()), axis=0)
                self.params = self.apply_update(mean_grad)
                self.updates_applied += 1
                self._pending.clear()
                for w in list(self._waiting) + [worker]:
                    protocol.send_params(self.comm, w, self.params)
                self._waiting.clear()
                self._sync_cv.notify_all()
            else:
                self._waiting.add(worker)
                generation = self.updates_applied
                completed = self._sync_cv.wait_for(
                    lambda: self.updates_applied > generation,
                    timeout=self.sync_timeout,
                )
                if not completed:
                    # a straggler never delivered: fail loudly instead of
                    # silently proceeding with stale parameters
                    raise RuntimeError(
                        f"sync-mode round timed out after "
                        f"{self.sync_timeout}s waiting on "
                        f"{num_workers - len(self._pending)} missing "
                        f"gradient(s) (worker {worker} was waiting)"
                    )
