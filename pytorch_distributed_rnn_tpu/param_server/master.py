"""Parameter-server master: owns parameters, optimizer state, membership.

Capability parity with the reference master
(``/root/reference/src/motion/param_server/master.py:15-59``): a single
process holds the authoritative model parameters and the optimizer; workers
never talk to each other (call-stack §3.3 asymmetry preserved).  The
reference reached this shape with RPC-remote forward + distributed autograd
+ a remote ``DistributedOptimizer``; here the contract is explicit
state transfer - workers push local gradients, the master applies the
update and returns fresh params ("grad-push" PS, the standard design the
reference's remote-forward machinery approximates).

Concurrency: one service thread per worker (each worker owns a dedicated
socket); optimizer updates run under a lock, so gradient application is
serialized but arrival order is free - the same effectively-asynchronous
semantics as the reference's per-worker RPC contexts.  ``sync_mode=True``
instead gathers one gradient from every worker, averages, and applies a
single update (DDP-equivalent math, useful for equivalence tests).

The reference's in-run assertion that gradients actually arrived
(``worker.py:55-58``) maps to the finite-gradient check before every
update.

Membership is a live object (``resilience/membership.py``): every worker
is a rostered member with a stable worker-id decoupled from its
transport rank.  ``elastic=True`` additionally runs an acceptor on the
rendezvous listener so a new or respawned worker can (re)join mid-run
via the REGISTER op - it receives a STATE_SYNC (current params + the
master's update count + its own push-seq watermark) and enters the next
sync round; the inverse of :meth:`_mark_dead`.  A SIGTERM-drained
worker leaves via DEREGISTER: the roster shrinks *voluntarily*, without
burning the quorum budget.
"""

from __future__ import annotations

import logging
import math
import threading
import time

import numpy as np

from pytorch_distributed_rnn_tpu.param_server import protocol
from pytorch_distributed_rnn_tpu.resilience import membership
from pytorch_distributed_rnn_tpu.utils import threadcheck

log = logging.getLogger(__name__)


class ParameterServerMaster:
    def __init__(self, comm, flat_params: np.ndarray, apply_update,
                 sync_mode=False, sync_timeout: float = 300.0,
                 quorum: float = 1.0, recorder=None,
                 elastic: bool = False, join_timeout: float = 60.0,
                 max_world: int | None = None):
        """``apply_update(flat_grads) -> flat_params`` advances the owned
        state by one optimizer step and returns the new flat params.
        ``sync_timeout`` bounds how long a sync-mode round waits for
        stragglers (the reference's RPC timeout analogue,
        ``/root/reference/src/motion/param_server/master.py:56``).

        ``quorum`` is the fraction of workers whose gradients suffice to
        close a sync round once ``sync_timeout`` expires: at the default
        1.0 a straggler past the timeout is fatal (strict DDP-equivalent
        rounds), while e.g. 0.5 lets the round DEGRADE - average what
        arrived, apply, release the waiters - so a preempted worker slows
        the world instead of killing it (the Podracer/pjit preemptible-
        worker baseline).  A straggler's late gradient joins the next
        round as an ordinary (stale) contribution.

        ``elastic`` accepts REGISTER (re)joins mid-run on the rendezvous
        listener: a dead worker is held on the roster for
        ``join_timeout`` seconds awaiting its respawn before being
        abandoned; worker deaths are tolerated (pending rejoin) even at
        quorum 1.0, and the final verdict only fails when an abandoned
        loss leaves fewer than the quorum's worth of successful
        (done/drained) workers.  ``max_world`` caps the transport rank
        slots reserved for brand-new joiners (default: world + 8)."""
        if not 0.0 < quorum <= 1.0:
            raise ValueError(f"quorum must be in (0, 1], got {quorum}")
        # structured telemetry (obs/recorder.py): degraded rounds, dead
        # workers, membership transitions and the serve() summary become
        # events the CLI can summarize
        from pytorch_distributed_rnn_tpu.obs.recorder import NULL_RECORDER

        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self.comm = comm
        self.params = flat_params.astype(np.float32)
        self.apply_update = apply_update
        self.sync_mode = sync_mode
        self.sync_timeout = float(sync_timeout)
        self.quorum = float(quorum)
        self.elastic = bool(elastic)
        self.join_timeout = float(join_timeout)
        self.max_world = max_world
        self.lock = threadcheck.lock(threading.Lock(), "master.round")
        self.num_params = int(flat_params.size)
        self.updates_applied = 0
        self.degraded_rounds = 0
        # the live membership table: launch-time workers are bootstrapped
        # with worker-id == initial rank; later joins/respawns go through
        # REGISTER.  Push-seq watermarks live on the members, so dedupe
        # survives a worker's respawn (the double-count guard).
        self.roster = membership.Roster(recorder=self.recorder)
        # a fixed world's launch set is not membership telemetry: only
        # elastic runs emit bootstrap member_join events (summarize/
        # health report membership as absent on non-elastic sidecars)
        self.roster.bootstrap(
            range(1, self.comm.world_size), quiet=not self.elastic
        )
        # sync-mode rendezvous state
        self._pending: dict[int, np.ndarray] = {}
        self._sync_cv = threading.Condition(self.lock)
        self._waiting: set[int] = set()
        # trace timeline: a sync round SPANS from its first gathered
        # gradient to the update that closes it (obs/timeline.py renders
        # one ps_round span per round; its close edge is also a clock-
        # alignment sync point against the workers' push-reply edges).
        # _round_seqs records WHICH push seq each worker contributed, so
        # the aligner can pair edges by id even when a degraded round or
        # a retried push shifts the ordinals.
        self._round_tm0: float | None = None
        self._round_seqs: dict[int, int] = {}
        # elastic bookkeeping: per-rank service-thread generation (a
        # stale thread dying after its rank was re-accepted must not
        # mark the NEW incarnation dead), and the tolerated-death table
        # a successful rejoin clears.  _gen_lock makes the stale check
        # atomic against the acceptor's bump: a thread that passes it
        # holds the lock through its _mark_dead, so the mark always
        # lands BEFORE the replacement thread exists (and thus before
        # the new incarnation can REGISTER), never after.
        # the acquisition-order contract (a dying service thread holds
        # _gen_lock through _mark_dead, which takes the round lock and
        # then the roster's; nothing may ever take them the other way):
        # lock-order: ParameterServerMaster._gen_lock -> ParameterServerMaster.lock -> Roster._lock
        self._thread_gen: dict[int, int] = {}
        self._gen_lock = threadcheck.lock(threading.Lock(), "master.gen")  # guards: _thread_gen
        self._tolerated: dict[int, BaseException] = {}
        self._member_cv = threading.Condition(
            threadcheck.lock(threading.Lock(), "master.member"))

    def serve(self):
        """Block until the roster reaches a terminal state: every member
        done (DONE) or drained (DEREGISTER), with no dead member still
        inside its rejoin window.  A failure in a worker's service
        thread (socket error, integrity assertion) is re-raised here so
        the master process reports failure instead of silently finishing
        on a reduced worker set - EXCEPT when deaths are tolerated
        (quorum-degraded sync mode, or any elastic world, where a dying
        worker is marked dead, dropped from later rounds, and awaited
        for rejoin); only a quorum-breaking abandoned loss is fatal."""
        num_workers = self.comm.world_size - 1
        serve_tm0 = time.perf_counter()
        errors: dict[int, BaseException] = {}
        tolerate = self.elastic or (self.sync_mode and self.quorum < 1.0)
        stop_accept = threading.Event()

        def guarded(worker, gen):
            try:
                self._serve_worker(worker, gen=gen)
            except BaseException as exc:  # noqa: BLE001 - propagated below
                with self._gen_lock:
                    if self._thread_gen.get(worker) != gen:
                        # a newer incarnation owns this rank already (the
                        # respawn raced this thread's death detection)
                        log.info(
                            f"stale service thread for rank {worker} "
                            f"exited ({type(exc).__name__}); rank re-owned"
                        )
                    elif tolerate:
                        self._tolerated[worker] = exc
                        self._mark_dead(worker, exc)
                    else:
                        errors[worker] = exc
            finally:
                with self._member_cv:
                    self._member_cv.notify_all()

        def spawn(worker):
            with self._gen_lock:
                gen = self._thread_gen.get(worker, 0) + 1
                self._thread_gen[worker] = gen
            t = threading.Thread(
                target=guarded, args=(worker, gen), daemon=True
            )
            t.start()
            return t

        if self.elastic and hasattr(self.comm, "reserve"):
            # BEFORE any service thread: the reserve reallocates the
            # peer table, which must not race in-flight send/recv
            self.comm.reserve(
                self.max_world or self.comm.world_size + 8
            )
        threads = [spawn(w) for w in range(1, self.comm.world_size)]

        acceptor = None
        if self.elastic and hasattr(self.comm, "accept_peer"):
            def accept_loop():
                while not stop_accept.is_set():
                    rank = self.comm.accept_peer(timeout_s=0.25)
                    if rank is not None:
                        log.info(
                            f"elastic accept: rank {rank} connected; "
                            "awaiting REGISTER"
                        )
                        threads.append(spawn(rank))

            acceptor = threading.Thread(target=accept_loop, daemon=True)
            acceptor.start()

        if not self.elastic:
            for t in threads:
                t.join()
        else:
            self._await_membership_terminal(errors)
            stop_accept.set()
            if acceptor is not None:
                acceptor.join(timeout=5.0)
            for t in list(threads):
                t.join(timeout=5.0)

        if errors:
            worker, exc = next(iter(errors.items()))
            raise RuntimeError(
                f"parameter-server worker thread(s) failed: "
                f"{sorted(errors)} (first: worker {worker})"
            ) from exc
        members = self.roster.members()
        lost = [m for m in members if m.state == membership.DEAD]
        survivors = sum(
            1 for m in members
            if m.state in (membership.DONE, membership.DRAINED)
        )
        if lost and survivors < self._quorum_count(num_workers):
            exc = self._tolerated.get(lost[0].rank)
            raise RuntimeError(
                f"parameter server lost quorum: "
                f"{sorted(m.rank for m in lost)} worker(s) "
                f"{'abandoned (rejoin window expired)' if self.elastic else 'died'}, "
                f"{survivors} survivor(s) < quorum "
                f"{self._quorum_count(num_workers)}"
            ) from exc
        counts = self.roster.counts()
        log.info(
            f"parameter server done: {self.updates_applied} updates "
            f"applied, roster {counts}"
            + (f", {self.degraded_rounds} degraded round(s)"
               if self.degraded_rounds else "")
            + (f", {self.roster.rejoins} rejoin(s)"
               if self.roster.rejoins else "")
        )
        self.recorder.record(
            "ps_summary", updates=self.updates_applied,
            degraded_rounds=self.degraded_rounds,
            workers_lost=len(lost), rejoins=self.roster.rejoins,
        )
        # the run_summary carries the roster verdict so `pdrnn-metrics
        # summarize`/`health` read membership off the master's sidecar
        # like any other run outcome
        self.recorder.record(
            "run_summary",
            duration_s=time.perf_counter() - serve_tm0,
            steps=self.updates_applied,
            roster=counts, rejoins=self.roster.rejoins,
            degraded_rounds=self.degraded_rounds,
        )
        self.recorder.flush()
        return self.params

    def _await_membership_terminal(self, errors):
        """Elastic completion wait: the run is over when no member is
        still joined and every dead member's rejoin window has expired
        (a rejoin re-enters ``joined`` and keeps the run alive)."""
        while not errors:
            members = self.roster.members()
            now = time.perf_counter()
            joined = [m for m in members if m.state == membership.JOINED]
            awaiting = [
                m for m in members
                if m.state == membership.DEAD and m.died_tm is not None
                and now - m.died_tm < self.join_timeout
            ]
            if not joined and not awaiting:
                return
            with self._member_cv:
                self._member_cv.wait(timeout=0.2)

    def _mark_dead(self, worker: int, exc: BaseException):
        """Involuntary loss: drop a dead worker from the rendezvous so
        later rounds close over the survivors instead of timing out on a
        corpse; if the in-flight round now has every live worker's
        gradient, close it here.  The member stays rostered as ``dead``
        so an elastic respawn can re-enter - only via REGISTER."""
        log.warning(
            f"worker {worker} dropped from the sync rendezvous "
            f"({type(exc).__name__}: {exc}); degrading to survivors"
        )
        self.recorder.record(
            "ps_worker_dead", worker=worker,
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        self.roster.mark_dead(
            worker, error=f"{type(exc).__name__}: {str(exc)[:200]}"
        )
        self._rendezvous_leave(worker)

    def _rendezvous_leave(self, worker: int):
        """A member left the round rendezvous (death or drain): discard
        its in-flight contribution and close the round if the survivors
        now cover it.  The roster transition must already have happened
        (``round_ranks`` excludes the leaver)."""
        with self._sync_cv:
            self._pending.pop(worker, None)
            self._round_seqs.pop(worker, None)
            self._waiting.discard(worker)
            live = len(self.roster.round_ranks())
            if self._pending and len(self._pending) >= max(1, live):
                self._close_round()

    def _serve_worker(self, worker: int, gen: int | None = None):
        while True:
            if gen is not None:
                with self._gen_lock:
                    stale = self._thread_gen.get(worker) != gen
                if stale:
                    # the rank's socket slot was re-accepted while this
                    # thread was processing a request: the NEW fd belongs
                    # to the replacement thread - exit instead of racing
                    # it on the wire framing
                    return
            # protocol: ps handles DONE, REGISTER, DEREGISTER, PULL, PUSH
            opcode, grads, seq = protocol.recv_request(
                self.comm, worker, self.num_params
            )
            if opcode == protocol.OP_DONE:
                self.roster.complete(worker)
                return
            if opcode == protocol.OP_REGISTER:
                self._register_worker(worker, worker_id=seq or worker)
                continue
            if opcode == protocol.OP_DEREGISTER:
                # voluntary leave (preemption-aware drain): exits the
                # rendezvous and the quorum denominator without burning
                # the quorum budget - and exits this thread cleanly
                self.roster.drain(worker, seq=seq)
                self._rendezvous_leave(worker)
                return
            if opcode == protocol.OP_PULL:
                with self.lock:
                    # hold contract: the reply must carry the params it
                    # was snapshotted against; sending outside the lock
                    # could interleave with a concurrent update and ship
                    # a half-applied view (per-worker sockets keep the
                    # send short and uncontended)
                    # protocol: ps reply PULL
                    protocol.send_params(self.comm, worker,  # noqa: PD302 - deliberate send-under-lock, see comment
                                         self.params)
                continue
            # OP_PUSH
            member = self.roster.member_for_rank(worker)
            if member is None and self.elastic:
                # a star-joined rank pushing without REGISTER: unrostered
                # gradients must never be averaged in (and its _pending
                # entry could close a round early against a rendezvous
                # that does not count it) - entry is join-protocol-only
                raise RuntimeError(
                    f"push from unrostered rank {worker} without "
                    "REGISTER; elastic-world entry requires the join "
                    "protocol"
                )
            if member is not None and member.state == membership.DEAD:
                # a rank marked dead whose transport recovered: it must
                # re-enter via REGISTER (state sync + watermarks), never
                # by silently reappearing - applying its stale stream
                # here could double-count against its respawn's
                raise RuntimeError(
                    f"push from dead member (worker-id "
                    f"{member.worker_id}, rank {worker}) without "
                    "REGISTER; membership re-entry requires the join "
                    "protocol"
                )
            if not self.roster.note_push(worker, seq):
                # at-or-below the member's push-seq watermark: a retried
                # push whose ORIGINAL made it through but whose reply leg
                # failed (resilience/retry.py retries the whole
                # exchange), or a rejoined worker's stale in-flight push.
                # Either way the gradient is already accounted for - do
                # not average it in twice, just resend current params
                log.warning(
                    f"worker {worker} re-sent push seq {seq}; replying "
                    "with current params without re-applying"
                )
                with self.lock:
                    # same hold contract as the OP_PULL reply above
                    # protocol: ps reply PUSH
                    protocol.send_params(self.comm, worker,  # noqa: PD302 - deliberate send-under-lock, see OP_PULL
                                         self.params)
                continue
            assert grads is not None and grads.size == self.num_params, (
                f"worker {worker} pushed a malformed gradient"
            )
            assert np.isfinite(grads).all(), (
                f"worker {worker} pushed non-finite gradients "
                "(the reference asserts gradient presence per batch; "
                "we assert integrity)"
            )
            if self.sync_mode:
                self._push_sync(worker, grads, seq=seq)
            else:
                with self.lock:
                    # span measured INSIDE the lock: the lock serializes
                    # updates, so per-thread spans on the shared ps
                    # timeline row stay disjoint (lock WAIT would overlap)
                    t0 = time.perf_counter()
                    self.params = self.apply_update(grads)
                    self.updates_applied += 1
                    protocol.send_params(self.comm, worker,  # noqa: PD302 - reply must pair with the update just applied; see OP_PULL contract
                                         self.params)
                    applied = self.updates_applied
                    if self.recorder.enabled:
                        self.recorder.emit_span(
                            "ps_round", t0, time.perf_counter() - t0,
                            cat="ps", round=applied, worker=worker,
                            seq=seq, mode="async",
                        )

    def _register_worker(self, worker: int, worker_id: int):
        """The join protocol's master half: roster the (re)join, then
        reply with a STATE_SYNC - current params, the master's update
        count, and the member's push-seq watermark, so the joiner adopts
        authoritative state and numbers its pushes above everything
        already applied."""
        t0 = time.perf_counter()
        member = self.roster.join(worker_id, worker)
        self._tolerated.pop(worker, None)
        with self.lock:
            step_watermark = self.updates_applied
            seq_watermark = member.push_seq
            # protocol: ps reply REGISTER
            protocol.send_state_sync(
                self.comm, worker, self.params, step_watermark,
                seq_watermark,
            )
        log.info(
            f"state sync: worker-id {worker_id} (rank {worker}, "
            f"incarnation {member.incarnation}) <- {self.num_params} "
            f"params @ update {step_watermark}, push-seq watermark "
            f"{seq_watermark}"
        )
        if self.recorder.enabled:
            self.recorder.emit_span(
                "state_sync", t0, time.perf_counter() - t0, cat="member",
                worker_id=worker_id, rank_slot=worker,
                incarnation=member.incarnation, step=step_watermark,
                seq=seq_watermark,
            )
        with self._member_cv:
            self._member_cv.notify_all()

    def _close_round(self, degraded: bool = False):  # holds: lock
        """Average the gathered gradients, apply ONE update, reply to
        every worker owed fresh params, wake the waiters.  Caller holds
        the lock."""
        gathered = len(self._pending)
        expected = len(self.roster.round_ranks())
        tm0 = self._round_tm0
        self._round_tm0 = None
        seqs = {str(w): s for w, s in self._round_seqs.items()
                if s is not None}
        self._round_seqs = {}
        mean_grad = np.mean(list(self._pending.values()), axis=0)
        self.params = self.apply_update(mean_grad)
        self.updates_applied += 1
        if self.recorder.enabled:
            now = time.perf_counter()
            if tm0 is None:
                tm0 = now
            self.recorder.emit_span(
                "ps_round", tm0, now - tm0, cat="ps",
                round=self.updates_applied, gathered=gathered,
                expected=expected, degraded=degraded, mode="sync",
                # which push seq each worker contributed: the id the
                # clock aligner pairs against worker push-reply edges
                # (ordinal pairing breaks under degradation/retries)
                seqs=seqs,
            )
        for w in sorted(self._pending):
            try:
                protocol.send_params(self.comm, w, self.params)
            except Exception as exc:
                if self.quorum >= 1.0 and not self.elastic:
                    raise
                # a worker that died between push and reply: its service
                # thread will also fail and _mark_dead it; do not let the
                # broken reply socket kill the worker thread CLOSING the
                # round on everyone else's behalf
                log.warning(
                    f"reply to worker {w} failed ({exc}); leaving it to "
                    "the rendezvous death path"
                )
        self._pending.clear()
        self._waiting.clear()
        self._sync_cv.notify_all()

    def _quorum_count(self, num_workers: int) -> int:
        return max(1, math.ceil(self.quorum * num_workers))

    def _push_sync(self, worker: int, grads: np.ndarray,
                   seq: int | None = None):
        """Gather one gradient per live synced worker, average, apply
        once, release.

        On straggler timeout the round degrades to the configured quorum
        (``quorum < 1`` and enough gradients arrived) or fails loudly
        (strict mode, or not even a quorum delivered).  A member that
        (re)joined mid-round is not expected until its first push lands
        - it enters the NEXT round."""
        with self._sync_cv:
            num_workers = max(1, len(self.roster.round_ranks()))
            if not self._pending:
                self._round_tm0 = time.perf_counter()  # round opens here
            self._pending[worker] = grads
            self._round_seqs[worker] = seq
            if len(self._pending) >= num_workers:
                self._close_round()
                return
            self._waiting.add(worker)
            generation = self.updates_applied
            completed = self._sync_cv.wait_for(
                lambda: self.updates_applied > generation,
                timeout=self.sync_timeout,
            )
            if completed:
                return
            # wait_for re-checks under the lock, so exactly one waiter
            # observes the still-open round and owns the timeout decision;
            # later waiters see updates_applied advanced and return above
            num_workers = max(1, len(self.roster.round_ranks()))
            missing = num_workers - len(self._pending)
            if self.quorum < 1.0 and len(self._pending) >= self._quorum_count(
                num_workers
            ):
                self.degraded_rounds += 1
                log.warning(
                    f"sync round degraded to quorum: {len(self._pending)}/"
                    f"{num_workers} gradient(s) after {self.sync_timeout}s "
                    f"({missing} straggler(s)); applying partial average "
                    f"(degraded rounds so far: {self.degraded_rounds})"
                )
                # the degradation rides the round's span event (emitted
                # by _close_round with degraded=True), so the timeline
                # and the summary read one record, not two
                self._close_round(degraded=True)
                return
            # a straggler never delivered and no quorum covers it: fail
            # loudly instead of silently proceeding with stale parameters
            raise RuntimeError(
                f"sync-mode round timed out after {self.sync_timeout}s "
                f"waiting on {missing} missing gradient(s) (worker "
                f"{worker} was waiting; quorum "
                f"{self._quorum_count(num_workers)}/{num_workers} "
                f"{'not met' if self.quorum < 1.0 else 'disabled'})"
            )
