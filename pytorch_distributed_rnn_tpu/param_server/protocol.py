"""Wire protocol for the parameter-server strategy.

Tiny fixed-header messages over the native TCP transport
(``runtime.Communicator``).  The reference used torch RPC with pickled
tensors and distributed autograd (``/root/reference/src/motion/
param_server/util.py:23-25``); here the state that crosses the wire is
explicit: flat float32 parameter/gradient vectors plus a scalar header.

Messages (worker -> master):
  PULL    - request current flat params
  PUSH    - gradient vector; master replies with fresh params
  DONE    - worker finished all epochs

Master replies to PULL/PUSH with the current flat parameter vector.  Loss
stays local to the worker (shipping it per batch would force a host sync
on the worker's device loss scalar for a value the master never needs).

The header carries a per-worker SEQUENCE NUMBER so a retried exchange
(``resilience/retry.py``: the worker re-runs the whole push when only the
reply leg failed) is idempotent: the master detects a duplicate PUSH seq,
skips the re-apply, and just resends current params - without it a
lost-reply retry would average the same gradient into two consecutive
updates.  float32 carries step counts exactly up to 2^24 (~16.7M steps
per run, far past any schedule here).

Elastic membership (``resilience/membership.py``) extends the same wire
format with three membership ops:

  REGISTER   - a new or respawned worker announces its stable WORKER-ID
               (the seq header slot); the master replies with a
               STATE_SYNC payload: [master update count, the worker's
               push-seq watermark] + the current flat params, so the
               joiner adopts authoritative state AND resumes its push
               numbering above everything already applied (stale
               in-flight pushes then dedupe away instead of
               double-averaging)
  DEREGISTER - voluntary leave (preemption-aware drain): the seq slot
               carries the worker's last push seq; the master shrinks
               the roster without burning quorum budget
  STATE_SYNC - reserved for symmetry (the reply to REGISTER; never sent
               worker -> master)
"""

from __future__ import annotations

import numpy as np

OP_PULL = 1
OP_PUSH = 2
OP_DONE = 3
OP_REGISTER = 4
OP_DEREGISTER = 5
OP_STATE_SYNC = 6

_HEADER_DTYPE = np.float32
_HEADER_LEN = 2  # [opcode, seq]  (seq doubles as worker-id for REGISTER)


def send_request(comm, opcode: int, grads: np.ndarray = None,
                 seq: int = 0):
    header = np.array([float(opcode), float(seq)], dtype=_HEADER_DTYPE)
    comm.send(0, header)
    if opcode == OP_PUSH:
        comm.send(0, grads.astype(np.float32, copy=False))


def recv_request(comm, worker: int, num_params: int):
    """Master side: receive one request from ``worker``.
    Returns (opcode, grads-or-None, seq)."""
    header = comm.recv(worker, (_HEADER_LEN,), np.float32)
    opcode = int(header[0])
    seq = int(header[1])
    grads = None
    if opcode == OP_PUSH:
        grads = comm.recv(worker, (num_params,), np.float32)
    return opcode, grads, seq


def send_params(comm, worker: int, flat_params: np.ndarray):
    comm.send(worker, flat_params.astype(np.float32, copy=False))


def recv_params(comm, num_params: int) -> np.ndarray:
    return comm.recv(0, (num_params,), np.float32)


def send_state_sync(comm, worker: int, flat_params: np.ndarray,
                    step: int, seq: int):
    """Master side: the REGISTER reply - [step watermark (master update
    count), the worker's push-seq watermark] then the current params."""
    header = np.array(
        [float(OP_STATE_SYNC), float(step), float(seq)], dtype=_HEADER_DTYPE
    )
    comm.send(worker, header)
    send_params(comm, worker, flat_params)


def recv_state_sync(comm, num_params: int):
    """Worker side: receive the REGISTER reply.
    Returns (flat_params, step_watermark, seq_watermark)."""
    header = comm.recv(0, (3,), np.float32)
    opcode = int(header[0])
    if opcode != OP_STATE_SYNC:
        raise RuntimeError(
            f"expected a STATE_SYNC reply to REGISTER, got opcode {opcode}"
        )
    flat = recv_params(comm, num_params)
    return flat, int(header[1]), int(header[2])
