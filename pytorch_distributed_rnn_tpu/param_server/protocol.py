"""Wire protocol for the parameter-server strategy.

Tiny fixed-header messages over the native TCP transport
(``runtime.Communicator``).  The reference used torch RPC with pickled
tensors and distributed autograd (``/root/reference/src/motion/
param_server/util.py:23-25``); here the state that crosses the wire is
explicit: flat float32 parameter/gradient vectors plus a scalar header.

Messages (worker -> master):
  PULL    - request current flat params
  PUSH    - gradient vector; master replies with fresh params
  DONE    - worker finished all epochs

Master replies to PULL/PUSH with the current flat parameter vector.  Loss
stays local to the worker (shipping it per batch would force a host sync
on the worker's device loss scalar for a value the master never needs).

The header carries a per-worker SEQUENCE NUMBER so a retried exchange
(``resilience/retry.py``: the worker re-runs the whole push when only the
reply leg failed) is idempotent: the master detects a duplicate PUSH seq,
skips the re-apply, and just resends current params - without it a
lost-reply retry would average the same gradient into two consecutive
updates.  float32 carries step counts exactly up to 2^24 (~16.7M steps
per run, far past any schedule here).

Elastic membership (``resilience/membership.py``) extends the same wire
format with three membership ops:

  REGISTER   - a new or respawned worker announces its stable WORKER-ID
               (the seq header slot); the master replies with a
               STATE_SYNC payload: [master update count, the worker's
               push-seq watermark] + the current flat params, so the
               joiner adopts authoritative state AND resumes its push
               numbering above everything already applied (stale
               in-flight pushes then dedupe away instead of
               double-averaging)
  DEREGISTER - voluntary leave (preemption-aware drain): the seq slot
               carries the worker's last push seq; the master shrinks
               the roster without burning quorum budget
  STATE_SYNC - reserved for symmetry (the reply to REGISTER; never sent
               worker -> master)

The streaming actor/learner runner (``streaming/``) adds two ops on the
same wire so experience traffic composes with the membership machinery
above instead of needing a second transport:

  EXPERIENCE - an actor pushes one version-stamped experience batch:
               the 2-float request header ``[opcode, seq]`` is followed
               by an extension header ``[params_version, payload_len]``
               and then the float32 payload.  The learner ALWAYS
               replies with a fixed 3-float verdict
               ``[status, learner_version, throttle_hint_s]`` so the
               wire never stalls: OK (enqueued, watermark advanced),
               DUPLICATE (seq at-or-below the actor's watermark -
               acknowledged but not re-applied), STALE (generated more
               than ``--max-staleness`` versions ago - actor must
               refresh params and re-send under a fresh version) or
               BACKOFF (learner queue full - actor sleeps the throttle
               hint and retries the SAME seq).
  PARAMS_AT  - an actor asks for current params; the learner replies
               ``[params_version]`` + the flat vector.  Unlike PULL
               this reply is version-stamped, which is what lets the
               actor stamp the batches it generates.

float32 carries seq/version counts exactly up to 2^24, same budget as
the PUSH seq header.
"""

from __future__ import annotations

import numpy as np

# The `# protocol: ps ...` trailers are the PD401 wire-contract
# registry (lint/lifecycle.py): every op declared here must name at
# least one `handles` site, and every `request` site must pair with a
# `reply` site unless the op is `oneway` (fire-and-forget).
OP_PULL = 1          # protocol: ps op PULL
OP_PUSH = 2          # protocol: ps op PUSH
OP_DONE = 3          # protocol: ps op DONE oneway
OP_REGISTER = 4      # protocol: ps op REGISTER
OP_DEREGISTER = 5    # protocol: ps op DEREGISTER oneway
OP_STATE_SYNC = 6    # protocol: ps op STATE_SYNC
OP_EXPERIENCE = 7    # protocol: ps op EXPERIENCE
OP_PARAMS_AT = 8     # protocol: ps op PARAMS_AT

# EXPERIENCE reply statuses (the first float of the verdict header)
EXP_OK = 0
EXP_DUPLICATE = 1
EXP_STALE = 2
EXP_BACKOFF = 3

_HEADER_DTYPE = np.float32
_HEADER_LEN = 2  # [opcode, seq]  (seq doubles as worker-id for REGISTER)
_EXP_EXT_LEN = 2  # [params_version, payload_len]
_EXP_REPLY_LEN = 3  # [status, learner_version, throttle_hint_s]


def send_request(comm, opcode: int, grads: np.ndarray = None,
                 seq: int = 0):
    header = np.array([float(opcode), float(seq)], dtype=_HEADER_DTYPE)
    comm.send(0, header)
    if opcode == OP_PUSH:
        comm.send(0, grads.astype(np.float32, copy=False))


def recv_request(comm, worker: int, num_params: int):
    """Master side: receive one request from ``worker``.
    Returns (opcode, grads-or-None, seq)."""
    header = comm.recv(worker, (_HEADER_LEN,), np.float32)
    opcode = int(header[0])
    seq = int(header[1])
    grads = None
    if opcode == OP_PUSH:
        grads = comm.recv(worker, (num_params,), np.float32)
    return opcode, grads, seq


def send_params(comm, worker: int, flat_params: np.ndarray):
    comm.send(worker, flat_params.astype(np.float32, copy=False))


def recv_params(comm, num_params: int) -> np.ndarray:
    return comm.recv(0, (num_params,), np.float32)


def send_state_sync(comm, worker: int, flat_params: np.ndarray,
                    step: int, seq: int):
    """Master side: the REGISTER reply - [step watermark (master update
    count), the worker's push-seq watermark] then the current params."""
    header = np.array(
        [float(OP_STATE_SYNC), float(step), float(seq)], dtype=_HEADER_DTYPE
    )
    comm.send(worker, header)
    send_params(comm, worker, flat_params)


def recv_state_sync(comm, num_params: int):
    """Worker side: receive the REGISTER reply.
    Returns (flat_params, step_watermark, seq_watermark)."""
    header = comm.recv(0, (3,), np.float32)
    opcode = int(header[0])
    if opcode != OP_STATE_SYNC:
        raise RuntimeError(
            f"expected a STATE_SYNC reply to REGISTER, got opcode {opcode}"
        )
    flat = recv_params(comm, num_params)
    return flat, int(header[1]), int(header[2])


def send_experience(comm, seq: int, version: int, payload: np.ndarray):
    """Actor side: push one experience batch stamped with the params
    version it was generated under."""
    send_request(comm, OP_EXPERIENCE, seq=seq)
    flat = np.asarray(payload, dtype=np.float32).reshape(-1)
    ext = np.array([float(version), float(flat.size)], dtype=_HEADER_DTYPE)
    comm.send(0, ext)
    comm.send(0, flat)


def recv_experience_ext(comm, worker: int):
    """Learner side: after ``recv_request`` returned OP_EXPERIENCE,
    receive the extension header + payload.
    Returns (params_version, payload)."""
    ext = comm.recv(worker, (_EXP_EXT_LEN,), np.float32)
    version = int(ext[0])
    payload_len = int(ext[1])
    payload = comm.recv(worker, (payload_len,), np.float32)
    return version, payload


def send_experience_reply(comm, worker: int, status: int, version: int,
                          throttle_hint_s: float = 0.0):
    """Learner side: the fixed verdict reply to every EXPERIENCE push."""
    header = np.array(
        [float(status), float(version), float(throttle_hint_s)],
        dtype=_HEADER_DTYPE,
    )
    comm.send(worker, header)


def recv_experience_reply(comm):
    """Actor side: receive the verdict.
    Returns (status, learner_version, throttle_hint_s)."""
    header = comm.recv(0, (_EXP_REPLY_LEN,), np.float32)
    return int(header[0]), int(header[1]), float(header[2])


def send_params_at(comm, worker: int, version: int,
                   flat_params: np.ndarray):
    """Learner side: the PARAMS_AT reply - [version] + current params."""
    comm.send(worker, np.array([float(version)], dtype=_HEADER_DTYPE))
    send_params(comm, worker, flat_params)


def recv_params_at(comm, num_params: int):
    """Actor side: receive the PARAMS_AT reply.
    Returns (flat_params, version)."""
    header = comm.recv(0, (1,), np.float32)
    flat = recv_params(comm, num_params)
    return flat, int(header[0])
