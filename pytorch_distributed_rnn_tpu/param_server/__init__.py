"""Parameter-server strategy: coordinator-owned params, remote updates.

Capability parity target: the reference's RPC parameter server
(``/root/reference/src/motion/param_server/__init__.py:11-37`` CLI surface:
``parameter-server --world-size --rank --master-address --master-port``).
The TPU-native design replaces torch RPC + distributed autograd with the
framework's native C++ TCP transport (``runtime/``): the master process
owns parameters and Adam state; workers compute local gradients and push
them / pull fresh params.

Run the whole world on one machine (fake-cluster pattern) by omitting
``--rank``:

  python -m pytorch_distributed_rnn_tpu.main --epochs 2 parameter-server \
      --world-size 3
"""

from __future__ import annotations


def add_sub_command(sub_parser):
    parser = sub_parser.add_parser("parameter-server")
    parser.add_argument("--world-size", type=int, default=2)
    parser.add_argument("--rank", type=int, default=None)
    parser.add_argument("--master-address", type=str, default="127.0.0.1")
    parser.add_argument("--master-port", type=str, default="29500")
    parser.add_argument(
        "--ps-mode",
        choices=["async", "sync"],
        default="async",
        help="async: apply each worker's gradient on arrival (reference-"
        "style); sync: average one gradient per worker per step",
    )
    parser.add_argument(
        "--ps-quorum", type=float, default=1.0, metavar="F",
        help="sync mode: fraction of workers whose gradients close a "
        "round once --ps-sync-timeout expires (1.0 = strict, a straggler "
        "is fatal; 0.5 = degrade to half the world and keep training - "
        "the preemptible-worker contract).  Dead workers are dropped "
        "from later rounds while at least ceil(F x workers) survive",
    )
    parser.add_argument(
        "--ps-sync-timeout", type=float, default=300.0, metavar="SECONDS",
        help="sync mode: how long a round waits for stragglers before "
        "erroring (--ps-quorum 1.0) or degrading (< 1.0)",
    )
    parser.add_argument(
        "--ps-transport-retries", type=int, default=3, metavar="N",
        help="worker-side retries (exponential backoff + jitter) for a "
        "failed push/pull exchange before giving up; the whole retry "
        "storm is additionally wall-clock-capped at --ps-sync-timeout "
        "so it can never outlive the round it is retrying into",
    )
    parser.add_argument(
        "--elastic", action="store_true",
        help="elastic membership: the master accepts REGISTER (re)joins "
        "mid-run on the rendezvous listener, and (in spawn mode) a "
        "supervisor respawns dead workers with the same WORKER-ID - the "
        "stable membership identity, decoupled from the transport RANK "
        "(the socket slot a respawn plugs back into).  A rejoiner "
        "receives a STATE_SYNC (current params + its push-seq "
        "watermark) and enters the next sync round",
    )
    parser.add_argument(
        "--min-workers", type=int, default=1, metavar="N",
        help="elastic spawn mode: the supervisor keeps the run alive "
        "while at least N workers are live or completed; below the "
        "floor (respawn budgets exhausted) it tears the world down",
    )
    parser.add_argument(
        "--ps-max-respawns", type=int, default=3, metavar="N",
        help="elastic spawn mode: respawn budget per worker slot",
    )
    parser.add_argument(
        "--ps-join-timeout", type=float, default=60.0, metavar="SECONDS",
        help="elastic: how long the master holds a dead member on the "
        "roster awaiting its REGISTER rejoin before abandoning it "
        "(an abandoned loss is what counts against --ps-quorum)",
    )
    parser.add_argument(
        "--ps-rejoin", action="store_true",
        help="multi-node rank mode: (re)enter a running --elastic world "
        "- star-join the transport at --rank and REGISTER instead of "
        "the initial rendezvous (the manual analogue of the spawn-mode "
        "supervisor's respawn)",
    )
    parser.add_argument(
        "--ps-worker-id", type=int, default=None, metavar="ID",
        help="with --ps-rejoin: the stable worker-id to register under "
        "(default: the transport rank).  The id keys the data shard, "
        "dropout stream and push-seq watermark; the rank is just the "
        "socket slot",
    )
    parser.add_argument(
        "--ps-checkpoint-rounds", type=int, default=0, metavar="N",
        help="master: write a crash-safe checkpoint of the "
        "authoritative params + optimizer state to "
        "--checkpoint-directory every N applied updates (and once at "
        "the end); with --resume auto a restarted master bootstraps "
        "from the newest valid one.  0 disables",
    )
    parser.set_defaults(func=execute)


def execute(args):
    from pytorch_distributed_rnn_tpu.param_server.runner import run

    if getattr(args, "profile", None) or getattr(args, "profile_steps", None):
        # training happens in spawned worker processes; a parent-process
        # trace would be empty - fail loudly instead of silently writing
        # nothing (the other subcommands support --profile/--profile-steps.
        # --metrics IS supported: each spawned role writes its own
        # rank-suffixed sidecar)
        raise SystemExit(
            "--profile/--profile-steps are not supported by the "
            "parameter-server strategy (training runs in spawned worker "
            "processes)"
        )
    from pytorch_distributed_rnn_tpu.training.families import require_family

    # char's vocab-head gradients are the transport stressor; moe rides
    # the same wire dense-exact (expert grads are ordinary pytree leaves)
    require_family(args, ("rnn", "char", "attention", "moe"),
                   "parameter-server")
    return run(args)
