"""Parameter-server strategy: coordinator-owned params, remote updates.

Capability parity target: the reference's RPC parameter server
(``/root/reference/src/motion/param_server/__init__.py:11-37`` CLI surface:
``parameter-server --world-size --rank --master-address --master-port``).
The TPU-native design replaces torch RPC + distributed autograd with the
framework's native C++ TCP transport (``runtime/``): the master process
owns parameters and Adam state; workers compute local gradients and push
them / pull fresh params.

Implementation lands with the runtime milestone; the CLI surface is
registered now so the subcommand set matches the reference.
"""

from __future__ import annotations


def add_sub_command(sub_parser):
    parser = sub_parser.add_parser("parameter-server")
    parser.add_argument("--world-size", type=int, default=2)
    parser.add_argument("--rank", type=int, default=None)
    parser.add_argument("--master-address", type=str, default="localhost")
    parser.add_argument("--master-port", type=str, default="29500")
    parser.set_defaults(func=execute)


def execute(args):
    try:
        from pytorch_distributed_rnn_tpu.param_server.runner import run
    except ImportError as exc:
        raise SystemExit(
            "the parameter-server strategy is not implemented yet "
            "(it lands with the native runtime milestone)"
        ) from exc
    return run(args)
