"""Parameter-server launcher: master + worker process orchestration.

Capability parity with ``/root/reference/src/motion/param_server/
__init__.py:40-73``: sets the MASTER_ADDR/MASTER_PORT-style rendezvous,
runs rank 0 as the parameter-server master and ranks >0 as one worker
process each.  Like the reference, a single invocation launches the role
for ITS rank (one process per node); additionally, omitting ``--rank``
spawns the whole world locally via multiprocessing - the single-machine
fake-cluster pattern (SURVEY §4.2).
"""

from __future__ import annotations

import json
import logging
import multiprocessing as mp

import jax
import numpy as np
import optax
from jax.flatten_util import ravel_pytree

from pytorch_distributed_rnn_tpu.runtime import Communicator

log = logging.getLogger(__name__)


def _build_model_and_flat_params(args, training_set, seed):
    """Family-aware model + flat parameter vector (the PS wire format).
    Families rnn/char/attention/moe via ``training/families.py`` - master
    and workers must build the IDENTICAL model from the same flags/seed,
    so the one construction path serves both roles."""
    from pytorch_distributed_rnn_tpu.training import families

    model = families.build_model(args, training_set)
    params = model.init(jax.random.PRNGKey(seed if seed is not None else 0))
    flat, unravel = ravel_pytree(params)
    return model, np.asarray(flat, np.float32), unravel


def _load_datasets(args):
    from pytorch_distributed_rnn_tpu.training import families

    return families.load_datasets(args)


def run_master(args):
    from pytorch_distributed_rnn_tpu.param_server.master import (
        ParameterServerMaster,
    )

    logging.basicConfig(level=args.log)
    training_set, _, _ = _load_datasets(args)
    _, flat, unravel = _build_model_and_flat_params(
        args, training_set, args.seed
    )

    optimizer = optax.adam(args.learning_rate)
    opt_state = optimizer.init(unravel(flat))

    @jax.jit
    def _update(flat_params, opt_state, flat_grads):
        params = unravel(flat_params)
        grads = unravel(flat_grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_flat, _ = ravel_pytree(new_params)
        return new_flat, opt_state

    state = {"flat": flat, "opt": opt_state}

    def apply_update(flat_grads):
        new_flat, new_opt = _update(state["flat"], state["opt"], flat_grads)
        state["flat"] = np.asarray(new_flat, np.float32)
        state["opt"] = new_opt
        return state["flat"]

    from pytorch_distributed_rnn_tpu.obs import MetricsRecorder

    # the master's sidecar is rank-0's (workers are ranks >= 1): quorum
    # degradations and dead workers land next to the workers' step events
    recorder = MetricsRecorder.resolve(args, rank=0, meta={"role": "master"})
    comm = Communicator(
        args.master_address, int(args.master_port), 0, args.world_size
    )
    try:
        master = ParameterServerMaster(
            comm, flat, apply_update, sync_mode=(args.ps_mode == "sync"),
            sync_timeout=getattr(args, "ps_sync_timeout", 300.0),
            quorum=getattr(args, "ps_quorum", 1.0),
            recorder=recorder,
        )
        final = master.serve()
    finally:
        comm.close()
        recorder.close()
    return final


def _worker_faults(args, rank: int | None = None):
    """The worker-side chaos schedule (``--faults`` / ``PDRNN_CHAOS``),
    bound to the worker's rank so ``@rank``-qualified events (preempt
    ONE worker) fire in the right process.  Network events ride the
    ``PDRNN_FAULT_*`` env, exported both here and by :func:`run` before
    spawning (children inherit it)."""
    from pytorch_distributed_rnn_tpu.resilience import FaultSchedule

    return FaultSchedule.resolve(args, rank=rank)


def run_worker(args, rank: int):
    from pytorch_distributed_rnn_tpu.param_server.worker import (
        ParameterServerWorkerTrainer,
    )

    logging.basicConfig(level=args.log)
    # rendezvous BEFORE loading data: the master preprocesses first and
    # writes the cache, so workers (released only once the master's side of
    # the rendezvous exists) read the warm cache instead of racing to
    # preprocess the same files
    comm = Communicator(
        args.master_address, int(args.master_port), rank, args.world_size
    )
    training_set, _, _ = _load_datasets(args)
    model, _, _ = _build_model_and_flat_params(
        args, training_set, args.seed
    )
    from pytorch_distributed_rnn_tpu.obs import MetricsRecorder
    from pytorch_distributed_rnn_tpu.training import families

    trainer_class = families.wrap_trainer(args, ParameterServerWorkerTrainer)
    # per-worker telemetry sidecar (rank-suffixed path): ps_exchange
    # latency/retry events plus the base trainer's step/epoch stream
    recorder = MetricsRecorder.resolve(args, rank=rank,
                                       meta={"role": "worker"})
    try:
        trainer = trainer_class(
            comm,
            model,
            training_set,
            batch_size=args.batch_size,
            learning_rate=args.learning_rate,
            worker_rank=rank,
            num_workers=args.world_size - 1,
            seed=args.seed,
            # forwarded so the unsupported-flag guard raises instead of
            # the flag being silently dropped
            grad_accum=getattr(args, "grad_accum", 1),
            fuse_run=getattr(args, "fuse_run", False),
            checkpoint_format=getattr(args, "checkpoint_format",
                                      "gathered"),
            checkpoint_async=getattr(args, "checkpoint_async", False),
            transport_retries=getattr(args, "ps_transport_retries", 3),
            faults=_worker_faults(args, rank),
            recorder=recorder,
        )
        _, train_history, _ = trainer.train(epochs=args.epochs)
        trainer.finish()
    finally:
        comm.close()
        recorder.close()

    if rank == 1:
        with open("history.json", "w") as file:
            json.dump(
                {"train_history": train_history, "validation_history": []}, file
            )
    return train_history


def _spawn_entry(args, rank):
    # force CPU in spawned children: each child would otherwise race to
    # claim the single local accelerator
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
    if rank == 0:
        run_master(args)
    else:
        run_worker(args, rank)


def run(args):
    if args.world_size < 2:
        raise SystemExit("parameter-server needs --world-size >= 2")
    if getattr(args, "max_bad_steps", 0):
        # loud, not silent: the optimizer that applies updates lives on
        # the master, so a worker-side apply_if_finite wrap would never
        # see an update - the master's finite-gradient assertion (and,
        # under --ps-quorum < 1, dropping the offending worker) is the
        # PS-side integrity story
        log.warning(
            "--max-bad-steps has no effect under the parameter-server "
            "strategy: the master asserts gradient integrity per push "
            "instead (quorum mode drops a worker whose pushes fail)"
        )
    # bridge the chaos schedule's net events onto the transport's
    # PDRNN_FAULT_* contract BEFORE any communicator (or spawned child,
    # which inherits the env) is constructed
    faults = _worker_faults(args)
    if faults is not None:
        faults.export_network()
    if args.rank is not None:
        # one role per invocation (multi-node layout)
        if args.rank == 0:
            return run_master(args)
        return run_worker(args, args.rank)

    # local mode: spawn the whole world (fake-cluster pattern)
    ctx = mp.get_context("spawn")
    procs = [
        ctx.Process(target=_spawn_entry, args=(args, rank))
        for rank in range(args.world_size)
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join()
    failed = {rank: p.exitcode for rank, p in enumerate(procs)
              if p.exitcode != 0}
    if failed:
        # quorum-degraded sync mode tolerates preempted WORKERS at the
        # process level too, mirroring the master's in-run policy: the
        # run succeeded if the master finished (it enforced quorum on
        # every round) and a quorum of workers completed
        import math

        quorum = getattr(args, "ps_quorum", 1.0)
        num_workers = args.world_size - 1
        survivors = num_workers - sum(1 for r in failed if r >= 1)
        if (
            args.ps_mode == "sync"
            and quorum < 1.0
            and 0 not in failed
            and survivors >= max(1, math.ceil(quorum * num_workers))
        ):
            log.warning(
                f"parameter-server run degraded: worker process(es) "
                f"{sorted(failed)} died ({failed}), {survivors}/"
                f"{num_workers} workers completed (quorum held)"
            )
            return 0
        raise SystemExit(
            f"parameter-server processes failed: "
            f"{sorted(failed.values())}"
        )
    return 0
