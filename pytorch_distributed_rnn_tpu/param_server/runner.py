"""Parameter-server launcher: master + worker process orchestration.

Capability parity with ``/root/reference/src/motion/param_server/
__init__.py:40-73``: sets the MASTER_ADDR/MASTER_PORT-style rendezvous,
runs rank 0 as the parameter-server master and ranks >0 as one worker
process each.  Like the reference, a single invocation launches the role
for ITS rank (one process per node); additionally, omitting ``--rank``
spawns the whole world locally via multiprocessing - the single-machine
fake-cluster pattern (SURVEY §4.2).

Elastic mode (``--elastic``): the spawn world is supervised
(``launcher/supervisor.py``) - a worker that dies is respawned with the
same worker-id, star-joins the transport, and re-enters the run via the
REGISTER/STATE_SYNC join protocol; a SIGTERM'd worker drains (flushes
its in-flight gradient, DEREGISTERs, exits 0) instead of crashing.  The
master can additionally bootstrap its authoritative state from the
newest valid checkpoint (``--resume auto`` + ``--checkpoint-directory``)
and write one every ``--ps-checkpoint-rounds`` updates, so a master
restart re-seeds the world from durable state.
"""

from __future__ import annotations

import json
import logging
import multiprocessing as mp
import os
import threading

import jax
import numpy as np
import optax
from jax.flatten_util import ravel_pytree

from pytorch_distributed_rnn_tpu.runtime import Communicator

log = logging.getLogger(__name__)

# exit code of a worker that drained on SIGTERM: 0 on purpose - a
# voluntary leave is success (the supervisor must not respawn it, CI
# must not redden on it); the telemetry distinction rides the
# member_drain event, not the exit code
DRAIN_EXIT_CODE = 0


class AsyncCheckpointWriter:
    """Coalescing background checkpoint writer for the master.

    ``apply_update`` runs under the master's round lock (sync-mode close
    or the async push handler), so serializing the full params+opt state
    to disk inline would stall every worker's push/pull reply behind
    file I/O.  The master's state values are REPLACED per update, never
    mutated, so a snapshot is a reference grab: :meth:`submit` parks the
    newest snapshot and the writer thread persists it outside every
    lock.  Back-to-back submissions coalesce - only the most recent
    pending snapshot is written."""

    def __init__(self, write):
        self._write = write
        self._cv = threading.Condition()
        self._snap = None
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name="ps-ckpt-writer", daemon=True
        )
        self._thread.start()

    def submit(self, *snap) -> None:
        with self._cv:
            self._snap = snap
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._snap is None and not self._stop:
                    self._cv.wait()
                snap, self._snap = self._snap, None
                if snap is None:
                    return
            self._write(*snap)

    def close(self, timeout: float = 60.0) -> None:
        """Stop the writer (dropping any still-pending snapshot - the
        caller writes the authoritative final state synchronously)."""
        with self._cv:
            self._snap = None
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=timeout)


def _build_model_and_flat_params(args, training_set, seed):
    """Family-aware model + flat parameter vector (the PS wire format).
    Families rnn/char/attention/moe via ``training/families.py`` - master
    and workers must build the IDENTICAL model from the same flags/seed,
    so the one construction path serves both roles."""
    from pytorch_distributed_rnn_tpu.training import families

    model = families.build_model(args, training_set)
    params = model.init(jax.random.PRNGKey(seed if seed is not None else 0))
    flat, unravel = ravel_pytree(params)
    return model, np.asarray(flat, np.float32), unravel


def _load_datasets(args):
    from pytorch_distributed_rnn_tpu.training import families

    return families.load_datasets(args)


def run_master(args):
    from pytorch_distributed_rnn_tpu.param_server.master import (
        ParameterServerMaster,
    )

    logging.basicConfig(level=args.log)
    training_set, _, _ = _load_datasets(args)
    _, flat, unravel = _build_model_and_flat_params(
        args, training_set, args.seed
    )

    optimizer = optax.adam(args.learning_rate)
    opt_state = optimizer.init(unravel(flat))

    # master-restart bootstrap: --resume auto re-seeds the authoritative
    # params + optimizer state from the newest VALID checkpoint (corrupt
    # files are skipped by the loader), so a restarted master hands
    # rejoining workers trained state instead of a fresh init
    ckpt_dir = getattr(args, "checkpoint_directory", None)
    ckpt_rounds = int(getattr(args, "ps_checkpoint_rounds", 0) or 0)
    ckpt_count = 0
    if getattr(args, "resume", None) is not None and ckpt_dir:
        from pytorch_distributed_rnn_tpu.training.checkpoint import (
            find_latest_checkpoint,
            load_checkpoint,
        )

        latest = find_latest_checkpoint(ckpt_dir)
        if latest is not None:
            params, opt_state, meta = load_checkpoint(
                latest, unravel(flat), opt_state
            )
            flat = np.asarray(ravel_pytree(params)[0], np.float32)
            ckpt_count = int(meta["epoch"])
            log.info(
                f"master bootstrap: restored {latest} "
                f"(checkpoint ordinal {ckpt_count})"
            )

    state = {"flat": flat, "opt": opt_state, "updates": 0}

    @jax.jit
    def _update(flat_params, opt_state, flat_grads):
        params = unravel(flat_params)
        grads = unravel(flat_grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_flat, _ = ravel_pytree(new_params)
        return new_flat, opt_state

    def apply_update(flat_grads):
        new_flat, new_opt = _update(state["flat"], state["opt"], flat_grads)
        state["flat"] = np.asarray(new_flat, np.float32)
        state["opt"] = new_opt
        state["updates"] += 1
        if ckpt_writer is not None and state["updates"] % ckpt_rounds == 0:
            # snapshot, don't write: apply_update runs under the
            # master's round lock, and the state values are replaced
            # (never mutated), so the references are a consistent pair
            ckpt_writer.submit(state["flat"], state["opt"], state["updates"])
        return state["flat"]

    def _save_master_checkpoint(flat_now, opt_now, updates_now):
        from pytorch_distributed_rnn_tpu.training.checkpoint import (
            save_checkpoint,
        )

        nonlocal ckpt_count
        path = save_checkpoint(
            ckpt_dir, ckpt_count, unravel(flat_now), opt_now, loss=0.0,
        )
        ckpt_count += 1
        log.info(f"master checkpoint: {path} @ update {updates_now}")

    ckpt_writer = (
        AsyncCheckpointWriter(_save_master_checkpoint)
        if ckpt_rounds and ckpt_dir else None
    )

    from pytorch_distributed_rnn_tpu.obs import MetricsRecorder

    # the master's sidecar is rank-0's (workers are ranks >= 1): quorum
    # degradations, membership transitions and dead workers land next to
    # the workers' step events
    recorder = MetricsRecorder.resolve(args, rank=0, meta={"role": "master"})
    # live plane: the master anchors the /metrics + /health aggregator
    # (the digests it ingests include its own - roster story included -
    # and every worker's); SIGUSR2 dumps all-thread stacks on demand
    plane = None
    if recorder.enabled:
        from pytorch_distributed_rnn_tpu.obs.live import LivePlane
        from pytorch_distributed_rnn_tpu.obs.watchdog import (
            install_stack_dump_handler,
        )

        install_stack_dump_handler(recorder.path)
        # no chaos annotation here: fault schedules fire in the workers
        # (the master applies updates, it does not run the data path)
        plane = LivePlane.resolve(args, recorder, rank=0, role="master")
    comm = Communicator(
        args.master_address, int(args.master_port), 0, args.world_size
    )
    try:
        master = ParameterServerMaster(
            comm, flat, apply_update, sync_mode=(args.ps_mode == "sync"),
            sync_timeout=getattr(args, "ps_sync_timeout", 300.0),
            quorum=getattr(args, "ps_quorum", 1.0),
            recorder=recorder,
            elastic=bool(getattr(args, "elastic", False)),
            join_timeout=getattr(args, "ps_join_timeout", 60.0),
        )
        final = master.serve()
        if ckpt_writer is not None:
            # drain the writer, then persist the authoritative final
            # state synchronously (no lock is held here)
            ckpt_writer.close()
            _save_master_checkpoint(
                state["flat"], state["opt"], state["updates"]
            )
    finally:
        if ckpt_writer is not None:
            ckpt_writer.close()
        comm.close()
        recorder.close()
        if plane is not None:
            plane.close()
    return final


def _worker_faults(args, rank: int | None = None):
    """The worker-side chaos schedule (``--faults`` / ``PDRNN_CHAOS``),
    bound to the worker's rank so ``@rank``-qualified events (preempt
    ONE worker) fire in the right process.  Network events ride the
    ``PDRNN_FAULT_*`` env, exported both here and by :func:`run` before
    spawning (children inherit it)."""
    from pytorch_distributed_rnn_tpu.resilience import FaultSchedule

    return FaultSchedule.resolve(args, rank=rank)


def run_worker(args, rank: int, worker_id: int | None = None,
               rejoin: bool = False):
    """One PS worker process.  ``rejoin=True`` is the elastic path: the
    transport is star-joined (the master's acceptor installs the rank)
    and the run enters via REGISTER/STATE_SYNC instead of the initial
    rendezvous + pull.  Returns this worker's train history; a SIGTERM
    drain returns None after deregistering (process exits 0)."""
    from pytorch_distributed_rnn_tpu.param_server.worker import (
        ParameterServerWorkerTrainer,
    )
    from pytorch_distributed_rnn_tpu.resilience.membership import (
        DrainRequested,
        DrainSignal,
    )

    logging.basicConfig(level=args.log)
    # the preemption notice: SIGTERM requests a drain; the trainer
    # honors it at the next step boundary (in-flight gradient flushed)
    drain = DrainSignal().install()
    faults = _worker_faults(args, rank)
    if rejoin and faults is not None:
        # a respawned incarnation must not replay the deterministic
        # lifetime fault that killed its predecessor (addresses are
        # run-relative; the drill would never converge)
        faults = faults.for_rejoin()
    # rendezvous BEFORE loading data: the master preprocesses first and
    # writes the cache, so workers (released only once the master's side of
    # the rendezvous exists) read the warm cache instead of racing to
    # preprocess the same files
    comm = Communicator(
        args.master_address, int(args.master_port), rank, args.world_size,
        star=rejoin,
    )
    training_set, _, _ = _load_datasets(args)
    model, _, _ = _build_model_and_flat_params(
        args, training_set, args.seed
    )
    from pytorch_distributed_rnn_tpu.obs import MetricsRecorder
    from pytorch_distributed_rnn_tpu.training import families

    trainer_class = families.wrap_trainer(args, ParameterServerWorkerTrainer)
    # per-worker telemetry sidecar (rank-suffixed path): ps_exchange
    # latency/retry events plus the base trainer's step/epoch stream.
    # A respawn REWRITES the rank's sidecar (its meta carries the
    # incarnation hint via rejoin) - the master's sidecar keeps the
    # whole membership story either way
    recorder = MetricsRecorder.resolve(
        args, rank=rank, meta={"role": "worker", "rejoin": rejoin}
    )
    # live plane: workers push digests to the master's aggregator (the
    # --live address is shared via the spawned args / PDRNN_LIVE env);
    # each worker runs its own stall watchdog + SIGUSR2 dump hook
    plane = None
    if recorder.enabled:
        from pytorch_distributed_rnn_tpu.obs.live import LivePlane
        from pytorch_distributed_rnn_tpu.obs.watchdog import (
            install_stack_dump_handler,
        )

        install_stack_dump_handler(recorder.path)
        plane = LivePlane.resolve(args, recorder, rank=rank,
                                  role="worker", faults=faults)
    train_history = None
    try:
        trainer = trainer_class(
            comm,
            model,
            training_set,
            batch_size=args.batch_size,
            learning_rate=args.learning_rate,
            worker_rank=rank,
            num_workers=max(1, args.world_size - 1),
            seed=args.seed,
            # forwarded so the unsupported-flag guard raises instead of
            # the flag being silently dropped
            grad_accum=getattr(args, "grad_accum", 1),
            fuse_run=getattr(args, "fuse_run", False),
            checkpoint_format=getattr(args, "checkpoint_format",
                                      "gathered"),
            checkpoint_async=getattr(args, "checkpoint_async", False),
            transport_retries=getattr(args, "ps_transport_retries", 3),
            # retry storms must die inside the round they retry into
            transport_deadline_s=getattr(args, "ps_sync_timeout", 300.0),
            worker_id=worker_id if worker_id is not None else rank,
            register=rejoin,
            drain_signal=drain,
            faults=faults,
            recorder=recorder,
        )
        try:
            _, train_history, _ = trainer.train(epochs=args.epochs)
            trainer.finish()
        except DrainRequested:
            # preemption-aware drain: the in-flight gradient already
            # flushed (the drain is honored after the exchange), so
            # deregister and leave SUCCESSFULLY - distinguishable from a
            # crash by exit code AND by the member_drain event
            trainer.deregister()
            log.warning(
                f"worker {rank} drained on SIGTERM (exit "
                f"{DRAIN_EXIT_CODE})"
            )
    finally:
        comm.close()
        recorder.close()
        if plane is not None:
            plane.close()

    if rank == 1 and train_history is not None:
        with open("history.json", "w") as file:
            json.dump(
                {"train_history": train_history, "validation_history": []}, file
            )
    return train_history


def _spawn_entry(args, rank, worker_id=None, rejoin=False):
    # force CPU in spawned children: each child would otherwise race to
    # claim the single local accelerator
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
    if rank == 0:
        run_master(args)
    else:
        run_worker(args, rank, worker_id=worker_id, rejoin=rejoin)


def _run_elastic(args, ctx):
    """Supervised elastic spawn world: the master runs unsupervised (it
    owns the state); workers are supervised - a death is respawned with
    the same worker-id (rejoining via REGISTER) until the respawn
    budget runs out, a drain/completion (exit 0) is terminal."""
    from pytorch_distributed_rnn_tpu.launcher.supervisor import (
        ElasticSupervisor,
        supervision_alert_hook,
    )
    from pytorch_distributed_rnn_tpu.obs.live import resolve_event_push

    master = ctx.Process(target=_spawn_entry, args=(args, 0))
    master.start()

    def spawn_worker(rank, worker_id, rejoin):
        p = ctx.Process(
            target=_spawn_entry, args=(args, rank, worker_id, rejoin)
        )
        p.start()
        return p

    # supervisor events -> fleet alerts: the parent process has no
    # recorder (rank 0's sidecar belongs to the master child), so
    # respawn/collapse findings go straight to the aggregator over the
    # live plane's push contract
    supervisor = ElasticSupervisor(
        spawn_worker,
        min_workers=int(getattr(args, "min_workers", 1) or 1),
        max_respawns=int(getattr(args, "ps_max_respawns", 3)),
        on_event=supervision_alert_hook(push=resolve_event_push(args)),
    )
    supervisor.launch(range(1, args.world_size))
    healthy = supervisor.supervise(lambda: master.exitcode)
    if not healthy:
        log.error(
            "elastic supervisor: worker pool fell below --min-workers "
            f"{supervisor.min_workers} with no respawn budget left; "
            "tearing down"
        )
        master.terminate()
    master.join()
    # the master's exit ends the run: reap/terminate what remains WITHOUT
    # respawning into a dead world
    supervisor.shutdown()
    verdict = supervisor.verdict()
    log.info(f"elastic supervisor verdict: {verdict}")
    if not healthy or master.exitcode != 0:
        raise SystemExit(
            f"elastic parameter-server run failed: master exit "
            f"{master.exitcode}, supervisor {verdict}"
        )
    return 0


def run(args):
    if args.world_size < 2:
        raise SystemExit("parameter-server needs --world-size >= 2")
    if getattr(args, "max_bad_steps", 0):
        # loud, not silent: the optimizer that applies updates lives on
        # the master, so a worker-side apply_if_finite wrap would never
        # see an update - the master's finite-gradient assertion (and,
        # under --ps-quorum < 1, dropping the offending worker) is the
        # PS-side integrity story
        log.warning(
            "--max-bad-steps has no effect under the parameter-server "
            "strategy: the master asserts gradient integrity per push "
            "instead (quorum mode drops a worker whose pushes fail)"
        )
    # bridge the chaos schedule's net events onto the transport's
    # PDRNN_FAULT_* contract BEFORE any communicator (or spawned child,
    # which inherits the env) is constructed
    faults = _worker_faults(args)
    if faults is not None:
        faults.export_network()
    if args.rank is not None:
        # one role per invocation (multi-node layout); --ps-rejoin is
        # the manual elastic re-entry: star-join + REGISTER under the
        # given (or rank-derived) worker-id
        if args.rank == 0:
            return run_master(args)
        return run_worker(
            args, args.rank,
            worker_id=getattr(args, "ps_worker_id", None),
            rejoin=bool(getattr(args, "ps_rejoin", False)),
        )

    # local mode: spawn the whole world (fake-cluster pattern)
    ctx = mp.get_context("spawn")
    if getattr(args, "elastic", False):
        return _run_elastic(args, ctx)
    procs = [
        ctx.Process(target=_spawn_entry, args=(args, rank))
        for rank in range(args.world_size)
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join()
    failed = {rank: p.exitcode for rank, p in enumerate(procs)
              if p.exitcode != 0}
    if failed:
        # quorum-degraded sync mode tolerates preempted WORKERS at the
        # process level too, mirroring the master's in-run policy: the
        # run succeeded if the master finished (it enforced quorum on
        # every round) and a quorum of workers completed
        import math

        quorum = getattr(args, "ps_quorum", 1.0)
        num_workers = args.world_size - 1
        survivors = num_workers - sum(1 for r in failed if r >= 1)
        if (
            args.ps_mode == "sync"
            and quorum < 1.0
            and 0 not in failed
            and survivors >= max(1, math.ceil(quorum * num_workers))
        ):
            log.warning(
                f"parameter-server run degraded: worker process(es) "
                f"{sorted(failed)} died ({failed}), {survivors}/"
                f"{num_workers} workers completed (quorum held)"
            )
            return 0
        raise SystemExit(
            f"parameter-server processes failed: "
            f"{sorted(failed.values())}"
        )
    return 0
