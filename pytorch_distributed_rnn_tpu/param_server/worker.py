"""Parameter-server worker: local data + gradients, remote parameters.

Capability parity with the reference worker
(``/root/reference/src/motion/param_server/worker.py:18-94``): the worker
trainer keeps the data pipeline and loss computation; parameters and the
optimizer live on the master.  Where the reference routed every forward
through an RPC to the master and span the backward graph across both
processes via distributed autograd, the TPU-native worker computes forward
AND backward locally as one jitted XLA program (the accelerator is on the
worker - shipping activations over RPC per batch would starve it), then
pushes the flat gradient and receives fresh parameters.  Evaluation and
checkpointing are disabled on workers like the reference
(``worker.py:67-75``).

Elastic membership (``resilience/membership.py``): a worker has a stable
``worker_id`` decoupled from its transport rank.  With ``register=True``
(a respawned or late-joining worker) the initial pull is replaced by the
join protocol - REGISTER, then a STATE_SYNC reply carrying the current
params and the worker's push-seq watermark, so its push numbering
resumes above everything the master already applied and any stale
in-flight push dedupes away.  A SIGTERM (preemption notice) is a
*drain*: the in-flight gradient exchange completes, DEREGISTER is sent,
and the process exits 0 - telemetry-distinguishable from a crash.
"""

from __future__ import annotations

import logging
import math
import time

import jax
import numpy as np
from jax.flatten_util import ravel_pytree

from pytorch_distributed_rnn_tpu.data.sampler import DistributedSampler
from pytorch_distributed_rnn_tpu.param_server import protocol
from pytorch_distributed_rnn_tpu.resilience.membership import DrainSignal
from pytorch_distributed_rnn_tpu.resilience.retry import retry_transport
from pytorch_distributed_rnn_tpu.training.base import Trainer
from pytorch_distributed_rnn_tpu.training.formatter import TrainingMessageFormatter

log = logging.getLogger(__name__)


class ParameterServerWorkerTrainer(Trainer):
    """Trainer whose optimizer step happens on the master."""

    # every step pushes gradients / pulls params over TCP: the host must
    # act per batch, so the scanned device-resident epoch path cannot apply
    DEVICE_DATA = False
    SUPPORTS_GRAD_ACCUM = False  # grads are computed by its own push step

    def __init__(
        self,
        comm,
        model,
        training_set,
        batch_size: int,
        learning_rate: float,
        worker_rank: int,
        num_workers: int,
        seed: int | None = None,
        grad_accum: int = 1,
        fuse_run: bool = False,
        checkpoint_format: str = "gathered",
        checkpoint_async: bool = False,
        transport_retries: int = 3,
        transport_deadline_s: float | None = None,
        worker_id: int | None = None,
        register: bool = False,
        drain_signal: DrainSignal | None = None,
        # resilience knobs; on PS workers only `faults` is meaningful
        # (checkpointing is disabled here, and the optimizer that applies
        # updates lives on the MASTER, whose finite-gradient assertion is
        # the PS-side integrity guard)
        **kwargs,
    ):
        # the shard follows the stable worker-id (a respawn re-reads ITS
        # data stream); a late joiner beyond the launch world wraps onto
        # an existing shard - PS semantics tolerate overlap, gradients
        # just average
        shard = ((worker_id if worker_id is not None else worker_rank) - 1
                 ) % max(1, num_workers)
        sampler = DistributedSampler(
            len(training_set),
            num_replicas=num_workers,
            rank=shard,
            seed=seed or 0,
        )
        super().__init__(
            model=model,
            training_set=training_set,
            # global-batch semantics: each worker loads its share
            batch_size=max(1, batch_size // num_workers),
            **kwargs,
            learning_rate=learning_rate,
            validation_set=None,  # eval disabled on PS workers (reference parity)
            test_set=None,
            checkpoint_dir=None,  # checkpointing disabled on PS workers
            sampler=sampler,
            seed=seed,
            grad_accum=grad_accum,
            # DEVICE_DATA=False: an explicit --fuse-run is rejected loudly
            # by the base gate (every step needs the host for push/pull)
            fuse_run=fuse_run,
            # checkpointing is disabled on PS workers (checkpoint_dir=None
            # above - reference parity), but the flags still route through
            # base validation so bad combinations raise instead of being
            # silently dropped
            checkpoint_format=checkpoint_format,
            checkpoint_async=checkpoint_async,
        )
        self.comm = comm
        self.worker_rank = worker_rank
        self.num_workers = num_workers
        # the stable membership identity: survives respawns (the
        # supervisor relaunches a dead worker with the same id), while
        # worker_rank is just the transport slot it plugs back into
        self.worker_id = int(worker_id) if worker_id is not None else int(
            worker_rank
        )
        # preemption-aware drain: checked at step boundaries, AFTER the
        # in-flight exchange completed (the flush contract)
        self._drain = drain_signal
        # transient transport errors (injected faults, preemptible
        # networks) retry with exponential backoff + jitter seeded by the
        # rank, so workers decorrelate their retry storms while a chaos
        # run stays reproducible
        self._transport_retries = int(transport_retries)
        # total-deadline budget for one exchange's retry storm: derived
        # from --ps-sync-timeout by the runner, so retries can never
        # outlive the sync round they are retrying into
        self._transport_deadline = transport_deadline_s
        # per-step push sequence number: a RETRY re-sends the same seq,
        # so the master can detect a duplicate (reply leg failed after
        # the update applied) and not average the gradient in twice
        self._push_seq = 0
        flat, self._unravel = ravel_pytree(self.params)
        self.num_params = int(flat.size)

        if register:
            # join protocol (respawn/late join): REGISTER announces the
            # stable worker-id; the STATE_SYNC reply carries the params
            # AND the push-seq watermark this worker's stream already
            # reached, so numbering resumes above it
            self._state_sync()
        else:
            # initial pull: adopt the master's authoritative parameters
            # (hvd.broadcast_parameters / DDP-wrap analogue for the PS
            # world)
            self._adopt(
                self._exchange(self._pull_params, what="initial pull")
            )

    def _pull_params(self):
        protocol.send_request(self.comm, protocol.OP_PULL)  # protocol: ps request PULL
        return protocol.recv_params(self.comm, self.num_params)

    def _state_sync(self):
        """REGISTER -> STATE_SYNC: adopt the master's params, update
        count and this worker's push-seq watermark; position the epoch
        cursor so training resumes where this worker-id's stream left
        off instead of re-pushing every epoch from scratch."""

        def register():
            # protocol: ps request REGISTER
            protocol.send_request(
                self.comm, protocol.OP_REGISTER, seq=self.worker_id
            )
            # protocol: ps handles STATE_SYNC
            return protocol.recv_state_sync(self.comm, self.num_params)

        t0 = time.perf_counter()
        flat, step_wm, seq_wm = self._exchange(register, what="register")
        self._adopt(flat)
        self._push_seq = int(seq_wm)
        # epoch-granularity resume off the push watermark: the seq IS
        # this worker's own step count, so floor-divide by its steps per
        # epoch (re-pushing the dead incarnation's partial epoch is the
        # price of epoch-granularity restart - those gradients average
        # into live rounds like any straggler's)
        steps_per_epoch = max(
            1, math.ceil(len(self.sampler) / self.batch_size)
        )
        self._start_epoch = int(seq_wm) // steps_per_epoch
        log.info(
            f"state sync: worker-id {self.worker_id} rejoined at master "
            f"update {step_wm}, push-seq watermark {seq_wm} -> resuming "
            f"at epoch {self._start_epoch}"
        )
        if self.recorder.enabled:
            self.recorder.emit_span(
                "state_sync", t0, time.perf_counter() - t0, cat="member",
                worker_id=self.worker_id, rank_slot=self.worker_rank,
                step=int(step_wm), seq=int(seq_wm),
                resume_epoch=self._start_epoch,
            )

    def _exchange(self, fn, what: str, seq: int | None = None):
        """One protocol exchange under the retry policy.  An exchange is
        retried WHOLE (request + reply); safe for pushes because the
        header's per-step sequence number lets the master detect a
        duplicate (original applied, reply leg lost) and resend params
        without averaging the gradient in twice.

        Telemetry: each exchange records latency + retry count as a
        ``ps_exchange`` event (the wire half of a PS step the in-program
        collective counters can never see).  ``seq`` - the wire push
        sequence - rides the event so a push correlates with the
        master's round of the same ordinal (the step+round correlation
        the trace timeline and its clock alignment key off)."""
        recording = self.recorder.enabled
        retries = [0]

        def on_retry(attempt, exc):
            retries[0] = attempt

        t0 = time.perf_counter() if recording else 0.0
        try:
            result = retry_transport(
                fn, retries=self._transport_retries, seed=self.worker_rank,
                what=f"{what} (worker {self.worker_rank})",
                on_retry=on_retry if recording else None,
                deadline_s=self._transport_deadline,
            )
        except Exception:
            if recording:
                self.recorder.record(
                    "ps_exchange", what=what, step=self._steps_done,
                    seq=seq, seconds=time.perf_counter() - t0,
                    retries=retries[0], failed=True,
                )
                self.recorder.flush()  # the run is about to die with this
            raise
        if recording:
            self.recorder.record(
                "ps_exchange", what=what, step=self._steps_done, seq=seq,
                seconds=time.perf_counter() - t0, retries=retries[0],
            )
        return result

    def _adopt(self, flat_params: np.ndarray):
        assert flat_params.size == self.num_params, "parameter size mismatch"
        self.params = self._unravel(jax.numpy.asarray(flat_params))

    def _get_formatter(self, epochs):
        return TrainingMessageFormatter(epochs, self.worker_rank)

    def _fold_rank(self, key):
        # each PS worker draws its own dropout mask (folded by the
        # stable id, so a respawn redraws ITS stream, not a neighbor's)
        return jax.random.fold_in(key, self.worker_id)

    def _build_train_step(self):
        """Local fused forward+backward; the update is remote."""
        grad_fn = jax.jit(
            jax.value_and_grad(self._loss_and_metrics, has_aux=True)
        )

        def push_pull(flat_grads, seq):
            # protocol: ps request PUSH
            protocol.send_request(
                self.comm, protocol.OP_PUSH, grads=flat_grads, seq=seq
            )
            return protocol.recv_params(self.comm, self.num_params)

        def step(params, opt_state, batch, *extra):
            (loss, metrics), grads = grad_fn(params, batch, *extra)
            flat_grads, _ = ravel_pytree(grads)
            flat_grads = np.asarray(flat_grads)
            self._push_seq += 1  # once per STEP; retries re-send the same
            seq = self._push_seq
            new_flat = self._exchange(
                lambda: push_pull(flat_grads, seq), what="gradient push",
                seq=seq,
            )
            self._adopt(new_flat)
            if self._drain is not None:
                # the step's exchange is complete (gradient flushed,
                # params adopted): a pending SIGTERM drain is honored
                # HERE, so the last push is applied exactly once and
                # nothing is torn mid-protocol
                self._drain.check()
            return self.params, opt_state, loss, metrics

        return step

    def finish(self):
        protocol.send_request(self.comm, protocol.OP_DONE)  # protocol: ps request DONE

    def deregister(self):
        """Voluntary leave (the drain path): tell the master this worker
        is exiting on purpose - the roster shrinks without burning the
        quorum budget - and record the drain on this rank's sidecar so
        ``pdrnn-metrics health`` classifies it drained, not dead."""
        # protocol: ps request DEREGISTER
        protocol.send_request(
            self.comm, protocol.OP_DEREGISTER, seq=self._push_seq
        )
        log.info(
            f"worker-id {self.worker_id} (rank {self.worker_rank}) "
            f"deregistered after push seq {self._push_seq}"
        )
        if self.recorder.enabled:
            self.recorder.record(
                "member_drain", worker_id=self.worker_id,
                rank_slot=self.worker_rank, seq=self._push_seq,
            )
            self.recorder.flush()
