"""TPU-native distributed RNN training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
project ``jkhlr/pytorch-distributed-rnn`` (a PyTorch/MPI/Horovod/RPC
data-parallel RNN trainer for a Raspberry-Pi cluster; see
``/root/reference/src/motion/main.py:16``):

Subpackages (``models``, ``ops``, ``parallel``, ``data``, ``training``,
``runtime``, ``utils``) each carry their own docstring describing the
reference capability they re-implement and the TPU-native design chosen.
"""

__version__ = "0.1.0"

# Version-compatibility shims (jax 0.4.x spellings of the >=0.9 API the
# framework is written against) apply on any package import.  Guarded:
# jax-free tools in the package (the AST linter) stay importable in
# lint-only environments.
try:
    from pytorch_distributed_rnn_tpu.utils import compat as _compat  # noqa: F401
except ImportError:  # pragma: no cover - jax-less lint environment
    pass
