"""MoE loss adapter: the ``--model moe`` family on the shared loop.

Mirrors :mod:`pytorch_distributed_rnn_tpu.training.lm`: the shared loop and
every strategy consume ``_loss_and_metrics(params, (x, y), key)``
(``training/base.py``); the MoE family differs only by adding the Switch
load-balancing auxiliary loss to the classification objective, so this
mixin swaps exactly that surface.  Train AND eval report CE +
aux_weight * aux (one objective, comparable across epochs); accuracy
bookkeeping is unchanged.
"""

from __future__ import annotations

import jax.numpy as jnp

from pytorch_distributed_rnn_tpu.ops.losses import cross_entropy_loss


class MoELossMixin:
    """Overrides the two loss surfaces to include the Switch aux loss
    (dense-exact forward; the mesh strategy overrides the train steps with
    the expert-parallel program and uses this only for evaluation)."""

    def _moe_logits_aux(self, params, x, key):
        # _apply_model supplies shared dropout-key gating; the family has
        # no dropout, so route directly through apply_with_aux
        return self.model.apply_with_aux(params, x, key)

    def _loss_and_metrics(self, params, batch, key=None):
        x, y = batch
        logits, aux = self._moe_logits_aux(params, x, key)
        loss = cross_entropy_loss(logits, y) + self.model.aux_weight * aux
        correct = jnp.sum(jnp.argmax(logits, axis=1) == y)
        return loss, {"correct": correct}

    def _weighted_loss_and_metrics(self, params, batch, w, key=None):
        """0/1-weighted variant (fused-run padding mask).  The aux loss is
        computed over ALL rows including padded ones - padding rows are
        real (repeated) examples, so the router statistics stay
        well-defined; with all-ones weights this equals the plain loss
        exactly."""
        x, y = batch
        logits, aux = self._moe_logits_aux(params, x, key)
        nll = cross_entropy_loss(logits, y, reduction="none")
        loss = (
            jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)
            + self.model.aux_weight * aux
        )
        correct = jnp.sum((jnp.argmax(logits, axis=1) == y) * (w > 0))
        return loss, {"correct": correct}


_WRAPPED: dict = {}


def wrap_moe_trainer(trainer_class):
    """The trainer class with MoE losses mixed in (cached per base)."""
    cls = _WRAPPED.get(trainer_class)
    if cls is None:
        cls = type(
            f"MoE{trainer_class.__name__}", (MoELossMixin, trainer_class), {}
        )
        _WRAPPED[trainer_class] = cls
    return cls


# ---------------------------------------------------------------------------
# pdrnn-lint --deep trace registry (lint/trace_registry.py)


def declare_trace_entries(register):
    """Register the MoE mesh step (dp x ep: batch over both axes, experts
    over ep, router f32 by contract even under bf16 compute)."""

    def build():
        import jax
        import optax

        from pytorch_distributed_rnn_tpu.lint.trace_registry import (
            abstract_init,
            lint_mesh,
            prng_spec,
            sds,
        )
        from pytorch_distributed_rnn_tpu.models import MoEClassifier
        from pytorch_distributed_rnn_tpu.parallel.strategy import (
            make_mesh_grad_step,
            make_moe_mesh_loss_fn,
        )

        mesh = lint_mesh({"dp": 2, "ep": 2})
        model = MoEClassifier(input_dim=9, hidden_dim=8, layer_dim=1,
                              output_dim=6, num_experts=4,
                              expert_hidden=16)
        params = abstract_init(model.init, prng_spec())
        optimizer = optax.adam(1e-3)
        opt_state = abstract_init(optimizer.init, params)
        step = make_mesh_grad_step(
            make_moe_mesh_loss_fn(model, mesh), optimizer
        )
        batch = (sds((8, 12, 9), jax.numpy.float32),
                 sds((8,), jax.numpy.int32))
        jitted = jax.jit(step, donate_argnums=(0, 1))
        return jitted, (params, opt_state, batch)

    register(
        name="moe.mesh_train_step", family="moe",
        path="pytorch_distributed_rnn_tpu/training/moe.py",
        build=build, mesh_axes={"dp": 2, "ep": 2}, data_axis="dp",
        donate=(0, 1),
    )
