"""Checkpoint save/load for params + optimizer state pytrees.

Capability parity with the reference's ``_save_checkpoint``
(``/root/reference/src/motion/trainer/base.py:164-177``): a checkpoint
bundles ``{epoch, model_state, optimizer_state, loss}``, written as
``best-model.ckpt`` on a new best validation loss or
``checkpoint-epoch-N.ckpt`` otherwise.

New capability the reference lacks (its checkpoints are write-only,
SURVEY §5): ``load_checkpoint`` restores params/optimizer state into
templates so training can RESUME.

Format: one binary file - a JSON header line with metadata and section
lengths, followed by two flax-msgpack sections (model state, optimizer
state).  Portable and pickle-free.
"""

from __future__ import annotations

import json
from pathlib import Path

import jax
import numpy as np
from flax import serialization


def _to_host(tree):
    return jax.tree.map(np.asarray, tree)


def save_checkpoint(
    checkpoint_dir, epoch: int, params, opt_state, loss: float, best: bool = False
) -> Path:
    """Write a checkpoint; returns the path."""
    checkpoint_dir = Path(checkpoint_dir)
    checkpoint_dir.mkdir(parents=True, exist_ok=True)
    name = "best-model.ckpt" if best else f"checkpoint-epoch-{epoch + 1}.ckpt"
    path = checkpoint_dir / name

    model_bytes = serialization.to_bytes(_to_host(params))
    opt_bytes = serialization.to_bytes(_to_host(opt_state))
    header = json.dumps(
        {
            "epoch": epoch + 1,
            "loss": float(loss),
            "model_len": len(model_bytes),
            "opt_len": len(opt_bytes),
        }
    ).encode()
    with open(path, "wb") as f:
        f.write(header + b"\n")
        f.write(model_bytes)
        f.write(opt_bytes)
    return path


def load_checkpoint(path, params_template, opt_state_template):
    """Restore ``(params, opt_state, meta)`` from ``path``.

    Templates supply the pytree structure (the trainer's freshly
    initialized params/optimizer state).
    """
    with open(path, "rb") as f:
        header = json.loads(f.readline().decode())
        model_bytes = f.read(header["model_len"])
        opt_bytes = f.read(header["opt_len"])
    params = serialization.from_bytes(params_template, model_bytes)
    opt_state = serialization.from_bytes(opt_state_template, opt_bytes)
    return params, opt_state, {"epoch": header["epoch"], "loss": header["loss"]}
