"""Checkpoint save/load for params + optimizer state pytrees.

Capability parity with the reference's ``_save_checkpoint``
(``/root/reference/src/motion/trainer/base.py:164-177``): a checkpoint
bundles ``{epoch, model_state, optimizer_state, loss}``, written as
``best-model.ckpt`` on a new best validation loss or
``checkpoint-epoch-N.ckpt`` otherwise.

New capabilities the reference lacks (its checkpoints are write-only,
SURVEY §5): ``load_checkpoint`` restores params/optimizer state into
templates so training can RESUME, and the write path is CRASH-SAFE - a
process killed mid-write (the ``resilience/faults.py`` preemption model)
can never leave a half-written file under the checkpoint name:

- writes go to a temp file, ``fsync``, then atomic ``os.replace``;
- the header carries a CRC32 per section, verified on load;
- ``load_checkpoint`` rejects truncated/corrupt files with
  :class:`CheckpointCorruptError` so auto-resume
  (``resilience/guard.py``) falls back to the previous valid file;
- ``rotate_checkpoints`` bounds disk growth (``--keep-checkpoints N``).

Format: one binary file - a JSON header line with metadata, section
lengths and CRCs, followed by two flax-msgpack sections (model state,
optimizer state).  Portable and pickle-free.  Pre-CRC files (no ``crcs``
header field) still load; lengths are validated either way.
"""

from __future__ import annotations

import json
import logging
import os
import re
import zlib
from pathlib import Path

import jax
import numpy as np
from flax import serialization

log = logging.getLogger(__name__)

_EPOCH_CKPT_RE = re.compile(r"^checkpoint-epoch-(\d+)\.ckpt$")


class CheckpointCorruptError(RuntimeError):
    """The file is truncated, unparseable, or fails CRC verification."""


def _to_host(tree):
    return jax.tree.map(np.asarray, tree)


def save_checkpoint(
    checkpoint_dir, epoch: int, params, opt_state, loss: float,
    best: bool = False, extra: dict | None = None,
) -> Path:
    """Write a checkpoint atomically; returns the path.

    ``extra`` is an optional JSON-serializable dict stored in the header
    line - state that must be crash-consistent WITH the params/optimizer
    sections (the streaming learner's params version and per-actor
    push-seq watermarks: persisting them in a second file would open a
    window where a crash leaves new params with stale watermarks, and a
    restarted learner would re-apply experience it already trained on).
    """
    checkpoint_dir = Path(checkpoint_dir)
    checkpoint_dir.mkdir(parents=True, exist_ok=True)
    name = "best-model.ckpt" if best else f"checkpoint-epoch-{epoch + 1}.ckpt"
    path = checkpoint_dir / name

    model_bytes = serialization.to_bytes(_to_host(params))
    opt_bytes = serialization.to_bytes(_to_host(opt_state))
    header_fields = {
        "epoch": epoch + 1,
        "loss": float(loss),
        "model_len": len(model_bytes),
        "opt_len": len(opt_bytes),
        "crcs": {
            "model": zlib.crc32(model_bytes),
            "opt": zlib.crc32(opt_bytes),
        },
    }
    if extra is not None:
        header_fields["extra"] = extra
    header = json.dumps(header_fields).encode()
    # temp-write + fsync + atomic rename: a crash at ANY point leaves
    # either the previous complete file or no file - never a truncated
    # one under the checkpoint name.  pid-suffixed temp so concurrent
    # writers (multi-process strategies misconfigured to all write)
    # cannot interleave into one temp file.
    tmp = path.with_name(path.name + f".tmp.{os.getpid()}")
    try:
        with open(tmp, "wb") as f:
            f.write(header + b"\n")
            f.write(model_bytes)
            f.write(opt_bytes)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if tmp.exists():  # replace failed or write raised
            tmp.unlink()
    # fsync the directory so the rename itself is durable (best-effort:
    # not every filesystem supports directory fds)
    try:
        dir_fd = os.open(checkpoint_dir, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
    except OSError:  # pragma: no cover - platform-dependent
        pass
    return path


def _read_sections(path):
    """Parse ``(header, model_bytes, opt_bytes)`` off ``path``, raising
    :class:`CheckpointCorruptError` on any structural damage."""
    try:
        with open(path, "rb") as f:
            header_line = f.readline()
            try:
                header = json.loads(header_line.decode())
            except (UnicodeDecodeError, json.JSONDecodeError) as exc:
                raise CheckpointCorruptError(
                    f"{path}: unparseable header ({exc})"
                ) from exc
            try:
                model_len = int(header["model_len"])
                opt_len = int(header["opt_len"])
            except (KeyError, TypeError, ValueError) as exc:
                raise CheckpointCorruptError(
                    f"{path}: header missing section lengths ({exc})"
                ) from exc
            model_bytes = f.read(model_len)
            opt_bytes = f.read(opt_len)
            trailing = f.read(1)
    except OSError as exc:
        raise CheckpointCorruptError(f"{path}: unreadable ({exc})") from exc
    # a short read deserializes garbage (the historical truncation bug:
    # f.read(n) returns what is there, not n bytes) - validate lengths
    if len(model_bytes) != model_len or len(opt_bytes) != opt_len:
        raise CheckpointCorruptError(
            f"{path}: truncated - expected {model_len}+{opt_len} section "
            f"bytes, found {len(model_bytes)}+{len(opt_bytes)}"
        )
    if trailing:
        raise CheckpointCorruptError(
            f"{path}: trailing bytes past the declared sections"
        )
    crcs = header.get("crcs")
    if crcs is not None:  # pre-CRC files load on lengths alone
        for name, blob in (("model", model_bytes), ("opt", opt_bytes)):
            if zlib.crc32(blob) != crcs.get(name):
                raise CheckpointCorruptError(
                    f"{path}: {name} section CRC mismatch (bit rot or "
                    "partial overwrite)"
                )
    return header, model_bytes, opt_bytes


def verify_checkpoint(path) -> dict:
    """Structural verification without deserializing: header, section
    lengths, CRCs.  Returns the header; raises
    :class:`CheckpointCorruptError`."""
    header, _, _ = _read_sections(path)
    return header


def load_checkpoint(path, params_template, opt_state_template):
    """Restore ``(params, opt_state, meta)`` from ``path``.

    Templates supply the pytree structure (the trainer's freshly
    initialized params/optimizer state).  Raises
    :class:`CheckpointCorruptError` for truncated/corrupt files so
    callers (auto-resume) can fall back to an earlier checkpoint instead
    of deserializing garbage.
    """
    header, model_bytes, opt_bytes = _read_sections(path)
    try:
        params = serialization.from_bytes(params_template, model_bytes)
        opt_state = serialization.from_bytes(opt_state_template, opt_bytes)
    except Exception as exc:
        # CRC-valid bytes that still do not deserialize = a checkpoint
        # from a different model/optimizer shape; say which file
        raise CheckpointCorruptError(
            f"{path}: sections verified but failed to deserialize into "
            f"the trainer's state templates ({exc})"
        ) from exc
    meta = {"epoch": header["epoch"], "loss": header["loss"]}
    if "extra" in header:
        meta["extra"] = header["extra"]
    return params, opt_state, meta


def load_model_params(path, params_template):
    """Restore ``(params, meta)`` from ``path`` without touching the
    optimizer section.

    The serving path (``serving/``): an inference server has no
    optimizer, and demanding the training-time ``opt_state`` template
    just to skip those bytes would couple serving to every trainer's
    optimizer choice.  Sections are still length+CRC verified as a
    whole, so a corrupt optimizer section fails the load even though
    its bytes are never deserialized - a checkpoint is either intact or
    rejected, never half-trusted.
    """
    header, model_bytes, _ = _read_sections(path)
    try:
        params = serialization.from_bytes(params_template, model_bytes)
    except Exception as exc:
        raise CheckpointCorruptError(
            f"{path}: model section verified but failed to deserialize "
            f"into the given params template ({exc})"
        ) from exc
    return params, {"epoch": header["epoch"], "loss": header["loss"]}


def checkpoint_candidates(checkpoint_dir) -> list[Path]:
    """Resume candidates under ``checkpoint_dir``, newest-first.

    Epoch checkpoints ordered by their filename epoch (descending);
    ``best-model.ckpt`` is appended LAST - it is the best-validation
    state, not the furthest progress, so plain epoch recency wins for
    resume and best-model remains the final fallback.
    """
    checkpoint_dir = Path(checkpoint_dir)
    if not checkpoint_dir.is_dir():
        return []
    epochs = []
    for entry in checkpoint_dir.iterdir():
        m = _EPOCH_CKPT_RE.match(entry.name)
        if m:
            epochs.append((int(m.group(1)), entry))
    out = [p for _, p in sorted(epochs, key=lambda t: t[0], reverse=True)]
    best = checkpoint_dir / "best-model.ckpt"
    if best.exists():
        out.append(best)
    return out


def find_latest_checkpoint(checkpoint_dir) -> Path | None:
    """The newest checkpoint that passes structural verification, or
    ``None`` - corrupt/truncated files are skipped (and logged), which
    is what makes crash-time resume safe: the file being written when
    the process died never wins."""
    for path in checkpoint_candidates(checkpoint_dir):
        try:
            verify_checkpoint(path)
        except CheckpointCorruptError as exc:
            log.warning(f"find_latest_checkpoint: skipping {path}: {exc}")
            continue
        return path
    return None


def rotate_checkpoints(checkpoint_dir, keep_last: int) -> list[Path]:
    """Delete all but the newest ``keep_last`` epoch checkpoints
    (``best-model.ckpt`` is never rotated).  Returns the deleted paths.
    ``keep_last <= 0`` keeps everything."""
    if keep_last <= 0:
        return []
    epoch_ckpts = [
        p for p in checkpoint_candidates(checkpoint_dir)
        if _EPOCH_CKPT_RE.match(p.name)
    ]
    deleted = []
    for path in epoch_ckpts[keep_last:]:
        try:
            path.unlink()
            deleted.append(path)
        except OSError as exc:  # pragma: no cover - racing cleanup is fine
            log.warning(f"rotate_checkpoints: could not delete {path}: {exc}")
    return deleted
