"""Log-message formatter with the reference's machine-readable contracts.

The perf line format is a hard compatibility requirement: the reference's
evaluation notebooks regex-parse
``'{rank}: Memory Usage: {m}, Training Duration: {d}'`` out of captured
stderr (``/root/reference/src/motion/trainer/formatter.py:27``,
``evaluation/Experiments.ipynb`` cell 2), and the launcher archives that
stderr into results JSONs.  The other message shapes mirror
``formatter.py:6-24`` so human-readable logs stay comparable.
"""

from __future__ import annotations


def _pct(current, overall) -> float:
    return 100.0 * (current / overall)


class TrainingMessageFormatter:
    def __init__(self, num_epochs: int, rank: int = 0):
        self.num_epochs = num_epochs
        self.rank = rank

    def epoch_start_message(self, epoch: int) -> str:
        return f"Rank: {self.rank:02d}   Start Epoch {epoch}"

    def train_progress_message(
        self, batch_idx, batches, training_examples, correct, loss
    ) -> str:
        batch_idx += 1
        return (
            f"Rank: {self.rank:02d}   "
            f"Train Batch: {batch_idx}/{batches} ({_pct(batch_idx, batches):.0f}%)\t"
            f"Loss: {loss:.6f}\t"
            f"Acc: {correct}/{training_examples} "
            f"({_pct(float(correct), training_examples):.0f}%)"
        )

    def evaluation_message(
        self, accuracy, examples, epoch, eval_loss, total_correct
    ) -> str:
        metrics = (
            f"Loss: {eval_loss:.4f}\t "
            f"Accuracy: {total_correct}/{examples} ({100.0 * accuracy:.0f}%)\n"
        )
        if epoch is None:
            prefix = "Test Evaluation:\t"
        else:
            epoch += 1
            prefix = (
                f"Evaluation Epoch: {epoch}/{self.num_epochs} "
                f"({_pct(epoch, self.num_epochs):.0f}%)\t"
            )
        return prefix + metrics

    def performance_message(self, memory, duration) -> str:
        # Parsed downstream by evaluation/analysis.py PERF_LINE_RE - keep
        # byte-compatible.  The values are RAW floats (str() formatting),
        # so the parser accepts scientific ('5e-05') and integer-valued
        # ('700') renderings too; the round-trip is property-tested in
        # tests/test_evaluation.py.
        return f"{self.rank}: Memory Usage: {memory}, Training Duration: {duration}"
