"""Sharded checkpointing: each process writes only the shards it owns.

The reference's checkpoints are ``torch.save`` pickles of a full replica
(``/root/reference/src/motion/trainer/base.py:164-177``); the gathered
format (``training/checkpoint.py``) reproduces that contract byte-for-
byte-portably.  This module is the scale path the gathered format cannot
take: a ZeRO/FSDP-sharded model is sharded precisely because ONE replica
does not comfortably exist, yet ``ZeroTrainer._checkpoint_state`` must
all-gather exactly such a replica before rank 0 can write it.  Here the
state tree goes to orbax/tensorstore as-is: every array is written
shard-by-shard by the devices that own it (multi-controller worlds
coordinate through the jax.distributed client orbax picks up), and
restore places each shard directly onto its target device from the
template's sharding - the full model never materializes in any single
host's memory in either direction.

Async mode hands the device arrays to orbax's background thread and
returns to the training loop immediately (the copy to host overlaps the
next epochs' compute); the trainer waits on the previous save before
starting the next one, and drains at train end.

Layout on disk: ``<dir>/<name>.orbax/`` (an orbax StandardSave tree of
``{"params": ..., "opt_state": ...}``) plus ``<dir>/<name>.meta.json``
carrying ``{epoch, loss}`` - sibling file, not inside the orbax dir,
because orbax finalizes its directory atomically.  Names mirror the
gathered format: ``best-model`` / ``checkpoint-epoch-N``.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import jax


def _checkpointer(async_: bool):
    import orbax.checkpoint as ocp

    handler = ocp.StandardCheckpointHandler()
    return (ocp.AsyncCheckpointer(handler) if async_
            else ocp.Checkpointer(handler))


def checkpoint_name(epoch: int, best: bool) -> str:
    return "best-model" if best else f"checkpoint-epoch-{epoch + 1}"


def _meta_path(orbax_path: Path) -> Path:
    """The meta sidecar for a ``<name>.orbax`` dir - one formula shared
    by save, wait and restore so the three can never target different
    files."""
    return orbax_path.parent / (
        orbax_path.name[:-len(".orbax")] + ".meta.json")


class ShardedCheckpointHandle:
    """A possibly-in-flight sharded save.  ``wait()`` blocks until the
    write is durable; idempotent."""

    def __init__(self, checkpointer, path: Path, meta: dict):
        self._checkpointer = checkpointer
        self.path = path
        self._meta = meta

    def wait(self):
        if self._checkpointer is None:
            return
        # sync Checkpointer has no wait_until_finished (save already
        # returned durable); AsyncCheckpointer does
        wait = getattr(self._checkpointer, "wait_until_finished", None)
        if wait is not None:
            wait()
        self._checkpointer.close()
        self._checkpointer = None
        # the meta sidecar is written only AFTER the orbax write is
        # durable: writing it at submit time would let a crash mid-
        # background-write (or an in-flight best-model overwrite) leave
        # meta describing state the .orbax dir does not hold
        if jax.process_index() == 0:
            # temp-file + rename: a crash mid-write must leave either no
            # sidecar or a complete one, never a truncated JSON that
            # blocks restore of the (durable) .orbax next to it
            meta_path = _meta_path(self.path)
            tmp = meta_path.with_suffix(".json.tmp")
            with open(tmp, "w") as f:
                json.dump(self._meta, f)
            os.replace(tmp, meta_path)

    @property
    def in_flight(self) -> bool:
        return self._checkpointer is not None


def save_sharded(checkpoint_dir, epoch: int, params, opt_state,
                 loss: float, *, best: bool = False,
                 async_: bool = False) -> ShardedCheckpointHandle:
    """Write ``{params, opt_state}`` sharded; returns a handle.

    Synchronous unless ``async_``; an async save's handle MUST be
    ``wait()``-ed before the process exits (the trainer drains it).
    Every process of a multi-controller world must call this - the
    shard writes and the final directory rename are coordinated.
    """
    checkpoint_dir = Path(checkpoint_dir).resolve()  # orbax wants absolute
    checkpoint_dir.mkdir(parents=True, exist_ok=True)
    name = checkpoint_name(epoch, best)
    path = checkpoint_dir / f"{name}.orbax"
    import orbax.checkpoint as ocp

    checkpointer = _checkpointer(async_)
    checkpointer.save(
        path,
        args=ocp.args.StandardSave({"params": params,
                                    "opt_state": opt_state}),
        force=True,  # overwrite: best-model is rewritten on every new best
    )
    # an overwriting save removes the previous .orbax dir at submit time
    # (synchronously, inside save) while the NEW write may still be in a
    # background thread: the old meta sidecar must not outlive the
    # checkpoint it describes, or a crash mid-background-write leaves
    # meta lying about a missing .orbax.  Unlinked only after save()
    # returns, so a submit-time failure leaves the old checkpoint AND
    # its meta fully intact.
    if jax.process_index() == 0:
        _meta_path(path).unlink(missing_ok=True)
    handle = ShardedCheckpointHandle(
        checkpointer, path, {"epoch": epoch + 1, "loss": float(loss)})
    if not async_:
        handle.wait()
    return handle


def is_sharded_checkpoint(path) -> bool:
    """A sharded checkpoint is a ``.orbax``-suffixed DIRECTORY (the
    gathered format is a single file).  The suffix requirement keeps an
    accidental ``--resume <checkpoint parent dir>`` from dispatching
    into orbax and dying with an opaque tensorstore error."""
    path = Path(path)
    return path.is_dir() and path.name.endswith(".orbax")


def restore_sharded(path, params_template, opt_state_template):
    """Restore ``(params, opt_state, meta)`` from a ``.orbax`` dir.

    Templates are the trainer's LIVE state: their shapes/dtypes validate
    the tree and their shardings tell orbax where each restored shard
    belongs, so a ZeRO-laid-out trainer gets its layout back without a
    gather or a host-side replica.
    """
    path = Path(path).resolve()
    if not is_sharded_checkpoint(path):
        raise ValueError(
            f"{path} is not a sharded checkpoint (expected an existing "
            ".orbax directory, e.g. models/checkpoint-epoch-3.orbax)"
        )
    import orbax.checkpoint as ocp

    def _abstract(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=x.sharding)
        return x  # non-array leaves (ints in optax state) restore as-is

    abstract = jax.tree.map(
        _abstract, {"params": params_template,
                    "opt_state": opt_state_template})
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as checkpointer:
        restored = checkpointer.restore(
            path, args=ocp.args.StandardRestore(abstract))

    meta = {"epoch": 0, "loss": float("inf")}
    try:
        with open(_meta_path(path)) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError):
        # meta is auxiliary: a missing sidecar (never written, or just
        # unlinked by a concurrent overwriting save), a corrupt one
        # (pre-atomic-write truncation), or any other read failure must
        # not block restore of the durable .orbax next to it
        pass
    return restored["params"], restored["opt_state"], meta
