"""``mesh`` strategy: train over a composed device mesh from the CLI.

Promotes the TP/SP/PP library axes (``parallel/{tp,sp,pp}.py``) into a
first-class *training strategy* behind the reference's inversion (strategy
= CLI subcommand on one shared loop, ``/root/reference/src/motion/trainer/
__init__.py:10-18``):

    python -m pytorch_distributed_rnn_tpu.main ... mesh --mesh dp=2,sp=4

The epoch/eval/checkpoint loop is untouched ``Trainer`` machinery; only the
train-step builders change - they differentiate a shard_mapped
replicated-scalar loss (grad OUTSIDE the shard_map, the
``parallel/combined.py`` pattern) whose body runs the stacked LSTM with the
requested axis: time-sharded wavefront relay (sp), Megatron gate/head
sharding (tp), or a GPipe stage schedule (pp).  Batch rows shard over
``dp`` exactly like the DDP strategies; evaluation uses the plain
single-device forward (identical numerics).
"""

from __future__ import annotations

import jax
import numpy as np

from pytorch_distributed_rnn_tpu.parallel.mesh import make_mesh
from pytorch_distributed_rnn_tpu.parallel.strategy import (
    make_mesh_grad_step,
    make_motion_mesh_loss_fn,
    parse_mesh_spec,
    validate_rnn_mesh,
)
from pytorch_distributed_rnn_tpu.training.distributed import SpmdTrainer


class MeshTrainer(SpmdTrainer):
    """Composed-mesh training strategy for the motion model."""

    # composed meshes mix model axes into the update (TP/SP/PP/EP
    # layouts shard parameters themselves); the pure-DP flat-ravel
    # sharded update does not apply, so --sharded-update is inert
    SUPPORTS_SHARDED_UPDATE = False

    def __init__(self, *, mesh_axes, schedule: str = "wavefront",
                 num_microbatches: int = 4, pp_schedule: str = "gpipe",
                 pp_chunks: int = 2, **kwargs):
        if pp_schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"unknown pp schedule {pp_schedule!r} - use gpipe, 1f1b "
                "or interleaved"
            )
        if pp_schedule == "interleaved" and pp_chunks < 2:
            raise ValueError(
                f"--pp-schedule interleaved needs --pp-chunks >= 2 "
                f"(got {pp_chunks}); V=1 IS the 1f1b schedule"
            )
        self.pp_schedule = pp_schedule
        # V virtual chunks per device only under the interleaved
        # schedule; the flat engines take num_chunks=1
        self.pp_chunks = pp_chunks if pp_schedule == "interleaved" else 1
        axes = dict(mesh_axes)
        if "dp" not in axes:
            axes = {"dp": 1, **axes}
        model = kwargs["model"]
        # the attention family composes the FULL dp x sp x tp mesh (ring
        # attention over sp, Megatron sharding over tp); RNN cells (motion
        # classifier and char-LM alike) take dp plus at most one model
        # axis; the MoE family takes dp x ep (experts sharded over ep)
        self.is_attention = hasattr(model, "num_heads")
        self.is_char = hasattr(model, "vocab_size")
        self.is_moe = hasattr(model, "num_experts")
        # `!= 1`, not `> 1`: a -1 ("all remaining devices") size must hit
        # these rejects too, not silently resolve into ghost replication
        if not self.is_moe and axes.get("ep", 1) != 1:
            raise ValueError(
                "the ep axis shards MoE experts - it applies to "
                "--model moe only (parallel/ep.py)"
            )
        if self.is_moe:
            bad = [a for a in ("sp", "tp", "pp") if axes.get(a, 1) != 1]
            if bad:
                raise ValueError(
                    f"--model moe composes dp x ep only; got {bad} "
                    "(the attention family covers sp/tp composition)"
                )
            axes = {"dp": axes.get("dp", 1), "ep": axes.get("ep", 1)}
            self.model_axis = None
        elif self.is_attention:
            # `!= 1`, not `> 1`: pp=-1 ("all remaining devices") must
            # enter this branch too, not silently drop to plain DDP
            if axes.get("pp", 1) != 1:
                # GPipe over encoder blocks (parallel/pp.py), optionally
                # with Megatron tp INSIDE each stage (r4); pp does not
                # compose with sp in one program - reject loudly rather
                # than silently dropping an axis
                if axes.get("sp", 1) != 1:
                    raise ValueError(
                        "attention pp does not compose with sp - use "
                        "dp x pp (x tp) (e.g. --mesh dp=2,pp=2,tp=2) or "
                        "the dp x sp x tp composition"
                    )
                # depth % pp is checked AFTER make_mesh resolves pp=-1
                # (below) - depth % -1 would vacuously pass here
                axes = {"dp": axes.get("dp", 1), "pp": axes["pp"],
                        "tp": axes.get("tp", 1)}
            else:
                axes.pop("pp", None)
                # every axis name must exist in the mesh for the composed
                # program; unused axes get size 1
                axes = {"dp": axes.get("dp", 1), "sp": axes.get("sp", 1),
                        "tp": axes.get("tp", 1)}
            self.model_axis = None
        else:
            # the char family additionally composes sp x tp (gate-sharded
            # cell inside the sp relay) -> model_axis "sp+tp"
            self.model_axis = validate_rnn_mesh(
                axes, getattr(model, "cell", "lstm"),
                allow_sp_tp=self.is_char,
            )
        self.mesh_axes = axes
        self.schedule = schedule
        self.num_microbatches = num_microbatches
        mesh = make_mesh(axes)
        # resolve -1 ("all remaining devices") to the actual size
        self.mesh_axes = {name: mesh.shape[name] for name in axes}
        if self.is_moe and model.num_experts % self.mesh_axes["ep"]:
            # after -1 resolution, so `ep=-1` fails here too, at
            # construction rather than inside the first jitted step
            raise ValueError(
                f"--num-experts {model.num_experts} does not shard over "
                f"ep={self.mesh_axes['ep']}"
            )
        if self.is_attention and "pp" in self.mesh_axes:
            # after -1 resolution: a pp=-1 that resolved to 1 would keep
            # {dp, pp} axes while _loss_fn (gated on pp > 1) routed to the
            # sp/tp loss builder and failed with a misdirected "needs axis
            # 'sp'" error - reject the degenerate request here instead
            if self.mesh_axes["pp"] == 1:
                raise ValueError(
                    "pp resolved to 1 stage (pp=-1 with no devices left "
                    "over) - drop the pp axis or leave >=2 devices for it"
                )
            if model.depth % self.mesh_axes["pp"]:
                raise ValueError(
                    f"--stacked-layer {model.depth} blocks do not split "
                    f"into pp={self.mesh_axes['pp']} stages"
                )
            tp_size = self.mesh_axes.get("tp", 1)
            if tp_size > 1 and model.num_heads % tp_size:
                raise ValueError(
                    f"--num-heads {model.num_heads} does not shard over "
                    f"tp={tp_size} (pp x tp composition)"
                )
        super().__init__(mesh=mesh, axis="dp", **kwargs)
        if self.is_char and self.model_axis in ("sp", "sp+tp"):
            window = self.training_set.features.shape[1]
            sp_size = self.mesh_axes["sp"]
            if window % sp_size:
                raise ValueError(
                    f"char-LM window ({window} = seq_length + 1) not "
                    f"divisible by sp={sp_size} - pick --seq-length so "
                    f"that sp divides seq_length + 1"
                )
        if self.pp_schedule in ("1f1b", "interleaved") and (
            self.is_attention or self.is_moe or self.model_axis != "pp"
        ):
            raise ValueError(
                f"--pp-schedule {self.pp_schedule} drives the motion and "
                "char families' dp x pp meshes (parallel/pp.py:"
                "pp_{rnn,char}_1f1b_value_and_grad); other families/axes "
                "run gpipe"
            )
        if self.pp_schedule == "interleaved" and self.model_axis == "pp":
            layers = self.model.layer_dim
            total = self.mesh_axes["pp"] * self.pp_chunks
            if layers % total:
                raise ValueError(
                    f"--stacked-layer {layers} does not split into "
                    f"pp={self.mesh_axes['pp']} x --pp-chunks "
                    f"{self.pp_chunks} = {total} virtual stages"
                )
        # bf16 + remat thread through EVERY model axis since r4 (the tp
        # gate-sharded and pp GPipe stacks take the same levers as the
        # sp relay: compute-dtype matmuls/collective bytes, f32 carries,
        # per-layer/per-tick checkpointing) - no tp/pp precision reject.
        if self._dropout > 0.0 and self.model_axis in ("tp", "pp"):
            raise NotImplementedError(
                "dropout is not supported on tp/pp mesh strategies (no "
                "dropout seam in the stage/gate kernels) - pass "
                "--dropout 0 (the CLI default 0.1 mirrors the reference "
                "surface, main.py:26)"
            )
        # every family's mesh programs thread bf16/remat since r4 (the
        # composed sp x tp blocks and the GPipe-staged blocks take the
        # same levers as model.apply) - no attention precision reject.
        if self._dropout > 0.0 and self.is_attention:
            # the attention family's dropout (models/attention.py) rides
            # the dp strategies' key plumbing; the composed-mesh programs
            # (attention_mesh_logits / the pp loss) thread no keys - a
            # key-less run would silently train without dropout
            raise NotImplementedError(
                "dropout is not supported on attention mesh strategies - "
                "use local/distributed/horovod/fsdp/distributed-native/"
                "parameter-server, or pass --dropout 0"
            )
        if (self._dropout > 0.0 and self.model_axis == "sp"
                and getattr(model, "cell", "lstm") == "lstm"
                and getattr(model, "layer_dim", 2) > 1
                and self.schedule != "sequential"):
            # fail at construction with the exact remedy (the strategy
            # layer re-checks this at trace time)
            raise ValueError(
                "sp dropout needs the sequential relay - pass "
                "--sp-schedule sequential or --dropout 0"
            )

    def _data_world_size(self) -> int:
        # moe shards batch rows over the FULL dp x ep product (every
        # device is a data shard for the backbone); everything else
        # shards data over dp only
        if getattr(self, "is_moe", False):
            return self.mesh.shape["dp"] * self.mesh.shape["ep"]
        return super()._data_world_size()

    def _mesh_loss_fn(self, weighted: bool):
        if self.is_moe:
            from pytorch_distributed_rnn_tpu.parallel.strategy import (
                make_moe_mesh_loss_fn,
            )

            return make_moe_mesh_loss_fn(
                self.model, self.mesh, weighted=weighted
            )
        if self.is_attention:
            if self.mesh_axes.get("pp", 1) > 1:
                from pytorch_distributed_rnn_tpu.parallel.strategy import (
                    make_attention_pp_loss_fn,
                )

                return make_attention_pp_loss_fn(
                    self.model, self.mesh,
                    num_microbatches=self.num_microbatches,
                    weighted=weighted,
                )
            from pytorch_distributed_rnn_tpu.parallel.strategy import (
                make_attention_mesh_loss_fn,
            )

            return make_attention_mesh_loss_fn(
                self.model, self.mesh, weighted=weighted
            )
        if self.is_char:
            if (self.model_axis == "pp"
                    and self.pp_schedule in ("1f1b", "interleaved")):
                from pytorch_distributed_rnn_tpu.parallel.strategy import (
                    make_char_pp_1f1b_loss_fn,
                )

                return make_char_pp_1f1b_loss_fn(
                    self.mesh, self.mesh_axes,
                    num_microbatches=self.num_microbatches,
                    num_chunks=self.pp_chunks,
                    weighted=weighted,
                    cell=getattr(self.model, "cell", "lstm"),
                    precision=getattr(self.model, "precision", "f32"),
                )
            from pytorch_distributed_rnn_tpu.parallel.strategy import (
                make_char_mesh_loss_fn,
            )

            return make_char_mesh_loss_fn(
                self.mesh, self.mesh_axes, schedule=self.schedule,
                num_microbatches=self.num_microbatches, weighted=weighted,
                dropout=self._dropout,
                cell=getattr(self.model, "cell", "lstm"),
                precision=getattr(self.model, "precision", "f32"),
                remat=getattr(self.model, "remat", False),
                num_layers=getattr(self.model, "layer_dim", None),
            )
        if (self.model_axis == "pp"
                and self.pp_schedule in ("1f1b", "interleaved")):
            from pytorch_distributed_rnn_tpu.parallel.strategy import (
                make_motion_pp_1f1b_loss_fn,
            )

            # remat is inherent to the 1f1b backward (it recomputes each
            # stage from the stashed input), so the flag needs no seam
            return make_motion_pp_1f1b_loss_fn(
                self.mesh, self.mesh_axes,
                num_microbatches=self.num_microbatches,
                num_chunks=self.pp_chunks, weighted=weighted,
                cell=getattr(self.model, "cell", "lstm"),
                precision=getattr(self.model, "precision", "f32"),
            )
        return make_motion_mesh_loss_fn(
            self.mesh, self.mesh_axes, schedule=self.schedule,
            num_microbatches=self.num_microbatches, weighted=weighted,
            dropout=self._dropout,
            cell=getattr(self.model, "cell", "lstm"),
            precision=getattr(self.model, "precision", "f32"),
            remat=getattr(self.model, "remat", False),
            num_layers=getattr(self.model, "layer_dim", None),
        )

    def _jit_replicated(self, fn):
        """jit with every output pinned fully replicated over the mesh.

        The mesh programs keep params replicated and their shard_mapped
        losses return replicated scalars, but an outer ``jax.jit`` without
        out_shardings may still PLACE a scalar on one process's device -
        unfetchable from the other controllers of a multi-process world.
        Pinning replicated outputs makes every host-side ``float()`` legal
        on every rank (the dp.py factories get this for free from their
        whole-program shard_map out_specs)."""
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(self.mesh, PartitionSpec())
        return jax.jit(fn, donate_argnums=(0, 1), out_shardings=rep)

    def _build_train_step(self):
        return self._jit_replicated(make_mesh_grad_step(
            self._mesh_loss_fn(weighted=False), self.optimizer
        ))

    def _build_idx_train_step(self):
        grad_step = make_mesh_grad_step(
            self._mesh_loss_fn(weighted=False), self.optimizer
        )

        def step(params, opt_state, features, labels, idx, *extra):
            return grad_step(
                params, opt_state, (features[idx], labels[idx]), *extra
            )

        return self._jit_replicated(step)

    def _build_epoch_fn(self):
        grad_step = make_mesh_grad_step(
            self._mesh_loss_fn(weighted=False), self.optimizer
        )
        with_key = self._dropout > 0.0

        def epoch(params, opt_state, features, labels, idx_mat,
                  key_mat=None):
            def body(carry, step_in):
                idx = step_in[0] if with_key else step_in
                extra = (step_in[1],) if with_key else ()
                params, opt_state, loss, metrics = grad_step(
                    *carry, (features[idx], labels[idx]), *extra
                )
                return (params, opt_state), (loss, metrics)

            xs = (idx_mat, key_mat) if with_key else idx_mat
            (params, opt_state), (losses, metrics) = jax.lax.scan(
                body, (params, opt_state), xs
            )
            metrics_sum = jax.tree.map(
                lambda m: jax.numpy.sum(m, axis=0), metrics
            )
            return params, opt_state, jax.numpy.sum(losses), metrics_sum

        return self._jit_replicated(epoch)

    def _build_run_fn(self):
        grad_step = make_mesh_grad_step(
            self._mesh_loss_fn(weighted=True), self.optimizer
        )
        with_key = self._dropout > 0.0

        def run(params, opt_state, features, labels, idx_mat, w_mat,
                key_mat=None):
            def body(carry, step_in):
                idx, w = step_in[0], step_in[1]
                extra = (step_in[2],) if with_key else ()
                params, opt_state, loss, metrics = grad_step(
                    *carry, (features[idx], labels[idx]), w, *extra
                )
                return (params, opt_state), (loss, metrics["correct"])

            xs = (idx_mat, w_mat, key_mat) if with_key else (idx_mat, w_mat)
            (params, opt_state), (losses, correct) = jax.lax.scan(
                body, (params, opt_state), xs
            )
            return params, opt_state, losses, correct

        return self._jit_replicated(run)


def mesh_trainer_factory(args):
    """Bind the CLI's mesh flags into a Trainer-compatible constructor."""
    spec = parse_mesh_spec(args.mesh)

    cls = MeshTrainer
    if getattr(args, "model", "rnn") == "char":
        # the mesh TRAIN steps come from make_char_mesh_loss_fn; the LM
        # mixin supplies the matching EVAL loss surface (the base class's
        # _loss_and_metrics is classification-shaped)
        from pytorch_distributed_rnn_tpu.training.lm import wrap_lm_trainer

        cls = wrap_lm_trainer(MeshTrainer)
    elif getattr(args, "model", "rnn") == "moe":
        # train steps come from make_moe_mesh_loss_fn (expert-parallel);
        # the MoE mixin supplies the dense-exact EVAL surface + aux loss
        from pytorch_distributed_rnn_tpu.training.moe import (
            wrap_moe_trainer,
        )

        cls = wrap_moe_trainer(MeshTrainer)

    def build(**kwargs):
        return cls(
            mesh_axes=spec,
            schedule=args.sp_schedule,
            num_microbatches=args.num_microbatches,
            pp_schedule=getattr(args, "pp_schedule", "gpipe"),
            pp_chunks=getattr(args, "pp_chunks", 2),
            **kwargs,
        )

    # tells families.wrap_trainer the LM loss is already wired in (wrapping the
    # factory's PRODUCT is not possible from outside - it is not a class)
    build.OWNS_LM_LOSS = True
    build.OWNS_MOE_LOSS = True
    return build
