"""Process-per-rank data parallelism over the native C++ TCP collectives.

The reference's primary path is N OS processes launched by ``mpirun``, each
holding a model replica, with torch DDP allreducing gradients over
OpenMPI (``/root/reference/src/motion/trainer/ddp.py:18-19``,
``fabfile.py:218-223``).  The SPMD trainers (``training/distributed.py``)
are the TPU-native answer when one controller owns all chips; THIS module
is the multi-process analogue for the topologies where ranks really are
separate processes/hosts - each rank computes forward+backward locally as
one jitted XLA program, then averages gradients through the framework's
C++ TCP runtime (``runtime/csrc/collectives.cpp``, the MPI-replacement
transport that also backs the parameter-server strategy), and applies the
optimizer locally.  Identical updates from identical averaged gradients
keep replicas in lockstep - the DDP invariant, checked by the rank-parity
tests.

Reference semantics kept: rank-0-only evaluation/checkpointing
(``distributed.py:20-22,60-62``), per-rank batch = batch_size //
world_size (``distributed.py:48-49``), rank-tagged log lines and per-rank
perf line, parameter broadcast from rank 0 before training (the
DDP-construction broadcast, ``example_ddp.py:46``).

Launch: ``MASTER_ADDR``/``MASTER_PORT``/``RANK``/``WORLD_SIZE`` env (the
``mpirun`` analogue - one process per rank), subcommand
``distributed-native``; or :func:`launch_world` spawns a local world (the
docker-compose fake-cluster analogue).
"""

from __future__ import annotations

import functools
import json
import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.flatten_util import ravel_pytree

from pytorch_distributed_rnn_tpu.data.sampler import DistributedSampler
from pytorch_distributed_rnn_tpu.parallel.bucketing import DEFAULT_BUCKET_MB
from pytorch_distributed_rnn_tpu.parallel.sharded_update import ShardedUpdate
from pytorch_distributed_rnn_tpu.training.base import Trainer
from pytorch_distributed_rnn_tpu.training.formatter import TrainingMessageFormatter

log = logging.getLogger(__name__)


def _wire_dtype(dtype):
    """The dtype gradients/params ride the TCP ring in: the params' OWN
    dtype when the native collectives support it (f32/f64/bf16 - bf16
    halves wire bytes vs the old unconditional f32 upcast), else f32."""
    from pytorch_distributed_rnn_tpu.runtime.native import _ALLREDUCE_DTYPES

    if np.dtype(dtype).name in _ALLREDUCE_DTYPES:
        return np.dtype(dtype)
    return np.dtype(np.float32)


class NativeDDPTrainer(Trainer):
    """One rank of a process-per-rank DDP world."""

    SUPPORTS_GRAD_ACCUM = False  # builds its step around the TCP allreduce
    # pure-DP ring: the sharded weight update (2004.13336) applies - each
    # rank reduce-scatters gradients, updates only its 1/world slice of
    # the params (holding only that slice's optimizer state), and
    # allgathers the fresh params
    SUPPORTS_SHARDED_UPDATE = True

    # gradients cross the host TCP transport every step, so the host must
    # act per batch (no scanned device-resident epoch program)
    DEVICE_DATA = False

    def __init__(
        self,
        comm,
        model,
        training_set,
        batch_size: int,
        learning_rate: float,
        validation_set=None,
        test_set=None,
        checkpoint_dir=None,
        seed: int | None = None,
        grad_accum: int = 1,
        fuse_run: bool = False,
        checkpoint_format: str = "gathered",
        checkpoint_async: bool = False,
        bucketed_comm: bool = True,
        bucket_mb: float = DEFAULT_BUCKET_MB,
        **kwargs,  # resilience knobs (faults/max_bad_steps/keep_checkpoints)
    ):
        if checkpoint_async:
            # base validation would also reject (async needs sharded),
            # but sharded itself is rejected here - say why directly
            raise ValueError(
                "--checkpoint-async needs --checkpoint-format sharded, "
                "which distributed-native does not support (no "
                "jax.distributed world for orbax to coordinate)"
            )
        if checkpoint_format == "sharded":
            # the TCP world has no jax.distributed client, so orbax would
            # see world_size independent "process 0"s all renaming the
            # same directory - reject instead of corrupting
            raise ValueError(
                "distributed-native checkpoints are per-rank local files; "
                "--checkpoint-format sharded needs a jax.distributed "
                "world (local/distributed/fsdp/mesh strategies)"
            )
        rank = comm.rank
        world = comm.world_size
        # set before super(): base's _init_opt_state hook runs inside
        # __init__ (before base assigns self.rank/world_size) and the
        # sharded layout needs the comm's rank/world
        self.comm = comm
        # overlapped bucketed gradient communication (default ON;
        # --no-bucketed-comm restores the monolithic sharded step).
        # Read before super() for the same _init_opt_state reason: the
        # bucketed step keeps per-bucket optimizer state.
        self._bucketed = bool(bucketed_comm)
        self._bucket_mb = float(bucket_mb)
        # whether the WORLD checkpoints (the pre-rank-gating arg): the
        # epoch-end opt-state gather is a collective, so every rank must
        # take the same decision even though only rank 0 keeps
        # checkpoint_dir set
        self._ckpt_world = checkpoint_dir is not None
        sampler = DistributedSampler(
            len(training_set), num_replicas=world, rank=rank, seed=seed or 0
        )
        super().__init__(
            model=model,
            training_set=training_set,
            # global-batch semantics (reference distributed.py:48-49)
            batch_size=max(1, batch_size // world),
            learning_rate=learning_rate,
            # rank-0-only evaluation and checkpointing (distributed.py:20-22)
            validation_set=validation_set if rank == 0 else None,
            test_set=test_set if rank == 0 else None,
            checkpoint_dir=checkpoint_dir if rank == 0 else None,
            sampler=sampler,
            seed=seed,
            grad_accum=grad_accum,
            # DEVICE_DATA=False makes the base gate reject an explicit
            # --fuse-run loudly (the per-step host allreduce cannot fuse)
            fuse_run=fuse_run,
            **kwargs,
        )
        self.rank = rank
        self.world_size = world

        # parameter broadcast from rank 0: the DDP-construction broadcast
        # (reference example_ddp.py:46) - afterwards every replica is
        # bit-identical and stays so via identical averaged updates.
        # Rides the params' native dtype (bf16 params broadcast at
        # 2 bytes/elem; the old unconditional f32 doubled their wire
        # bytes AND rounded the non-root replicas through f32).
        flat, self._unravel = ravel_pytree(self.params)
        wire = _wire_dtype(flat.dtype)
        bcast = self.comm.broadcast(np.asarray(flat, wire).copy(), root=0)
        self.params = self._unravel(
            jnp.asarray(bcast).astype(jnp.asarray(flat).dtype)
        )

    def _init_opt_state(self):
        # --sharded-update: each rank initializes ONLY its 1/world slice
        # of the optimizer state (parallel/sharded_update.py) - the
        # memory half of 2004.13336 on the process-per-rank ring
        self._shard_update = None
        self._bucket_plan = None
        self._ckpt_cache = None
        if self.sharded_update:
            self._shard_update = ShardedUpdate(
                self.optimizer, self.params, self.comm.world_size
            )
            if self._bucketed:
                su = self._shard_update
                self._bucket_plan = su.bucket_plan(
                    self._bucket_mb,
                    itemsize=_wire_dtype(su.dtype).itemsize,
                )
                return su.init_bucket_opt_state(
                    self.params, self.comm.rank, self._bucket_plan
                )
            return self._shard_update.init_shard_opt_state(
                self.params, self.comm.rank
            )
        return super()._init_opt_state()

    def _get_formatter(self, epochs):
        return TrainingMessageFormatter(epochs, self.rank)

    def _fold_rank(self, key):
        # per-process rank known at trace time: each rank draws its own
        # dropout mask (torch DDP per-rank RNG analogue)
        return jax.random.fold_in(key, self.rank)

    # -- per-step comm telemetry --------------------------------------------
    #
    # Every blocking comm call in the step is timed: `comm_wait_s` is
    # the wall time the host actually sat blocked, `comm_active_s` what
    # the collectives cost exclusively on the comm worker (the wire time
    # with zero overlap).  Base's host loop reads `_last_step_comm` and
    # rides both through the step event as comm_wait_s / overlap_frac;
    # sampled steps also get per-collective spans on the timeline's
    # "comm" lane.

    def _finish_step_comm(self, wait_s, active_s, spans):
        self._last_step_comm = (wait_s, active_s)
        if spans and self.recorder.enabled and self.recorder.is_sample_step(
            self._steps_done
        ):
            for name, tm_start, dur_s, attrs in spans:
                self.recorder.emit_span(
                    name, tm_start, dur_s, cat="comm",
                    step=self._steps_done, **attrs,
                )

    def _build_train_step(self):
        if self._shard_update is not None:
            if self._bucket_plan is not None:
                return self._build_bucketed_train_step()
            return self._build_sharded_train_step()
        grad_fn = jax.jit(
            jax.value_and_grad(self._loss_and_metrics, has_aux=True)
        )

        # the previous params/opt_state are dead once the update lands
        # (the step reassigns both), so donate them - without this the
        # update holds two full copies of the state at peak (PD103)
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def apply_update(params, opt_state, grads):
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params
            )
            return optax.apply_updates(params, updates), opt_state

        def step(params, opt_state, batch, *extra):
            (loss, metrics), grads = grad_fn(params, batch, *extra)
            flat, unravel = ravel_pytree(grads)
            # the DDP reducer analogue: one averaged allreduce over TCP
            # in the gradients' native dtype (no silent f32 upcast).
            # .copy() is load-bearing: on CPU np.asarray is a zero-copy
            # view of the XLA buffer and the native allreduce writes
            # in place through a raw pointer.  The np.asarray is also
            # the force point of the whole backward - it must stay
            # OUTSIDE the comm timer or compute reads as wire time
            vec = np.asarray(flat, _wire_dtype(flat.dtype)).copy()
            t0c = time.perf_counter()
            summed = self.comm.allreduce(vec)
            dur = time.perf_counter() - t0c
            grads = unravel(jnp.asarray(summed / self.world_size))
            params, opt_state = apply_update(params, opt_state, grads)
            self._finish_step_comm(
                dur, dur, [("allreduce", t0c, dur, {"bytes": summed.nbytes})]
            )
            return params, opt_state, loss, metrics

        return step

    def _build_sharded_train_step(self):
        """Sharded weight update over the ring (2004.13336): per-step
        wire traffic is one reduce-scatter (grads) + one allgather (fresh
        params) instead of one full allreduce, and the optimizer apply
        touches only this rank's 1/world slice.  Bitwise-identical to
        the replicated step: the C++ reduce-scatter reuses the
        allreduce's exact accumulation order, and the optax math is
        elementwise."""
        su = self._shard_update
        grad_fn = jax.jit(
            jax.value_and_grad(self._loss_and_metrics, has_aux=True)
        )

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def apply_update_sharded(p_shard, opt_state, g_shard):
            updates, opt_state = self.optimizer.update(
                g_shard, opt_state, p_shard
            )
            return optax.apply_updates(p_shard, updates), opt_state

        def step(params, opt_state, batch, *extra):
            (loss, metrics), grads = grad_fn(params, batch, *extra)
            flat, _ = ravel_pytree(grads)
            wire = _wire_dtype(flat.dtype)
            comm_s = 0.0
            spans = []
            # force the backward (np.asarray blocks on the XLA buffer)
            # BEFORE starting the comm timer - the A/B against the
            # bucketed path is wire time, not compute
            vec = su.pad_flat(np.asarray(flat, wire))
            t0c = time.perf_counter()
            g_shard = self.comm.reduce_scatter(vec)
            dur = time.perf_counter() - t0c
            comm_s += dur
            spans.append(("reduce_scatter", t0c, dur,
                          {"bytes": su.padded * wire.itemsize}))
            g_shard = g_shard / np.asarray(self.world_size, g_shard.dtype)
            if self.guard is not None:
                # global skip verdict: each rank's apply_if_finite only
                # sees its own slice, so sync a 1-element any-non-finite
                # flag and NaN-poison every slice when any rank is bad -
                # all wrappers then take the identical skip decision
                t0c = time.perf_counter()
                flag = self.comm.allreduce(np.asarray(
                    [0.0 if np.all(np.isfinite(g_shard)) else 1.0],
                    np.float32,
                ))
                comm_s += time.perf_counter() - t0c
                if flag[0] > 0:
                    g_shard = np.full_like(g_shard, np.nan)
            flat_p, unravel = ravel_pytree(params)
            p_shard = jnp.asarray(su.shard_slice(
                su.pad_flat(np.asarray(flat_p)), self.rank
            ))
            # the same cast unravel() applies on the replicated path
            # (wire dtype -> param dtype), so the optax math sees
            # identical inputs
            p_shard, opt_state = apply_update_sharded(
                p_shard, opt_state,
                jnp.asarray(g_shard).astype(p_shard.dtype),
            )
            # fresh params: each rank contributes its slice, every rank
            # reassembles the full (identical) vector
            contrib = np.ascontiguousarray(np.asarray(p_shard))
            t0c = time.perf_counter()
            gathered = self.comm.allgather(contrib)
            dur = time.perf_counter() - t0c
            comm_s += dur
            spans.append(("allgather", t0c, dur, {"bytes": contrib.nbytes}))
            params = unravel(jnp.asarray(gathered.reshape(-1)[: su.size]))
            # synchronous collectives: blocked time == exclusive wire
            # time, overlap_frac 0 by definition - the A/B baseline the
            # bucketed path is measured against
            self._finish_step_comm(comm_s, comm_s, spans)
            return params, opt_state, loss, metrics

        return step

    def _build_bucketed_train_step(self):
        """Overlapped bucketed sharded update: the flat gradient is split
        into ``--bucket-mb`` buckets (``parallel/bucketing.py`` - rank-
        shard sub-ranges, the layout that keeps the ring accumulation
        order), every bucket's reduce-scatter is posted as a nonblocking
        handle up front, and the pipeline then walks the buckets: wait
        bucket k's reduce-scatter (k+1... are still streaming on the
        comm worker), apply its 1/world optax update, and post its param
        allgather - which overlaps bucket k+1's apply.  Bitwise-identical
        to :meth:`_build_sharded_train_step` (same per-element
        accumulation order, same elementwise optax math per slice, one
        global non-finite verdict).

        A comm object without the async API (test fakes, older
        transports) degrades to blocking per-bucket collectives - same
        wire traffic and results, no overlap.
        """
        su = self._shard_update
        plan = self._bucket_plan
        grad_fn = jax.jit(
            jax.value_and_grad(self._loss_and_metrics, has_aux=True)
        )

        # compiles once per distinct bucket length: body buckets share
        # one shape and the remainder bucket adds at most one more, so
        # the jit cache stays at <= 2 entries for the whole run (the
        # no-retrace acceptance bar)
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def apply_update_sharded_bucket(p_sub, opt_state, g_sub):
            updates, opt_state = self.optimizer.update(
                g_sub, opt_state, p_sub
            )
            return optax.apply_updates(p_sub, updates), opt_state

        has_async = hasattr(self.comm, "reduce_scatter_async")

        def step(params, opt_state, batch, *extra):
            (loss, metrics), grads = grad_fn(params, batch, *extra)
            flat, _ = ravel_pytree(grads)
            wire = _wire_dtype(flat.dtype)
            # (world, shard) view: bucket b's wire vector is column range
            # [lo, hi) across ALL ranks' rows, so ring chunk r stays rank
            # r's sub-slice (the bitwise-parity layout)
            g_cols = su.pad_flat(np.asarray(flat, wire)).reshape(
                self.world_size, su.shard
            )
            wait_s = 0.0
            active_s = 0.0
            spans = []

            def begin(kind, vec, b):
                nonlocal wait_s, active_s
                if has_async:
                    t_post = time.perf_counter()
                    handle = (
                        self.comm.reduce_scatter_async(vec)
                        if kind == "reduce_scatter"
                        else self.comm.allgather_async(vec)
                    )
                    return ("async", handle, t_post, vec.nbytes)
                t_post = time.perf_counter()
                out = (
                    self.comm.reduce_scatter(vec)
                    if kind == "reduce_scatter"
                    else self.comm.allgather(vec)
                )
                dur = time.perf_counter() - t_post
                wait_s += dur
                active_s += dur
                spans.append((kind, t_post, dur,
                              {"bucket": b, "bytes": vec.nbytes}))
                return ("sync", out)

            def finish(pending, kind, b):
                nonlocal wait_s, active_s
                if pending[0] == "sync":
                    return pending[1]
                _, handle, t_post, nbytes = pending
                t_wait = time.perf_counter()
                out = self.comm.wait(handle)
                t_done = time.perf_counter()
                wait_s += t_done - t_wait
                active_s += handle.comm_seconds
                spans.append((kind, t_post, t_done - t_post,
                              {"bucket": b, "bytes": nbytes}))
                return out

            # post EVERY bucket's reduce-scatter before touching any
            # result: the comm worker streams them FIFO while the host
            # moves on to the applies
            rs_pending = [
                begin("reduce_scatter",
                      np.ascontiguousarray(g_cols[:, lo:hi]).reshape(-1), b)
                for b, (lo, hi) in enumerate(plan.bounds)
            ]

            g_subs = [None] * plan.num_buckets
            if self.guard is not None:
                # the non-finite verdict is GLOBAL over the whole
                # gradient (one flag allreduce, same wire bytes as the
                # monolithic path), so all reduce-scatters must land
                # before the first apply; allgathers still overlap the
                # applies below
                for b in range(plan.num_buckets):
                    g = finish(rs_pending[b], "reduce_scatter", b)
                    g_subs[b] = g / np.asarray(self.world_size, g.dtype)
                finite = all(
                    np.all(np.isfinite(g)) for g in g_subs
                )
                t0c = time.perf_counter()
                flag = self.comm.allreduce(np.asarray(
                    [0.0 if finite else 1.0], np.float32
                ))
                dur = time.perf_counter() - t0c
                wait_s += dur
                active_s += dur
                if flag[0] > 0:
                    g_subs = [np.full_like(g, np.nan) for g in g_subs]

            flat_p, unravel = ravel_pytree(params)
            my_shard = su.shard_slice(
                su.pad_flat(np.asarray(flat_p)), self.rank
            )
            new_opt = list(opt_state)
            ag_pending = [None] * plan.num_buckets
            for b, (lo, hi) in enumerate(plan.bounds):
                g = g_subs[b]
                if g is None:
                    g = finish(rs_pending[b], "reduce_scatter", b)
                    g = g / np.asarray(self.world_size, g.dtype)
                p_sub = jnp.asarray(my_shard[lo:hi])
                p_sub, new_opt[b] = apply_update_sharded_bucket(
                    p_sub, opt_state[b],
                    jnp.asarray(g).astype(p_sub.dtype),
                )
                # np.asarray fences THIS bucket's apply; later buckets'
                # reduce-scatters (and earlier buckets' allgathers) are
                # still streaming on the comm worker behind it
                ag_pending[b] = begin(
                    "allgather",
                    np.ascontiguousarray(np.asarray(p_sub)), b,
                )
            new_cols = np.empty(
                (self.world_size, su.shard), dtype=my_shard.dtype
            )
            for b, (lo, hi) in enumerate(plan.bounds):
                new_cols[:, lo:hi] = finish(ag_pending[b], "allgather", b)
            params = unravel(jnp.asarray(new_cols.reshape(-1)[: su.size]))
            self._finish_step_comm(wait_s, active_s, spans)
            return params, new_opt, loss, metrics

        return step

    # -- checkpoint layout (gathered, unsharded - collective-safe) -----------

    def _train_epoch(self, formatter):
        result = super()._train_epoch(formatter)
        if self._shard_update is not None and self._ckpt_world:
            # epoch-end opt-state gather on EVERY rank (the allgather is
            # a collective; _save_checkpoint runs only where
            # checkpoint_dir survived the rank gate, so gathering there
            # would deadlock the ring) - rank 0 then writes the cached
            # unsharded layout
            shard_state = self.opt_state
            if self._bucket_plan is not None:
                # checkpoints keep the standard unsharded layout no
                # matter the comm schedule: fold the per-bucket states
                # back into one shard-layout state before the gather
                shard_state = self._shard_update.merge_bucket_opt_state(
                    shard_state, self._bucket_plan
                )
            self._ckpt_cache = self._shard_update.gather_opt_state(
                shard_state, self.comm.allgather
            )
        return result

    def _checkpoint_state(self):
        if self._shard_update is not None:
            if self._ckpt_cache is None:
                raise RuntimeError(
                    "sharded-update checkpoint requested before any "
                    "epoch-end gather - no unsharded state cached"
                )
            return self.params, self._ckpt_cache
        return super()._checkpoint_state()

    def _checkpoint_template_state(self):
        if self._shard_update is not None:
            return self.params, jax.eval_shape(
                self.optimizer.init, self.params
            )
        return super()._checkpoint_template_state()

    def _adopt_restored_state(self, params, opt_state):
        if self._shard_update is not None:
            self.params = params
            self.opt_state = self._shard_update.shard_opt_state(
                opt_state, self.rank
            )
            if self._bucket_plan is not None:
                self.opt_state = self._shard_update.split_shard_opt_state(
                    self.opt_state, self._bucket_plan
                )
        else:
            super()._adopt_restored_state(params, opt_state)


# ---------------------------------------------------------------------------
# pdrnn-lint --deep trace registry (lint/trace_registry.py)


def declare_trace_entries(register):
    """Register the per-rank device programs of the TCP-transport DDP
    step.  The host allreduce between them cannot trace, so the donated
    update program is registered on its own - exactly the surface the
    donation rule (PD205) guards: params/opt_state are dead after the
    update reassigns both."""

    def build():
        from pytorch_distributed_rnn_tpu.lint.trace_registry import (
            abstract_init,
            prng_spec,
        )
        from pytorch_distributed_rnn_tpu.models import MotionModel

        model = MotionModel(input_dim=9, hidden_dim=8, layer_dim=1,
                            output_dim=6, impl="scan")
        params = abstract_init(model.init, prng_spec())
        optimizer = optax.adam(1e-3)
        opt_state = abstract_init(optimizer.init, params)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def apply_update(p, state, grads):
            updates, state = optimizer.update(grads, state, p)
            return optax.apply_updates(p, updates), state

        return apply_update, (params, opt_state, params)

    register(
        name="native_ddp.apply_update", family="ddp",
        path="pytorch_distributed_rnn_tpu/training/native_ddp.py",
        build=build, mesh_axes={}, data_axis=None, donate=(0, 1),
        kind="update",
    )

    def build_sharded():
        from pytorch_distributed_rnn_tpu.lint.trace_registry import (
            abstract_init,
            prng_spec,
            sds,
        )
        from pytorch_distributed_rnn_tpu.models import MotionModel
        from pytorch_distributed_rnn_tpu.parallel.sharded_update import (
            ShardedUpdate,
        )

        model = MotionModel(input_dim=9, hidden_dim=8, layer_dim=1,
                            output_dim=6, impl="scan")
        params = abstract_init(model.init, prng_spec())
        optimizer = optax.adam(1e-3)
        # the on-device program of the sharded ring step: this rank's
        # 1/world param slice + shard-local optimizer state + its slice
        # of the reduce-scattered gradient (world 2, the lint mesh
        # convention); the TCP reduce-scatter/allgather around it are
        # host collectives and cannot trace
        su = ShardedUpdate(optimizer, params, 2)
        p_shard = sds((su.shard,), su.dtype)
        opt_state = abstract_init(optimizer.init, p_shard)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def apply_update_sharded(p, state, g):
            updates, state = optimizer.update(g, state, p)
            return optax.apply_updates(p, updates), state

        return apply_update_sharded, (p_shard, opt_state, p_shard)

    register(
        name="native_ddp.apply_update_sharded", family="ddp",
        path="pytorch_distributed_rnn_tpu/training/native_ddp.py",
        build=build_sharded, mesh_axes={}, data_axis=None, donate=(0, 1),
        kind="update",
    )

    def build_bucketed():
        from pytorch_distributed_rnn_tpu.lint.trace_registry import (
            abstract_init,
            prng_spec,
            sds,
        )
        from pytorch_distributed_rnn_tpu.models import MotionModel
        from pytorch_distributed_rnn_tpu.parallel.sharded_update import (
            ShardedUpdate,
        )

        model = MotionModel(input_dim=9, hidden_dim=8, layer_dim=1,
                            output_dim=6, impl="scan")
        params = abstract_init(model.init, prng_spec())
        optimizer = optax.adam(1e-3)
        # the per-bucket device program of the overlapped step: one
        # bucket's sub-slice of this rank's shard + that bucket's own
        # optimizer state (world 2, a tiny bucket_mb so the plan holds
        # more than one bucket - the registered shape is the body-bucket
        # length, the shape every bucket but possibly the last compiles)
        su = ShardedUpdate(optimizer, params, 2)
        plan = su.bucket_plan(1e-3)
        blen = plan.bucket_len(0)
        p_sub = sds((blen,), su.dtype)
        opt_state = abstract_init(optimizer.init, p_sub)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def apply_update_bucketed(p, state, g):
            updates, state = optimizer.update(g, state, p)
            return optax.apply_updates(p, updates), state

        return apply_update_bucketed, (p_sub, opt_state, p_sub)

    register(
        name="native_ddp.apply_update_bucketed", family="ddp",
        path="pytorch_distributed_rnn_tpu/training/native_ddp.py",
        build=build_bucketed, mesh_axes={}, data_axis=None, donate=(0, 1),
        kind="update",
    )


def run_rank(comm, args, model, datasets, trainer_class=None):
    """Train this rank's replica; returns the trainer (rank 0 writes
    ``history.json``, every rank logs its perf line).  ``trainer_class``
    lets a family mix its loss surface over :class:`NativeDDPTrainer`."""
    training_set, validation_set, test_set = datasets
    from pytorch_distributed_rnn_tpu.obs import MetricsRecorder
    from pytorch_distributed_rnn_tpu.resilience import FaultSchedule

    # rank-bound chaos schedule (one entry point per strategy, all via
    # FaultSchedule.resolve so no strategy can silently drop --faults).
    # A rank-scoped NaN injection keeps replicas in sync: the allreduce
    # propagates the NaN to every rank, so every guard skips the same
    # step identically.
    faults = FaultSchedule.resolve(args, rank=comm.rank)
    # per-rank telemetry sidecar (rank-suffixed path; resolve mirrors the
    # FaultSchedule one-entry-point convention)
    from pytorch_distributed_rnn_tpu.obs import StepTraceCapture

    recorder = MetricsRecorder.resolve(args, rank=comm.rank)
    # --profile-steps: rank 0 only (the history.json convention) - the
    # per-process profilers would otherwise race one hostname-keyed
    # xplane file in the shared trace dir
    profile_steps = StepTraceCapture.resolve(args) if comm.rank == 0 else None
    # live plane: rank 0 anchors the /metrics aggregator, other ranks
    # push digests to it; SIGUSR2 dumps stacks next to the sidecar
    plane = None
    if recorder.enabled:
        from pytorch_distributed_rnn_tpu.obs.live import LivePlane
        from pytorch_distributed_rnn_tpu.obs.watchdog import (
            install_stack_dump_handler,
        )

        install_stack_dump_handler(recorder.path)
        plane = LivePlane.resolve(args, recorder, rank=comm.rank,
                                  role="trainer", faults=faults)
    trainer = (trainer_class or NativeDDPTrainer)(
        comm=comm,
        model=model,
        training_set=training_set,
        validation_set=validation_set,
        test_set=test_set,
        batch_size=args.batch_size,
        learning_rate=args.learning_rate,
        checkpoint_dir=args.checkpoint_directory,
        # previously dropped here: --faults epoch kills + --resume auto
        # on the ring need periodic epoch checkpoints to restart from
        checkpoint_every=getattr(args, "checkpoint_every", 0),
        seed=args.seed,
        # forwarded so the unsupported-flag guard raises instead of the
        # flag being silently dropped
        grad_accum=getattr(args, "grad_accum", 1),
        fuse_run=getattr(args, "fuse_run", False),
        checkpoint_format=getattr(args, "checkpoint_format", "gathered"),
        checkpoint_async=getattr(args, "checkpoint_async", False),
        faults=faults,
        max_bad_steps=getattr(args, "max_bad_steps", 0),
        keep_checkpoints=getattr(args, "keep_checkpoints", 0),
        recorder=recorder,
        profile_steps=profile_steps,
        sharded_update=getattr(args, "sharded_update", True),
        bucketed_comm=getattr(args, "bucketed_comm", True),
        bucket_mb=getattr(args, "bucket_mb", DEFAULT_BUCKET_MB),
    )
    resume = getattr(args, "resume", None)
    if resume is not None and str(resume) == "auto":
        # crash-restart contract (resilience/guard.py): newest valid
        # checkpoint, corrupt files fall back, none = fresh start.
        # Every rank resolves the SAME shared directory (args are
        # identical across ranks), so all replicas restore identical
        # state and the same start epoch.
        from pytorch_distributed_rnn_tpu.resilience import resume_latest

        meta = resume_latest(trainer, args.checkpoint_directory)
        if meta is None:
            log.info("--resume auto: no usable checkpoint; starting fresh")
    elif resume:
        meta = trainer.resume_from(resume)
        log.info(f"Resumed from {resume} at epoch {meta['epoch']}")
    try:
        _, train_history, validation_history = trainer.train(
            epochs=args.epochs
        )
    finally:
        recorder.close()
        if plane is not None:
            plane.close()
    # the rank-parity observable (reference example_ddp.py:92 prints the
    # same quantity): identical on every rank iff replicas stayed in sync
    flat, _ = ravel_pytree(trainer.params)
    log.info(
        f"{comm.rank}: parameters: "
        f"{float(np.asarray(flat, np.float64).sum()):.10f}"
    )
    if comm.rank == 0:
        with open("history.json", "w") as file:
            json.dump(
                {
                    "train_history": train_history,
                    "validation_history": validation_history,
                },
                file,
            )
    return trainer


def launch_world(world_size: int, cli_args, *, master_port: int = 29533,
                 cwd=None, timeout: float = 600, backend: str = "cpu"):
    """Spawn a local ``world_size``-process DDP world (the reference's
    docker-compose two-container fake cluster, as plain processes): each
    rank runs ``python -m pytorch_distributed_rnn_tpu.main <cli_args>
    distributed-native`` with the env rendezvous set.  ``backend="cpu"``
    forces each rank onto the CPU platform (the no-hardware path);
    ``"native"`` leaves the ambient platform (attached accelerator) alone.
    Returns ``(returncode, stdout, stderr)`` per rank in rank order;
    raises if any rank fails."""
    import os
    import sys
    from pathlib import Path

    from pytorch_distributed_rnn_tpu.utils.worlds import spawn_world

    repo_root = str(Path(__file__).resolve().parent.parent.parent)
    rank_cmds = []
    for rank in range(world_size):
        env = dict(os.environ)
        env.update(
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(master_port),
            RANK=str(rank),
            WORLD_SIZE=str(world_size),
        )
        if backend == "cpu":
            env["PDRNN_PLATFORM"] = "cpu"
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (repo_root, env.get("PYTHONPATH")) if p
        )
        rank_cmds.append((
            [sys.executable, "-m", "pytorch_distributed_rnn_tpu.main",
             *map(str, cli_args), "distributed-native"],
            env,
        ))
    return spawn_world(rank_cmds, timeout=timeout, cwd=cwd)


def execute(args):
    """CLI entry for one rank (``distributed-native`` subcommand): world
    topology from MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE env - exactly how
    mpirun-launched ranks discovered theirs in the reference.

    Families: rnn / char / attention / moe (``training/families.py``) -
    the char-LM's bigger gradient vector (vocab head) is exactly what
    stresses the per-step TCP allreduce; moe rides dense-exact (expert
    grads are ordinary pytree leaves on the ring)."""
    from pytorch_distributed_rnn_tpu.runtime.native import init_from_env
    from pytorch_distributed_rnn_tpu.training import families

    families.require_family(
        args, ("rnn", "char", "attention", "moe"), "distributed-native"
    )
    logging.basicConfig(level=args.log)
    logging.getLogger().setLevel(args.log)

    datasets = families.load_datasets(args)
    if args.no_validation:
        datasets = (datasets[0], None, None)
    model = families.build_model(args, datasets[0])
    with init_from_env() as comm:
        return run_rank(comm, args, model, datasets,
                        trainer_class=families.wrap_trainer(
                            args, NativeDDPTrainer))
