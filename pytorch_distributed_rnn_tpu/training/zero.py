"""``fsdp`` strategy: ZeRO/FSDP sharded state on the shared training loop.

The reference keeps a full replica per rank (``/root/reference/src/motion/
trainer/ddp.py:19``); ``parallel/zero.py`` provides the library-level
from-construction sharding.  This module is the *strategy* form: the same
CLI/loop surface as ``distributed``, but parameters and optimizer state
live sharded over the ``dp`` axis (each big tensor split along its largest
divisible dim - :func:`~pytorch_distributed_rnn_tpu.parallel.zero.
shard_rule`) and batches are sharded over ``dp`` too.

TPU-native mechanics: unlike the DDP/Horovod strategies (explicit
``shard_map`` + ``pmean``), this one keeps GLOBAL program semantics and
pins layouts with ``with_sharding_constraint``: params/opt state to their
shard specs on the way in and out of every step, the gathered batch to
``P("dp")``.  XLA's SPMD partitioner then derives the FSDP schedule itself
- all-gather weights where consumed, partition the forward/backward along
the batch, reduce-scatter gradients, update each state shard locally - and
overlaps those collectives with compute.  Every shared-loop program (per-
batch, idx-gather, whole-epoch scan, fused whole-run) gets the same
treatment via the ``_make_*`` hooks, so checkpointing, eval, dropout,
grad-accum, and the perf-line contract are untouched.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_rnn_tpu.parallel.zero import sharded_specs
from pytorch_distributed_rnn_tpu.training.base import Trainer
from pytorch_distributed_rnn_tpu.training.distributed import SpmdTrainer


class ZeroTrainer(SpmdTrainer):
    """dp-sharded parameters + optimizer state on the shared loop."""

    # steps are built from the base _make_* bodies (which route through
    # _make_grad_step), so microbatch accumulation composes fine
    SUPPORTS_GRAD_ACCUM = True
    # ZeRO already shards params AND optimizer state by layout; the
    # flat-ravel sharded update would be redundant (and fight the
    # NamedSharding placement), so --sharded-update is inert here
    SUPPORTS_SHARDED_UPDATE = False

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # re-lay-out the replicated init into the ZeRO layout.  (The
        # transient replica is the same cost the reference pays at init;
        # models too big for ONE replica use parallel/zero.init_sharded's
        # from-construction path directly.)
        self._param_shardings = sharded_specs(self.params, self.mesh)
        self._opt_shardings = sharded_specs(self.opt_state, self.mesh)
        self._apply_zero_layout()
        self._batch_sharding = NamedSharding(self.mesh, P(self.axis))
        self._gather_fn = None

    def per_device_state_bytes(self) -> int:
        """Max bytes any one device holds for params + optimizer state
        (the number ZeRO shrinks; used by tests and memory reporting)."""
        from pytorch_distributed_rnn_tpu.parallel.zero import per_device_bytes

        return per_device_bytes(self.params) + per_device_bytes(self.opt_state)

    # -- sharding plumbing ---------------------------------------------------

    def _fold_rank(self, key):
        # global program semantics (no named axis bound): masks are drawn
        # per-example over the global batch, so no per-rank fold is needed
        return key

    def _constrain_state(self, params, opt_state):
        wsc = jax.lax.with_sharding_constraint
        return (
            wsc(params, self._param_shardings),
            wsc(opt_state, self._opt_shardings),
        )

    def _shard_batch(self, batch):
        wsc = jax.lax.with_sharding_constraint
        return tuple(
            wsc(part, self._batch_sharding) for part in batch
        )

    def _make_grad_step(self, loss_and_metrics):
        """The base grad+update body with the ZeRO layout pinned: state
        constrained to its shard specs on entry and exit, the batch
        constrained to ``P(dp)`` - everything between is XLA's choice."""
        inner = super()._make_grad_step(loss_and_metrics)

        def step(params, opt_state, batch, *extra):
            params, opt_state = self._constrain_state(params, opt_state)
            batch = self._shard_batch(batch)
            params, opt_state, loss, metrics = inner(
                params, opt_state, batch, *extra
            )
            params, opt_state = self._constrain_state(params, opt_state)
            return params, opt_state, loss, metrics

        return step

    # the SPMD (shard_map) builders don't apply here: use the BASE class's
    # programs (they route through the constrained _make_grad_step above)
    _build_train_step = Trainer._build_train_step
    _build_idx_train_step = Trainer._build_idx_train_step
    _build_epoch_fn = Trainer._build_epoch_fn
    _build_run_fn = Trainer._build_run_fn

    def _build_eval_step(self):
        # eval shards the full-dataset batch too (parallel evaluation)
        def eval_fn(params, batch, *extra):
            return self._loss_and_metrics(
                params, self._shard_batch(batch), *extra
            )

        return jax.jit(eval_fn)

    # -- checkpointing -------------------------------------------------------

    def _gather_state(self):
        """Replicated host-writable copies of the sharded state.

        In a multi-controller world a ZeRO-sharded array spans devices the
        writing process cannot address, so ``np.asarray`` (the checkpoint
        writer's path) would fail - the state must be all-gathered FIRST,
        by every process (it is a collective program), after which rank 0
        alone writes.
        """
        rep = NamedSharding(self.mesh, P())
        if self._gather_fn is None:
            self._gather_fn = jax.jit(
                lambda p, o: (p, o),
                out_shardings=(
                    jax.tree.map(lambda _: rep, self.params),
                    jax.tree.map(lambda _: rep, self.opt_state),
                ),
            )
        return self._gather_fn(self.params, self.opt_state)

    def _apply_zero_layout(self):
        self.params = jax.device_put(self.params, self._param_shardings)
        self.opt_state = jax.device_put(self.opt_state, self._opt_shardings)

    def _checkpoint_state(self):
        if jax.process_count() > 1:
            # collective all-gather: runs on EVERY process (the base
            # _save_checkpoint calls this hook before its rank gate)
            return self._gather_state()
        # single controller: every shard is process-addressable, so the
        # writer's np.asarray assembles the tree host-side without ever
        # materializing a device-side replica (ZeRO's memory point)
        return self.params, self.opt_state

    def resume_from(self, checkpoint_path, advance_epoch: bool = False):
        meta = super().resume_from(checkpoint_path, advance_epoch)
        self._apply_zero_layout()  # the loader returns host trees
        return meta


# ---------------------------------------------------------------------------
# pdrnn-lint --deep trace registry (lint/trace_registry.py)


def declare_trace_entries(register):
    """Register the ZeRO/FSDP step: NO explicit collective exists in this
    program - the gradient reduction is derived by the SPMD partitioner
    from sharding annotations, which is exactly the contract the
    ``gspmd=True`` branch of PD201 verifies."""

    def build():
        import optax

        from pytorch_distributed_rnn_tpu.lint.trace_registry import (
            abstract_init,
            lint_mesh,
            prng_spec,
            sds,
        )
        from pytorch_distributed_rnn_tpu.models import CharRNN
        from pytorch_distributed_rnn_tpu.parallel.zero import (
            make_fsdp_train_step,
            sharded_specs,
        )

        mesh = lint_mesh({"dp": 2})
        model = CharRNN(vocab_size=16, embed_dim=8, hidden_dim=16,
                        layer_dim=1, impl="scan")
        params = abstract_init(model.init, prng_spec())
        optimizer = optax.adam(1e-3)
        opt_state = abstract_init(optimizer.init, params)
        # tiny trace model: drop the min-size floor so the layout rule
        # actually shards (the annotations ARE what PD201 checks)
        pshard = sharded_specs(params, mesh, min_shard_elems=1)
        oshard = sharded_specs(opt_state, mesh, min_shard_elems=1)
        step = make_fsdp_train_step(model.loss, optimizer, mesh,
                                    pshard, oshard)
        tokens = sds((4, 16), jax.numpy.int32)
        return step, (params, opt_state, tokens)

    register(
        name="zero.fsdp_train_step", family="zero",
        path="pytorch_distributed_rnn_tpu/training/zero.py",
        build=build, mesh_axes={"dp": 2}, data_axis="dp", gspmd=True,
        donate=(0, 1),
    )
