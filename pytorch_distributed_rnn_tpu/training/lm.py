"""Language-model loss adapter: any trainer strategy x the char-LM family.

The shared loop and every distribution strategy consume
``_loss_and_metrics(params, (x, y), key)`` with a classification shape
(``training/base.py``).  The LM's next-token objective differs only there,
so this module swaps exactly that surface: :func:`wrap_lm_trainer` composes
an LM-loss mixin over any trainer class (local / DDP / Horovod), and
everything else - samplers, global-batch semantics, device-resident epoch
scans, checkpointing, perf lines - applies to LM training unchanged.  The
reference has no LM path at all; this is how the rebuild makes its stress
family a first-class CLI citizen.
"""

from __future__ import annotations

import jax.numpy as jnp

from pytorch_distributed_rnn_tpu.ops.losses import cross_entropy_loss


class LMLossMixin:
    """Overrides the two loss surfaces for token-window batches.

    A batch is ``(tokens (B, T+1) int32, dummy_labels)``: inputs are
    ``tokens[:, :-1]``, targets ``tokens[:, 1:]`` (``CharRNN.loss``
    semantics).  ``metrics['correct']`` is the SUM over sequences of each
    sequence's mean next-token accuracy, so the shared loop's
    ``correct / len(dataset)`` prints mean token accuracy - the LM
    analogue of the classification accuracy line.
    """

    def _lm_logits_and_targets(self, params, tokens, key):
        # _apply_model supplies the shared dropout-key gating (train-mode
        # only, per-rank fold in SPMD subclasses)
        logits = self._apply_model(params, tokens[:, :-1], key)
        return logits.astype(jnp.float32), tokens[:, 1:]

    def _loss_and_metrics(self, params, batch, key=None):
        tokens, _ = batch
        logits, targets = self._lm_logits_and_targets(params, tokens, key)
        vocab = logits.shape[-1]
        loss = cross_entropy_loss(
            logits.reshape(-1, vocab), targets.reshape(-1)
        )
        acc = jnp.mean(jnp.argmax(logits, axis=-1) == targets, axis=1)
        return loss, {"correct": jnp.sum(acc)}

    def _weighted_loss_and_metrics(self, params, batch, w, key=None):
        """Per-sequence weights (the fused run's zero-padded tail): the
        weighted mean of per-sequence mean NLLs equals the plain loss for
        all-ones weights, same contract as the classification variant."""
        tokens, _ = batch
        logits, targets = self._lm_logits_and_targets(params, tokens, key)
        vocab = logits.shape[-1]
        nll = cross_entropy_loss(
            logits.reshape(-1, vocab), targets.reshape(-1), reduction="none"
        ).reshape(targets.shape)
        per_seq = jnp.mean(nll, axis=1)
        loss = jnp.sum(per_seq * w) / jnp.sum(w)
        acc = jnp.mean(jnp.argmax(logits, axis=-1) == targets, axis=1)
        return loss, {"correct": jnp.sum(acc * (w > 0))}


_WRAPPED: dict = {}


def wrap_lm_trainer(trainer_class):
    """The trainer class with LM losses mixed in (cached per base class)."""
    cls = _WRAPPED.get(trainer_class)
    if cls is None:
        cls = type(
            f"LM{trainer_class.__name__}", (LMLossMixin, trainer_class), {}
        )
        _WRAPPED[trainer_class] = cls
    return cls
