"""The shared training loop: one loop, pluggable distribution strategies.

Capability parity with the reference ``Trainer``
(``/root/reference/src/motion/trainer/base.py:17-177``): epoch loop with
``sampler.set_epoch``; per-batch forward / CrossEntropy / backward / Adam
with accuracy bookkeeping; rank-0 evaluation under no-grad semantics;
best-model checkpointing on validation loss; the whole loop wrapped in
peak-RSS + wall-clock measurement emitting the parseable perf line; final
test evaluation.  Subclass hooks mirror the reference's
(``_get_optimizer``, ``_get_formatter``, ``_save_checkpoint``).

TPU-native design: training state is an explicit ``(params, opt_state)``
pytree pair; the per-batch work is ONE jit-compiled XLA program (forward +
backward + optimizer + metrics - and, in distributed subclasses, the
gradient AllReduce fused in).  Python only slices batches and logs.  Loss
normalization parity is kept deliberately: train loss = sum of batch means
/ dataset size, eval loss = mean of batch means (``base.py:128,146``).

New capability: ``resume_from`` loads a checkpoint (the reference never
reads its own checkpoints, SURVEY §5).
"""

from __future__ import annotations

import logging
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pytorch_distributed_rnn_tpu.data.loader import DataLoader
from pytorch_distributed_rnn_tpu.data.sampler import DistributedSampler
from pytorch_distributed_rnn_tpu.ops.losses import cross_entropy_loss
from pytorch_distributed_rnn_tpu.training.checkpoint import (
    load_checkpoint,
    save_checkpoint,
)
from pytorch_distributed_rnn_tpu.training.formatter import TrainingMessageFormatter
from pytorch_distributed_rnn_tpu.utils.profiling import measure_memory_and_time


class Trainer:
    """Single-replica ("local") trainer; distribution strategies subclass.

    ``model`` is a functional model object with ``init(key)`` / ``apply``
    (e.g. ``MotionModel``); ``training_set`` etc. are array datasets.
    """

    def __init__(
        self,
        model,
        training_set,
        batch_size: int,
        learning_rate: float,
        validation_set=None,
        test_set=None,
        checkpoint_dir=None,
        sampler=None,
        seed: int | None = None,
    ):
        self.model = model
        self.checkpoint_dir = Path(checkpoint_dir) if checkpoint_dir else None
        self.rank = 0
        self.world_size = 1

        self.sampler = sampler if sampler is not None else DistributedSampler(
            len(training_set), num_replicas=1, rank=0, seed=seed or 0
        )
        self.training_set = training_set
        self.validation_set = validation_set
        self.test_set = test_set
        self.batch_size = batch_size
        self.learning_rate = learning_rate

        self.params = model.init(jax.random.PRNGKey(seed if seed is not None else 0))
        self.optimizer = self._get_optimizer(learning_rate)
        self.opt_state = self.optimizer.init(self.params)

        self._train_step_fn = None
        self._eval_step_fn = None
        self._resume_best_loss = None

    # -- subclass hooks ------------------------------------------------------

    def _get_optimizer(self, lr: float):
        return optax.adam(lr)  # torch Adam defaults: b1=.9 b2=.999 eps=1e-8

    def _get_formatter(self, epochs: int) -> TrainingMessageFormatter:
        return TrainingMessageFormatter(epochs)

    def _loss_and_metrics(self, params, batch):
        x, y = batch
        logits = self.model.apply(params, x)
        loss = cross_entropy_loss(logits, y)
        correct = jnp.sum(jnp.argmax(logits, axis=1) == y)
        return loss, {"correct": correct}

    def _build_train_step(self):
        """One fused XLA program: grad + update + metrics."""

        def step(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                self._loss_and_metrics, has_aux=True
            )(params, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, metrics

        return jax.jit(step, donate_argnums=(0, 1))

    def _build_eval_step(self):
        return jax.jit(self._loss_and_metrics)

    # -- data ----------------------------------------------------------------

    def _train_loader(self):
        return DataLoader(
            self.training_set, batch_size=self.batch_size, sampler=self.sampler
        )

    def _prepare_batch(self, features, labels):
        return jnp.asarray(features), jnp.asarray(labels).reshape(-1)

    # -- loop ----------------------------------------------------------------

    def train(self, epochs: int):
        training_history: list[float] = []
        validation_history: list[float] = []
        formatter = self._get_formatter(epochs)
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
        if self._eval_step_fn is None:
            self._eval_step_fn = self._build_eval_step()

        def train_inner():
            # seed the best-model threshold from a resumed checkpoint so a
            # worse post-resume epoch cannot clobber best-model.ckpt
            best_loss = self._resume_best_loss
            for epoch in range(epochs):
                self.sampler.set_epoch(epoch)
                logging.info(formatter.epoch_start_message(epoch))
                train_loss, train_acc = self._train_epoch(formatter)
                training_history.append(train_loss)

                if self.validation_set is not None:
                    validation_loss, _ = self._evaluate(
                        self.validation_set, formatter, epoch
                    )
                    validation_history.append(validation_loss)
                    if best_loss is None or best_loss > validation_loss:
                        logging.info(f"New best model in epoch {epoch + 1}")
                        best_loss = validation_loss
                        self._save_checkpoint(epoch, validation_loss, best=True)

        _, memory, duration = measure_memory_and_time(train_inner)
        logging.info(formatter.performance_message(memory, duration))

        if self.test_set is not None:
            self._evaluate(self.test_set, formatter)

        return self.params, training_history, validation_history

    def _train_epoch(self, formatter):
        # Accumulate on-device and convert once per epoch: per-batch
        # float()/int() would block on a host-device sync every step and
        # serialize XLA's async dispatch.  Per-batch logging (which needs
        # the values on host) only happens when INFO is actually enabled.
        log_progress = logging.getLogger().isEnabledFor(logging.INFO)
        total_loss = jnp.zeros(())
        total_correct = jnp.zeros((), jnp.int32)
        loader = self._train_loader()
        num_batches = len(loader)
        for batch_idx, (features, labels) in enumerate(loader):
            batch = self._prepare_batch(features, labels)
            self.params, self.opt_state, loss, metrics = self._train_step_fn(
                self.params, self.opt_state, batch
            )
            total_loss = total_loss + loss
            total_correct = total_correct + metrics["correct"]
            if log_progress:
                logging.info(
                    formatter.train_progress_message(
                        batch_idx=batch_idx,
                        batches=num_batches,
                        training_examples=len(features),
                        correct=int(metrics["correct"]),
                        loss=float(loss),
                    )
                )
        total_loss = float(total_loss)
        total_correct = int(total_correct)
        # parity quirk kept: sum of batch-mean losses / dataset size
        train_loss = total_loss / len(self.training_set)
        train_acc = total_correct / len(self.training_set)
        return train_loss, train_acc

    def _evaluate(self, dataset, formatter, epoch=None):
        """Full-dataset evaluation in one batch (reference loads val/test
        with batch_size=len(dataset), base.py:53-54)."""
        features, labels = dataset[np.arange(len(dataset))]
        batch = self._prepare_batch(features, labels)
        loss, metrics = self._eval_step_fn(self.params, batch)
        eval_loss = float(loss)  # one batch -> already the mean-of-batches
        total_correct = int(metrics["correct"])
        num_examples = len(dataset)
        accuracy = total_correct / num_examples
        logging.info(
            formatter.evaluation_message(
                accuracy, num_examples, epoch, eval_loss, total_correct
            )
        )
        return eval_loss, accuracy

    # -- checkpointing -------------------------------------------------------

    def _save_checkpoint(self, epoch, loss, best=False):
        if self.checkpoint_dir is None:
            return
        save_checkpoint(
            self.checkpoint_dir, epoch, self.params, self.opt_state, loss, best=best
        )

    def resume_from(self, checkpoint_path):
        """Restore params/optimizer state (new capability; the reference's
        checkpoints were write-only).  Returns the checkpoint metadata."""
        self.params, self.opt_state, meta = load_checkpoint(
            checkpoint_path, self.params, self.opt_state
        )
        self._resume_best_loss = meta["loss"]
        return meta
