"""The shared training loop: one loop, pluggable distribution strategies.

Capability parity with the reference ``Trainer``
(``/root/reference/src/motion/trainer/base.py:17-177``): epoch loop with
``sampler.set_epoch``; per-batch forward / CrossEntropy / backward / Adam
with accuracy bookkeeping; rank-0 evaluation under no-grad semantics;
best-model checkpointing on validation loss; the whole loop wrapped in
peak-RSS + wall-clock measurement emitting the parseable perf line; final
test evaluation.  Subclass hooks mirror the reference's
(``_get_optimizer``, ``_get_formatter``, ``_save_checkpoint``).

TPU-native design: training state is an explicit ``(params, opt_state)``
pytree pair; the per-batch work is ONE jit-compiled XLA program (forward +
backward + optimizer + metrics - and, in distributed subclasses, the
gradient AllReduce fused in).  Python only slices batches and logs.  Loss
normalization parity is kept deliberately: train loss = sum of batch means
/ dataset size, eval loss = mean of batch means (``base.py:128,146``).

New capability: ``resume_from`` loads a checkpoint (the reference never
reads its own checkpoints, SURVEY §5).
"""

from __future__ import annotations

import logging
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pytorch_distributed_rnn_tpu.data.loader import DataLoader
from pytorch_distributed_rnn_tpu.obs.recorder import NULL_RECORDER
from pytorch_distributed_rnn_tpu.data.prefetch import prefetch
from pytorch_distributed_rnn_tpu.data.sampler import DistributedSampler
from pytorch_distributed_rnn_tpu.ops.losses import cross_entropy_loss
from pytorch_distributed_rnn_tpu.resilience.guard import NonFiniteGuard
from pytorch_distributed_rnn_tpu.training.checkpoint import (
    load_checkpoint,
    rotate_checkpoints,
    save_checkpoint,
)
from pytorch_distributed_rnn_tpu.training.formatter import TrainingMessageFormatter
from pytorch_distributed_rnn_tpu.utils.profiling import measure_memory_and_time


def _fence(value):
    """The telemetry/profiler device fence - a module-level seam so the
    zero-overhead guard test can count fences (disabled telemetry must
    never add a per-step host sync)."""
    jax.block_until_ready(value)


def _correct_count(value) -> int:
    """Host-side display form of the ``correct`` metric: classification
    counts are exact integers; the LM's fractional per-sequence accuracy
    sums (``training/lm.py``) ROUND for display instead of flooring (int()
    would bias every printed accuracy downward)."""
    return int(round(float(value)))


class Trainer:
    """Single-replica ("local") trainer; distribution strategies subclass.

    ``model`` is a functional model object with ``init(key)`` / ``apply``
    (e.g. ``MotionModel``); ``training_set`` etc. are array datasets.

    Data path (``DEVICE_DATA = True``): the training arrays are placed in
    device memory ONCE and every batch is gathered on device from a small
    per-step index vector - when per-batch progress logging is off, the
    whole epoch additionally runs as ONE ``lax.scan`` program (a single
    dispatch per epoch).  This replaces the reference's per-batch
    host-loads (``/root/reference/src/motion/trainer/base.py:107``), which
    on an accelerator behind a host link leave the chip idle between steps.
    Strategies that must act on the host every batch (the parameter-server
    worker pushing gradients over TCP) set ``DEVICE_DATA = False`` and keep
    the materialized-batch loop.
    """

    DEVICE_DATA = True
    # strategies whose step programs are built by external factories
    # (SPMD pmean steps, native-TCP DDP, PS workers) flip this off until
    # they implement microbatch accumulation themselves
    SUPPORTS_GRAD_ACCUM = True
    # pure-DP strategies that can run the cross-replica sharded weight
    # update (reduce-scatter + 1/world optax apply + allgather,
    # parallel/sharded_update.py) flip this on; everywhere else the
    # --sharded-update flag is accepted and inert (world of 1, or the
    # optimizer state is already sharded by the strategy itself - ZeRO,
    # mesh layouts)
    SUPPORTS_SHARDED_UPDATE = False

    def __init__(
        self,
        model,
        training_set,
        batch_size: int,
        learning_rate: float,
        validation_set=None,
        test_set=None,
        checkpoint_dir=None,
        sampler=None,
        seed: int | None = None,
        checkpoint_every: int = 0,
        grad_accum: int = 1,
        fuse_run: bool = False,
        checkpoint_format: str = "gathered",
        checkpoint_async: bool = False,
        faults=None,
        max_bad_steps: int = 0,
        keep_checkpoints: int = 0,
        recorder=None,
        profile_steps=None,
        sharded_update: bool = True,
    ):
        self.model = model
        # structured telemetry (obs/recorder.py): NULL_RECORDER when off -
        # instrumented call sites then cost one attribute check and the
        # step loops keep their uninstrumented shape (no fencing, no
        # per-step bookkeeping)
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        # step-bounded jax.profiler capture (obs/profile.py); forces the
        # per-batch dispatch path so steps are addressable
        self._profile = profile_steps
        # traced collective traffic is recorded once per run
        self._collectives_recorded = False
        # analytic FLOPs of the traced live step (obs/flops.py), filled
        # by _maybe_record_collectives for the run_summary ledger block
        self._model_flops_per_step = None
        self._model_flops_exact = None
        # per-step-fn trace-cache sizes last observed: a bump after the
        # first compile is a RETRACE and emits a `compile` event
        self._trace_cache_seen = {}
        # gathered: the reference-parity single file (training/
        # checkpoint.py) - state is gathered to the writing host.
        # sharded: orbax/tensorstore per-shard writes - no gather, no
        # host-side replica; the scale path for ZeRO/mesh layouts
        # (training/sharded_checkpoint.py).
        if checkpoint_format not in ("gathered", "sharded"):
            raise ValueError(
                f"unknown checkpoint format {checkpoint_format!r} - use "
                "gathered or sharded"
            )
        if checkpoint_async and checkpoint_format != "sharded":
            raise ValueError(
                "--checkpoint-async overlaps the orbax background write "
                "with training and needs --checkpoint-format sharded"
            )
        self.checkpoint_format = checkpoint_format
        self.checkpoint_async = bool(checkpoint_async)
        self._pending_ckpt = None
        # --fuse-run: compile the whole multi-epoch run into ONE device
        # program even when INFO logging is on (the perf line still
        # prints; only the per-epoch Start-Epoch messages are traded
        # away).  Without it the fused path is taken only when nothing
        # observable needs the host between epochs.  On a remote-attached
        # chip each epoch dispatch costs a full tunnel round-trip, which
        # dominates this workload ~20x (BASELINE.md r4).
        self._fuse_run = bool(fuse_run)
        self.checkpoint_dir = Path(checkpoint_dir) if checkpoint_dir else None
        # periodic epoch checkpoints (checkpoint-epoch-N.ckpt) in addition
        # to best-model.ckpt; 0 = best-only (reference trigger, base.py:88-91)
        self.checkpoint_every = int(checkpoint_every or 0)
        # rotation: keep only the newest N epoch checkpoints (0 = keep all;
        # best-model.ckpt is never rotated) - resilience/guard.py auto-resume
        # walks whatever survives, newest first
        self.keep_checkpoints = int(keep_checkpoints or 0)
        # chaos harness (resilience/faults.py): a FaultSchedule whose
        # step-granularity events force the per-batch host loop so faults
        # can address individual optimizer steps
        self._faults = faults
        # non-finite-step guard (resilience/guard.py): with K > 0 the
        # optimizer is wrapped so NaN/Inf-gradient steps are skipped inside
        # the compiled program and the host aborts past K consecutive
        self.guard = NonFiniteGuard(max_bad_steps) if max_bad_steps else None
        # the resilience subsystems emit their own telemetry (nan_skip /
        # fault events) through the same recorder
        if self.guard is not None:
            self.guard.recorder = self.recorder
        if self._faults is not None:
            self._faults.recorder = self.recorder
        self.rank = 0
        self.world_size = 1

        self.sampler = sampler if sampler is not None else DistributedSampler(
            len(training_set), num_replicas=1, rank=0, seed=seed or 0
        )
        self.training_set = training_set
        self.validation_set = validation_set
        self.test_set = test_set
        self.batch_size = batch_size
        self.learning_rate = learning_rate
        # HBM lever: split each optimizer batch into `grad_accum` equal
        # microbatches, accumulate grads, apply ONE update - the effective
        # batch keeps the CLI batch-size semantics while peak activation
        # memory shrinks by ~grad_accum (how the 50M-LM preset reaches
        # batch sizes whose single-shot activations do not fit).
        self.grad_accum = 1 if grad_accum is None else int(grad_accum)
        if self.grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        if self.grad_accum > 1 and not self.SUPPORTS_GRAD_ACCUM:
            raise NotImplementedError(
                f"{type(self).__name__} builds its train step outside "
                "_make_grad_step and does not support grad_accum > 1"
            )
        if self.grad_accum > 1 and batch_size % self.grad_accum:
            # loud up front: silently running full batches at a smaller k
            # would use ~k_actual/k x the activation memory the user sized
            # for.  (The epoch's FINAL partial batch may still fall back to
            # a smaller divisor - it is smaller than a full batch, so its
            # memory never exceeds what the user asked for.)
            raise ValueError(
                f"batch_size {batch_size} is not divisible by "
                f"grad_accum {self.grad_accum}"
            )

        # --sharded-update (default on): strategies with
        # SUPPORTS_SHARDED_UPDATE use it in _init_opt_state to lay the
        # optimizer state out 1/world-sharded; stored before the init
        # hook runs so the hook can read it
        self.sharded_update = bool(sharded_update)

        self.params = model.init(jax.random.PRNGKey(seed if seed is not None else 0))
        self.optimizer = self._get_optimizer(learning_rate)
        if self.guard is not None:
            self.optimizer = self.guard.wrap(self.optimizer)
        self.opt_state = self._init_opt_state()

        # train-mode dropout: real here, unlike the reference's dead
        # --dropout flag (/root/reference/src/motion/main.py:26 - parsed,
        # never used; conscious fix, PARITY.md).  Per-step keys are threaded
        # as a trailing arg only when dropout is on, so the no-dropout
        # compiled programs are unchanged.
        self._dropout = float(getattr(model, "dropout", 0.0) or 0.0)
        self._dropout_key = jax.random.fold_in(
            jax.random.PRNGKey(seed if seed is not None else 0), 0x5EED
        )

        self._train_step_fn = None
        self._eval_step_fn = None
        self._idx_step_fn = None
        self._epoch_fn = None
        self._run_fn = None
        self._device_data = None
        self._eval_data_cache = {}
        self._resume_best_loss = None
        self._epoch = 0
        # auto-resume: epochs [0, _start_epoch) are already banked in the
        # restored checkpoint; train() continues from there
        self._start_epoch = 0
        # run-relative optimizer-step counter - the address space for the
        # fault schedule's step triggers
        self._steps_done = 0
        # (comm_wait_s, comm_active_s) published by the step fn that just
        # ran, or None when the strategy has no per-step host collectives;
        # the host loop rides it through the step event
        self._last_step_comm = None

    # -- subclass hooks ------------------------------------------------------

    def _get_optimizer(self, lr: float):
        return optax.adam(lr)  # torch Adam defaults: b1=.9 b2=.999 eps=1e-8

    def _init_opt_state(self):
        """Hook: build the initial optimizer state.  Strategies with
        SUPPORTS_SHARDED_UPDATE override to initialize it ALREADY in the
        1/world sharded flat layout (parallel/sharded_update.py) when
        ``self.sharded_update`` is on - the full-size state then never
        materializes per device."""
        return self.optimizer.init(self.params)

    def _get_formatter(self, epochs: int) -> TrainingMessageFormatter:
        return TrainingMessageFormatter(epochs)

    def _fold_rank(self, key):
        """Hook: SPMD subclasses fold the data-parallel rank into the
        dropout key so each shard draws an independent mask (matching
        torch DDP, where every rank has its own RNG stream)."""
        return key

    def _apply_model(self, params, x, key=None):
        """Model forward; threads the dropout key in train mode only."""
        if key is None or self._dropout <= 0.0:
            return self.model.apply(params, x)
        return self.model.apply(params, x, dropout_key=self._fold_rank(key))

    def _loss_and_metrics(self, params, batch, key=None):
        x, y = batch
        logits = self._apply_model(params, x, key)
        loss = cross_entropy_loss(logits, y)
        correct = jnp.sum(jnp.argmax(logits, axis=1) == y)
        return loss, {"correct": correct}

    def _weighted_loss_and_metrics(self, params, batch, w, key=None):
        """Masked variant used by the fused whole-run program: ``w`` is a
        0/1 weight per example.  With all-ones weights this equals
        ``_loss_and_metrics`` exactly; with a zero-padded tail it equals
        the reference's smaller final batch's mean (``base.py:46-51``).
        Override together with ``_loss_and_metrics``."""
        x, y = batch
        logits = self._apply_model(params, x, key)
        nll = cross_entropy_loss(logits, y, reduction="none")
        loss = jnp.sum(nll * w) / jnp.sum(w)
        correct = jnp.sum((jnp.argmax(logits, axis=1) == y) * (w > 0))
        return loss, {"correct": correct}

    def _make_grad_step(self, loss_and_metrics):
        """The shared grad+update body: ``step(params, opt_state, batch,
        *extra) -> (params, opt_state, loss, metrics)``; ``*extra`` is
        forwarded to the loss fn (the weighted-run path's mask).

        With ``grad_accum > 1`` (plain, unweighted loss only) the batch is
        reshaped into equal microbatches and scanned: grads and batch-mean
        losses are averaged across microbatches before the single optimizer
        update - numerically the full-batch mean/grad (up to float
        reassociation), at ~1/grad_accum the activation memory.  A dropout
        key in ``*extra`` is folded per microbatch (independent masks)."""

        def single_shot(params, opt_state, batch, *extra):
            (loss, metrics), grads = jax.value_and_grad(
                loss_and_metrics, has_aux=True
            )(params, batch, *extra)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, metrics

        if self.grad_accum <= 1:
            return single_shot

        k_conf = self.grad_accum

        def accum_step(params, opt_state, batch, *extra):
            # *extra here can only be the dropout PRNG key: it is vmapped
            # through fold_in below.  Any other payload (e.g. the weighted
            # path's mask vector) would be silently consumed as key
            # material - fail loudly instead.
            assert len(extra) <= 1, (
                f"accum_step takes at most a dropout key in *extra, "
                f"got {len(extra)} extras"
            )
            if extra:
                import jax.dtypes as _dtypes

                d = extra[0].dtype
                assert d == jnp.uint32 or _dtypes.issubdtype(
                    d, _dtypes.prng_key
                ), f"accum_step *extra must be a PRNG key, got dtype {d}"
            n = batch[0].shape[0]
            # the epoch's final partial batch (n = len(dataset) %
            # batch_size) need not divide by k: use the largest divisor
            # <= k_conf (worst case 1 = single shot) - the partial batch
            # is smaller than the full ones, so its single-shot
            # activations fit wherever the microbatched full ones did
            k = next(d for d in range(k_conf, 0, -1) if n % d == 0)
            if k == 1:
                return single_shot(params, opt_state, batch, *extra)
            micro = jax.tree.map(
                lambda a: a.reshape(k, n // k, *a.shape[1:]), batch
            )
            keys = (
                jax.vmap(lambda i: jax.random.fold_in(extra[0], i))(
                    jnp.arange(k)
                ),
            ) if extra else ()

            def body(carry, mb_in):
                g_acc, l_acc, m_acc = carry
                mb = mb_in[0] if extra else mb_in
                e = (mb_in[1],) if extra else ()
                (loss, metrics), grads = jax.value_and_grad(
                    loss_and_metrics, has_aux=True
                )(params, mb, *e)
                g_acc = jax.tree.map(jnp.add, g_acc, grads)
                m_acc = jax.tree.map(jnp.add, m_acc, metrics)
                return (g_acc, l_acc + loss, m_acc), None

            zeros_g = jax.tree.map(jnp.zeros_like, params)
            first_mb = jax.tree.map(lambda a: a[0], micro)
            zeros_m = jax.tree.map(
                jnp.zeros_like,
                jax.eval_shape(
                    lambda p, b: loss_and_metrics(p, b)[1], params, first_mb
                ),
            )
            xs = (micro, keys[0]) if extra else micro
            (g_sum, l_sum, m_sum), _ = jax.lax.scan(
                body, (zeros_g, jnp.zeros(()), zeros_m), xs
            )
            grads = jax.tree.map(lambda g: g / k, g_sum)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, l_sum / k, m_sum

        return accum_step

    def _build_train_step(self):
        """One fused XLA program: grad + update + metrics."""
        return jax.jit(
            self._make_grad_step(self._loss_and_metrics), donate_argnums=(0, 1)
        )

    def _build_eval_step(self):
        return jax.jit(self._loss_and_metrics)

    def _make_idx_train_step(self):
        """The un-jitted idx-gather step (sharding-aware subclasses re-jit
        it with layout constraints)."""
        grad_step = self._make_grad_step(self._loss_and_metrics)

        def step(params, opt_state, features, labels, idx, *extra):
            return grad_step(
                params, opt_state, (features[idx], labels[idx]), *extra
            )

        return step

    def _build_idx_train_step(self):
        """Train step taking (params, opt_state, features, labels, idx,
        [key]): the batch is gathered on device from resident arrays; the
        trailing per-step dropout key is passed only when dropout is on."""
        return jax.jit(self._make_idx_train_step(), donate_argnums=(0, 1))

    def _make_epoch_fn(self):
        """The un-jitted whole-epoch program (see _build_epoch_fn)."""
        grad_step = self._make_grad_step(self._loss_and_metrics)
        with_key = self._dropout > 0.0

        def epoch(params, opt_state, features, labels, idx_mat, key_mat=None):
            def body(carry, step_in):
                idx = step_in[0] if with_key else step_in
                extra = (step_in[1],) if with_key else ()
                params, opt_state, loss, metrics = grad_step(
                    *carry, (features[idx], labels[idx]), *extra
                )
                return (params, opt_state), (loss, metrics)

            xs = (idx_mat, key_mat) if with_key else idx_mat
            (params, opt_state), (losses, metrics) = jax.lax.scan(
                body, (params, opt_state), xs
            )
            metrics_sum = jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)
            return params, opt_state, jnp.sum(losses), metrics_sum

        return epoch

    def _build_epoch_fn(self):
        """Whole-epoch program: ``lax.scan`` over the epoch's (num_batches,
        batch) index matrix - one dispatch per epoch.  With dropout on, a
        (num_batches, 2) per-step key matrix rides the scan."""
        return jax.jit(self._make_epoch_fn(), donate_argnums=(0, 1))

    def _make_run_fn(self):
        """The un-jitted whole-run program (see _build_run_fn)."""
        grad_step = self._make_grad_step(self._weighted_loss_and_metrics)
        with_key = self._dropout > 0.0

        def run(params, opt_state, features, labels, idx_mat, w_mat,
                key_mat=None):
            def body(carry, step_in):
                idx, w = step_in[0], step_in[1]
                extra = (step_in[2],) if with_key else ()
                params, opt_state, loss, metrics = grad_step(
                    *carry, (features[idx], labels[idx]), w, *extra
                )
                return (params, opt_state), (loss, metrics["correct"])

            xs = (idx_mat, w_mat, key_mat) if with_key else (idx_mat, w_mat)
            (params, opt_state), (losses, correct) = jax.lax.scan(
                body, (params, opt_state), xs
            )
            return params, opt_state, losses, correct

        return run

    def _build_run_fn(self):
        """The whole multi-epoch training run as ONE program: scan over
        every batch of every epoch (weight-masked so the final partial
        batch keeps reference semantics), returning per-step losses and
        correct-counts for the host to fold into per-epoch history."""
        return jax.jit(self._make_run_fn(), donate_argnums=(0, 1))

    # -- dropout keys --------------------------------------------------------

    def _epoch_dropout_keys(self, epoch: int, num_batches: int):
        """Per-step dropout keys for one epoch, derived deterministically
        from (seed, epoch, batch index) so the batched scan path and the
        per-batch logging path produce identical numerics."""
        ekey = jax.random.fold_in(self._dropout_key, epoch)
        return np.asarray(
            jax.vmap(lambda i: jax.random.fold_in(ekey, i))(
                jnp.arange(num_batches)
            )
        )

    # -- data ----------------------------------------------------------------

    def _train_loader(self):
        return DataLoader(
            self.training_set, batch_size=self.batch_size, sampler=self.sampler
        )

    def _prepare_batch(self, features, labels):
        return jnp.asarray(features), jnp.asarray(labels).reshape(-1)

    def _data_sharding(self):
        """Sharding for device-resident dataset arrays (None = default
        placement; SPMD subclasses replicate over the mesh)."""
        return None

    def _device_train_data(self):
        """Training arrays resident on device (uploaded once, cached)."""
        if self._device_data is None:
            features = np.asarray(self.training_set.features)
            labels = np.asarray(self.training_set.labels).reshape(-1)
            sharding = self._data_sharding()
            if sharding is None:
                self._device_data = (
                    jax.device_put(features),
                    jax.device_put(labels),
                )
            else:
                self._device_data = (
                    jax.device_put(features, sharding),
                    jax.device_put(labels, sharding),
                )
        return self._device_data

    def _epoch_index_batches(self):
        """The epoch's batches as a list of index arrays, in order.  All
        but possibly the last have equal size (reference loader semantics:
        final partial batch included, ``base.py:46-51``)."""
        indices = np.asarray(self.sampler.indices())
        return [
            indices[start : start + self.batch_size]
            for start in range(0, len(indices), self.batch_size)
        ]

    def _has_partial_batch(self) -> bool:
        """Whether epochs end in a smaller final batch (batch sizes are
        epoch-invariant; only the order shuffles)."""
        batches = self._epoch_index_batches()
        return len(batches) > 1 and len(batches[-1]) != len(batches[0])

    def _pad_batch(self, b, full_size):
        """Pad an index batch to ``full_size`` with zero-weighted dummy
        examples (index 0, weight 0) for the fused fixed-shape run."""
        pad = full_size - len(b)
        if pad == 0:
            return b, np.ones(full_size, np.float32)
        return (
            np.concatenate([b, np.zeros(pad, dtype=b.dtype)]),
            np.concatenate([np.ones(len(b), np.float32), np.zeros(pad, np.float32)]),
        )

    # -- loop ----------------------------------------------------------------

    # compile-stage failure signatures, matched case-insensitively
    # against the exception text.  Specific markers, not the bare
    # "compil" substring: "XLA compilation failure", "remote_compile:
    # HTTP 500: tpu_compile_helper ..." (the documented batch-512
    # deep-LM failure class) all carry one of these, while an
    # execution-stage error that merely *mentions* compilation (e.g. a
    # shape error naming a "compiled program") must not trigger a
    # retry - by then donate_argnums may have consumed the state
    # buffers (also enforced directly by the liveness/progress guards
    # below, not just by this string heuristic).
    _COMPILE_FAILURE_MARKS = (
        "compilation failure",
        "tpu_compile",
        "remote_compile",
        # the TPU compile-stage OOM producer: "XLA:TPU compile
        # permanent error. Ran out of memory in memory space hbm..."
        "compile permanent error",
    )
    # fallback retries allowed per train() call: each retry climbs to
    # the next batch divisor, and three rungs of microbatch shrinking
    # is past the point where a deeper split has ever rescued a
    # compile (BENCH r5); beyond that, fail with the ORIGINAL error
    _MAX_COMPILE_RETRIES = 3

    @classmethod
    def is_compile_failure(cls, exc) -> bool:
        """Whether ``exc`` looks like a compile-stage failure - the ONE
        classifier, shared with bench-side ladders so the two can never
        disagree on what the grad-accum fallback rescues."""
        msg = str(exc).lower()
        return any(m in msg for m in cls._COMPILE_FAILURE_MARKS)

    def _grad_accum_fallback(self, exc) -> int | None:
        """The grad_accum to retry with after a compile-stage failure,
        or ``None`` when retrying cannot help (not a compile failure,
        the trainer cannot accumulate, or no further split divides the
        batch).  Returns the smallest divisor of ``batch_size`` above
        the current grad_accum (<= 16): each retry shrinks the
        microbatch program until it compiles like the shapes that work,
        instead of recording a skip and moving on."""
        if not self.is_compile_failure(exc):
            return None
        if not self.SUPPORTS_GRAD_ACCUM:
            return None
        # the marks are a string heuristic; the donation invariant is
        # checked directly: an EXECUTION-stage failure whose message
        # merely mentions compilation has already consumed the donated
        # state buffers, and retrying on deleted arrays would mask the
        # real error behind a secondary "Array has been deleted"
        for leaf in jax.tree.leaves((self.params, self.opt_state)):
            if getattr(leaf, "is_deleted", lambda: False)():
                return None
        for k in range(self.grad_accum + 1, 17):
            if self.batch_size % k == 0:
                return k
        return None

    def train(self, epochs: int):
        training_history: list[float] = []
        validation_history: list[float] = []
        formatter = self._get_formatter(epochs)
        first_exc: Exception | None = None
        retries = 0
        self._steps_done = 0  # fault-schedule step addresses are run-relative
        while True:
            # identity snapshot: every completed device program
            # reassigns self.params, so `is` detects ANY training
            # progress - including a whole-epoch program that landed
            # before a later program's compile failed mid-epoch (the
            # histories alone would miss it and a retry would re-train
            # epoch 0 on top of the applied updates)
            params_before = self.params
            try:
                memory, duration = self._train_attempt(
                    epochs, formatter, training_history,
                    validation_history)
                break
            except Exception as exc:  # noqa: BLE001 - gated right below
                k = self._grad_accum_fallback(exc)
                progressed = bool(training_history or validation_history
                                  or self.params is not params_before)
                if (k is None or retries >= self._MAX_COMPILE_RETRIES
                        or progressed):
                    if (first_exc is not None and not progressed
                            and self.is_compile_failure(exc)):
                        # retries exhausted on the same failure class:
                        # the FIRST failure is the diagnostic one - the
                        # original batch-size program's error, not the
                        # error of whichever shrunken retry died last.
                        # A later NON-compile failure, or any failure
                        # AFTER training progressed (a different
                        # program died), is a different problem and
                        # re-raises as itself.
                        raise first_exc
                    raise
                first_exc = first_exc or exc
                retries += 1
                # loud by design (VERDICT r4): the alternative was a
                # silent skip in every sweep that hit the failing
                # program class
                logging.warning(
                    "train step failed to compile at batch %d (%s: "
                    "%.160s); retrying with grad_accum=%d (microbatches "
                    "of %d)", self.batch_size, type(exc).__name__, exc,
                    k, self.batch_size // k)
                if self._fuse_run:
                    logging.warning(
                        "--fuse-run abandoned for the retry: grad "
                        "accumulation needs the per-epoch path")
                    self._fuse_run = False
                self.grad_accum = k
                self._train_step_fn = None
                self._idx_step_fn = None
                self._epoch_fn = None
                self._run_fn = None

        logging.info(formatter.performance_message(memory, duration))
        device_peaks = getattr(self, "_last_device_peaks", {}) or {}
        if device_peaks:
            # a SEPARATE line: the perf line above stays byte-compatible
            # with the reference notebooks' regex
            rendered = ", ".join(
                f"{d}={mb:.1f}" for d, mb in sorted(device_peaks.items())
            )
            logging.info(f"Device HBM peaks (MiB): {rendered}")
        if self.guard is not None and self.guard.total_skipped:
            logging.info(
                f"non-finite guard: skipped {self.guard.total_skipped} "
                "bad step(s); training continued"
            )
        if self._faults is not None and self._faults.fired:
            logging.info(f"chaos: faults fired {self._faults.fired}")
        if self._profile is not None:
            self.recorder.record("profile", **self._profile.close())
        self.recorder.record(
            "run_summary",
            memory_mb=memory,
            duration_s=duration,
            device_peaks_mb=device_peaks,
            steps=self._steps_done,
            epochs=epochs,
            nan_skipped=(
                self.guard.total_skipped if self.guard is not None else 0
            ),
            faults_fired=(
                dict(self._faults.fired) if self._faults is not None else {}
            ),
            ledger=self._ledger_block(),
        )
        self.recorder.flush()

        if self.test_set is not None:
            self._evaluate(self.test_set, formatter)

        return self.params, training_history, validation_history

    def _train_attempt(self, epochs, formatter, training_history,
                       validation_history):
        """One full training attempt; returns ``(memory, duration)``.
        Split out of :meth:`train` so a compile-stage failure can fall
        back to grad accumulation and re-enter with rebuilt programs."""
        if self.DEVICE_DATA and not self._chaos_host_loop():
            if self._idx_step_fn is None:
                self._idx_step_fn = self._build_idx_train_step()
            if self._epoch_fn is None:
                self._epoch_fn = self._build_epoch_fn()
        elif self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
        if self._eval_step_fn is None:
            self._eval_step_fn = self._build_eval_step()

        # the whole run fuses into one device program when nothing needs
        # the host between batches or epochs: no per-epoch validation /
        # checkpointing, no per-batch progress logging
        fusable = (
            self.DEVICE_DATA
            and self.validation_set is None
            and epochs > 0
            # with dropout on, a partial final batch would draw its mask
            # over the fused path's zero-padded batch shape and diverge
            # from the per-epoch path's unpadded draw; keep the two paths
            # bit-identical by taking the per-epoch path in that case
            and not (self._dropout > 0.0 and self._has_partial_batch())
            # periodic checkpointing needs the host at epoch boundaries
            and not (self.checkpoint_every and self.checkpoint_dir)
            # the fused run's weighted loss (per-example mask) is not
            # expressible as equal-microbatch accumulation
            and self.grad_accum == 1
            # chaos injection and epoch-offset resume both need the host
            # at epoch (or step) boundaries
            and self._faults is None
            and self._start_epoch == 0
            # step-bounded profiling addresses individual steps
            and self._profile is None
            # per-step telemetry needs the host per epoch at least; an
            # EXPLICIT --fuse-run still wins (epoch-level events only)
            and (self._fuse_run or not self.recorder.enabled)
        )
        if self._fuse_run and not fusable:
            # the user explicitly asked for one-program training; falling
            # back silently would reintroduce the per-epoch host syncs
            # they are trying to eliminate
            raise ValueError(
                "--fuse-run needs a run with no host work between epochs: "
                "device-resident data, --no-validation, no "
                "--checkpoint-every, --grad-accum 1, no --faults schedule "
                "or epoch-offset resume, and (with dropout) a batch size "
                "dividing the training set"
            )
        fused_run = fusable and (
            self._fuse_run
            or not logging.getLogger().isEnabledFor(logging.INFO)
        )

        def train_inner():
            if fused_run:
                training_history.extend(self._train_run_fused(epochs))
                return
            # seed the best-model threshold from a resumed checkpoint so a
            # worse post-resume epoch cannot clobber best-model.ckpt
            best_loss = self._resume_best_loss
            try:
                for epoch in range(self._start_epoch, epochs):
                    if self._faults is not None:
                        self._faults.on_epoch_start(epoch)
                    self.sampler.set_epoch(epoch)
                    self._epoch = epoch
                    logging.info(formatter.epoch_start_message(epoch))
                    train_loss, train_acc = self._train_epoch(formatter)
                    training_history.append(train_loss)

                    if (
                        self.checkpoint_every
                        and (epoch + 1) % self.checkpoint_every == 0
                    ):
                        self._save_checkpoint(epoch, train_loss, best=False)

                    if self.validation_set is not None:
                        validation_loss, _ = self._evaluate(
                            self.validation_set, formatter, epoch
                        )
                        validation_history.append(validation_loss)
                        if best_loss is None or best_loss > validation_loss:
                            logging.info(
                                f"New best model in epoch {epoch + 1}"
                            )
                            best_loss = validation_loss
                            self._save_checkpoint(
                                epoch, validation_loss, best=True
                            )
            finally:
                # finally, and inside the timed region on purpose: an
                # async sharded save that has not landed is training time
                # still owed, and a later-epoch exception must not strand
                # the in-flight write un-finalized (the crash-resume case
                # checkpoints exist for)
                self._drain_checkpoint()

        _, memory, duration, device_peaks = measure_memory_and_time(
            train_inner, include_device_memory=True
        )
        self._last_device_peaks = device_peaks
        return memory, duration

    def _train_run_fused(self, epochs: int):
        """Run ``epochs`` epochs as one device program; returns the
        per-epoch train-loss history (reference normalization: sum of
        batch-mean losses / dataset size)."""
        if self._run_fn is None:
            self._run_fn = self._build_run_fn()
        features, labels = self._device_train_data()

        idx_rows, w_rows, key_rows = [], [], []
        num_batches = None
        for epoch in range(epochs):
            self.sampler.set_epoch(epoch)
            batches = self._epoch_index_batches()
            num_batches = len(batches)
            full_size = len(batches[0])
            for b in batches:
                idx, w = self._pad_batch(b, full_size)
                idx_rows.append(idx)
                w_rows.append(w)
            if self._dropout > 0.0:
                key_rows.append(self._epoch_dropout_keys(epoch, len(batches)))
        idx_mat = np.stack(idx_rows)
        w_mat = np.stack(w_rows)
        extra = (np.concatenate(key_rows),) if self._dropout > 0.0 else ()

        self.params, self.opt_state, losses, correct = self._run_fn(
            self.params, self.opt_state, features, labels, idx_mat, w_mat,
            *extra,
        )
        # the fused run's ONE host visit: the guard decides here - the
        # in-program apply_if_finite already rejected every non-finite
        # update, so the late check only delays the abort, never
        # corrupts state
        if self.guard is not None:
            self.guard.check(self.opt_state)
        losses = np.asarray(losses).reshape(epochs, num_batches)
        n = len(self.training_set)
        history = [float(losses[e].sum()) / n for e in range(epochs)]
        if self.recorder.enabled:
            # the fused run's telemetry is post-hoc by design (its whole
            # point is zero host visits): per-epoch losses only
            for e, loss in enumerate(history):
                self.recorder.record(
                    "epoch", epoch=e, steps=num_batches, loss=loss,
                    acc=None, wall_s=None, path="fused",
                )
        return history

    def _maybe_record_collectives(self, step_fn, *args):
        """Trace the LIVE step program once and record its per-step
        collective traffic (``evaluation/collectives.
        closed_jaxpr_collective_stats`` - scan trip counts multiplied in)
        plus its analytic FLOP count (``obs/flops.py`` - the efficiency
        ledger's MFU numerator) off the same ClosedJaxpr.  Tracing is
        abstract (no execution, no compile) and happens once per run,
        before the first dispatch.  Steps that are host functions
        (native-TCP DDP, the PS worker's push/pull) abort the trace on
        their first host conversion - telemetry then records the
        absence instead of failing the run."""
        if self._collectives_recorded or not self.recorder.enabled:
            return
        self._collectives_recorded = True
        from pytorch_distributed_rnn_tpu.evaluation.collectives import (
            closed_jaxpr_collective_stats,
        )
        from pytorch_distributed_rnn_tpu.obs.flops import (
            closed_jaxpr_flop_stats,
        )

        try:
            closed = jax.make_jaxpr(step_fn)(*args)
            stats = closed_jaxpr_collective_stats(closed)
            flops = closed_jaxpr_flop_stats(closed)
        except Exception as exc:  # host-loop steps are untraceable
            self.recorder.record(
                "collectives", ops=None, bytes_per_step=None,
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            return
        self._model_flops_per_step = flops["flops"]
        self._model_flops_exact = flops["exact"]
        self.recorder.record(
            "collectives", ops=stats,
            bytes_per_step=sum(s["bytes"] for s in stats.values()),
            model_flops_per_step=flops["flops"],
            model_flops_exact=flops["exact"],
            arg_bytes=flops["arg_bytes"],
            out_bytes=flops["out_bytes"],
        )

    def _ledger_block(self) -> dict:
        """run_summary's efficiency-ledger block: the traced FLOP count
        and the backend peak the ledger CLI divides it by, recorded
        run-side so offline readers need no jax and no hardware."""
        from pytorch_distributed_rnn_tpu.utils.hw import peak_flops

        devices = jax.devices()
        peak = peak_flops(jax.default_backend(), devices[0].device_kind)
        return {
            "model_flops_per_step": self._model_flops_per_step,
            "model_flops_exact": self._model_flops_exact,
            "backend": jax.default_backend(),
            "device_kind": devices[0].device_kind,
            "device_count": len(devices),
            "peak_flops_total":
                peak["peak_flops_per_device"] * len(devices),
            # True whenever the peak did not come off a datasheet (CPU
            # and unknown devices) - every ledger surface labels it
            "peak_flops_estimated": peak["estimated"],
        }

    def _note_recompile(self, fn, step: int, seconds: float, tm: float):
        """Emit a `compile` event when ``fn``'s trace cache grew past
        its warm-up compile: a post-warm-up RETRACE (shape drift, weak
        types, donation mismatch) that silently re-pays compile cost.
        Probes the jit cache size OUTSIDE any traced region (the
        trace-transparency contract), one attribute call per recorded
        step."""
        size_fn = getattr(fn, "_cache_size", None)
        if size_fn is None:
            return
        try:
            size = int(size_fn())
        except Exception:
            return
        key = id(fn)
        seen = self._trace_cache_seen.get(key)
        self._trace_cache_seen[key] = size
        # first observation (the warm-up compile itself) is expected
        # and priced by the ledger's first-step excess, not an event
        if seen is None or size <= seen:
            return
        self.recorder.record(
            "compile", step=step, seconds=seconds, cache_size=size,
            tm=tm,
        )

    def _chaos_host_loop(self) -> bool:
        """Whether an attached fault schedule forces the per-batch host
        loop: step-addressed faults (NaN injection, per-step kill/stall)
        need the host between optimizer steps, which the scanned
        device-resident programs by design do not visit."""
        return self._faults is not None and self._faults.has_step_events

    def _train_epoch(self, formatter):
        if not self.DEVICE_DATA or self._chaos_host_loop():
            return self._train_epoch_host(formatter)

        # per-batch progress moved INFO -> DEBUG (conscious fix, PARITY.md):
        # each progress message needs loss/correct on host, serializing one
        # device round-trip per batch; at INFO the epoch runs as one
        # scanned program and only epoch-level messages are emitted
        log_progress = logging.getLogger().isEnabledFor(logging.DEBUG)
        # telemetry and step-bounded profiling also need per-step dispatch
        # (to address/time individual steps), but NOT per-step host values:
        # losses stay device scalars until epoch end, and only the sampled
        # fence cadence pays a device round-trip
        recording = self.recorder.enabled
        features, labels = self._device_train_data()
        batches = self._epoch_index_batches()
        keys = (
            self._epoch_dropout_keys(self._epoch, len(batches))
            if self._dropout > 0.0
            else None
        )
        # host-side accumulators: each program's loss/metrics outputs are
        # replicated over the (possibly multi-process) mesh, so fetching
        # them immediately is legal on every rank - while accumulating
        # into a process-LOCAL device zero can land the sum on a device
        # other controllers cannot address.  Cost: at most two fetches per
        # epoch on the fast path (whole-epoch program + optional remainder
        # step), values the host needs for history/logging anyway.
        total_loss = 0.0
        total_correct = 0.0
        t_epoch = time.perf_counter()
        epoch_path = "scan"

        if log_progress or recording or self._profile is not None:
            epoch_path = "step"
            # run-relative step addresses, matching the host loop's
            # convention (and _steps_done's documented contract): a
            # resumed run's telemetry and --profile-steps ranges count
            # steps EXECUTED THIS RUN on every strategy
            step_base = self._steps_done
            losses, corrects, raw = [], [], []
            for batch_idx, idx in enumerate(batches):
                step = step_base + batch_idx
                extra = (keys[batch_idx],) if keys is not None else ()
                if recording:
                    self._maybe_record_collectives(
                        self._idx_step_fn, self.params,
                        self.opt_state, features, labels, idx, *extra,
                    )
                if self._profile is not None:
                    self._profile.on_step_start(step)
                t0 = time.perf_counter()
                self.params, self.opt_state, loss, metrics = self._idx_step_fn(
                    self.params, self.opt_state, features, labels, idx, *extra
                )
                dispatch_s = time.perf_counter() - t0
                fenced_s = None
                if recording and self.recorder.is_sample_step(step):
                    _fence(loss)
                    fenced_s = time.perf_counter() - t0
                if recording:
                    self._note_recompile(
                        self._idx_step_fn, step, dispatch_s, t0
                    )
                if self._profile is not None:
                    self._profile.on_step_end(step, fence_value=loss)
                self._steps_done = step + 1
                self.recorder.note_progress(step)
                if log_progress:
                    # the progress message needs values NOW - this path
                    # keeps the documented fetch-per-batch cost of -v
                    losses.append(float(loss))
                    corrects.append(float(metrics["correct"]))
                    logging.debug(
                        formatter.train_progress_message(
                            batch_idx=batch_idx,
                            batches=len(batches),
                            training_examples=len(idx),
                            correct=_correct_count(corrects[-1]),
                            loss=losses[-1],
                        )
                    )
                else:
                    losses.append(loss)
                    corrects.append(metrics["correct"])
                if recording:
                    raw.append((step, t0, dispatch_s, fenced_s))
            total_loss = sum(float(l) for l in losses)
            total_correct = sum(float(c) for c in corrects)
            if recording:
                # step events are emitted AFTER the loop: the deferred
                # float() fetches here are the same epoch-end fetch the
                # uninstrumented path already pays, not per-step syncs.
                # tm is overridden to the step's dispatch START so the
                # timeline exporter can synthesize the dispatch/device
                # sub-spans from the durations (obs/spans.py).
                for (step, t0, dispatch_s, fenced_s), loss_v in zip(
                    raw, losses
                ):
                    self.recorder.record(
                        "step", step=step, epoch=self._epoch,
                        loss=float(loss_v), dispatch_s=dispatch_s,
                        data_wait_s=0.0, fenced_s=fenced_s, tm=t0,
                    )
        else:
            # fast path: all equal-size batches as ONE scanned program,
            # the final partial batch (if any) as one extra step
            full = batches
            remainder = None
            if len(batches) > 1 and len(batches[-1]) != len(batches[0]):
                full, remainder = batches[:-1], batches[-1]
            if full:
                idx_mat = np.stack(full)
                extra = (keys[: len(full)],) if keys is not None else ()
                (
                    self.params,
                    self.opt_state,
                    loss_sum,
                    metrics_sum,
                ) = self._epoch_fn(
                    self.params, self.opt_state, features, labels, idx_mat,
                    *extra,
                )
                total_loss += float(loss_sum)
                total_correct += float(metrics_sum["correct"])
            if remainder is not None:
                extra = (keys[-1],) if keys is not None else ()
                self.params, self.opt_state, loss, metrics = self._idx_step_fn(
                    self.params, self.opt_state, features, labels, remainder,
                    *extra,
                )
                total_loss += float(loss)
                total_correct += float(metrics["correct"])

        # parity quirk kept: sum of batch-mean losses / dataset size
        train_loss = total_loss / len(self.training_set)
        train_acc = total_correct / len(self.training_set)
        # scanned paths visit the host once per epoch, so the non-finite
        # guard decides here (updates were already skipped in-program)
        if self.guard is not None:
            self.guard.check(self.opt_state)
        self.recorder.record(
            "epoch", epoch=self._epoch, steps=len(batches),
            loss=train_loss, acc=train_acc,
            wall_s=time.perf_counter() - t_epoch, path=epoch_path,
            tm=t_epoch,  # epoch START: the event doubles as a span
        )
        return train_loss, train_acc

    # host-path input pipeline: how many prepared batches ride ahead of
    # the consuming step (data/prefetch.py - the torch-DataLoader-worker
    # analogue: the next batch's async H2D upload overlaps this step)
    PREFETCH_DEPTH = 2
    # device-staged prefetch: the producer thread device_put()s each
    # prepared batch and blocks until the H2D copy lands, so next()
    # hands the consumer device-resident buffers and no step pays the
    # transfer inline (torch DataLoader pin_memory + non_blocking
    # analogue).  Subclass escape hatch for strategies whose batches
    # must stay host-side
    DEVICE_STAGED_PREFETCH = True

    def _prefetch_stage(self):
        """Producer-side staging callable for the host-path prefetch, or
        None to hand batches through untouched."""
        if not self.DEVICE_STAGED_PREFETCH:
            return None

        def stage(batch):
            return jax.block_until_ready(jax.device_put(batch))

        return stage

    def _train_epoch_host(self, formatter):
        """Materialized-batch loop (used when the strategy must act on
        host every step - parameter-server push/pull, native-DDP TCP
        allreduce - or the dataset exceeds device residence).

        Pipelined: batch prep/upload is prefetched ``PREFETCH_DEPTH``
        ahead (H2D overlaps compute), and the per-batch scalar fetches
        are deferred to epoch end so steps dispatch back-to-back - each
        ``float()`` would otherwise block the host on that step.  At
        DEBUG, per-batch progress needs the values NOW; that path keeps
        the fetch-per-batch loop (the documented cost of -v progress).
        """
        log_progress = logging.getLogger().isEnabledFor(logging.DEBUG)
        loader = self._train_loader()
        num_batches = len(loader)
        keys = (
            self._epoch_dropout_keys(self._epoch, num_batches)
            if self._dropout > 0.0
            else None
        )
        faults = self._faults
        epoch_base = self._steps_done  # run-relative fault addresses

        def source():
            for i, (f, l) in enumerate(loader):
                if faults is not None:
                    # loader-side faults (stall/exception) originate in
                    # the PRODUCER - a real loader failure's position -
                    # and must cross the prefetch thread to the consumer
                    faults.on_producer_item(epoch_base + i)
                yield self._prepare_batch(f, l)

        recording = self.recorder.enabled
        t_epoch = time.perf_counter()
        stream = prefetch(source(), depth=self.PREFETCH_DEPTH,
                          stage=self._prefetch_stage())
        # device-scalar accumulators, fetched after the loop: the
        # programs' loss/metrics outputs are replicated over the
        # (possibly multi-process) mesh, so a post-loop fetch is legal on
        # every rank - while accumulating into a process-LOCAL device
        # zero could land the sum on a device other controllers cannot
        # address
        losses, corrects, raw = [], [], []
        try:
            batch_iter = iter(stream)
            batch_idx = 0
            while True:
                # the wait for the prefetch producer IS the input-bound
                # signal: with the pipeline keeping up it is ~0, and any
                # stall here is time the device sat idle for data
                t_wait = time.perf_counter()
                try:
                    batch = next(batch_iter)
                except StopIteration:
                    break
                data_wait_s = time.perf_counter() - t_wait
                step = epoch_base + batch_idx
                if faults is not None:
                    faults.maybe_kill(step=step)
                    batch = faults.corrupt_batch(step, batch)
                extra = (keys[batch_idx],) if keys is not None else ()
                if recording:
                    self._maybe_record_collectives(
                        self._train_step_fn, self.params, self.opt_state,
                        batch, *extra,
                    )
                if self._profile is not None:
                    self._profile.on_step_start(step)
                t0 = time.perf_counter()
                # step fns with host collectives publish this step's
                # (comm_wait_s, comm_active_s) here; reset first so a
                # skipped publish can't replay the previous step's
                self._last_step_comm = None
                self.params, self.opt_state, loss, metrics = self._train_step_fn(
                    self.params, self.opt_state, batch, *extra
                )
                dispatch_s = time.perf_counter() - t0
                step_comm = self._last_step_comm
                fenced_s = None
                if recording and self.recorder.is_sample_step(step):
                    _fence(loss)
                    fenced_s = time.perf_counter() - t0
                if recording:
                    self._note_recompile(
                        self._train_step_fn, step, dispatch_s, t0
                    )
                if self._profile is not None:
                    self._profile.on_step_end(step, fence_value=loss)
                self._steps_done = step + 1
                self.recorder.note_progress(step)
                if self.guard is not None and faults is not None:
                    # chaos runs are per-batch already; deciding per step
                    # costs one counter fetch and aborts K+1 steps after
                    # divergence starts instead of at epoch end
                    self.guard.check(self.opt_state)
                if log_progress:
                    # the progress message needs the values NOW - accumulate
                    # the already-fetched floats instead of re-fetching at
                    # epoch end
                    losses.append(float(loss))
                    corrects.append(float(metrics["correct"]))
                    logging.debug(
                        formatter.train_progress_message(
                            batch_idx=batch_idx,
                            batches=num_batches,
                            training_examples=len(batch[0]),
                            correct=_correct_count(corrects[-1]),
                            loss=losses[-1],
                        )
                    )
                else:
                    losses.append(loss)
                    corrects.append(metrics["correct"])
                if recording:
                    raw.append((step, t0, dispatch_s, fenced_s, data_wait_s,
                                step_comm))
                batch_idx += 1
        finally:
            # an early exit (injected exception, guard abort) must not
            # leave the prefetch producer thread running behind us
            stream.close()

        total_loss = sum(float(l) for l in losses)
        total_correct = sum(float(c) for c in corrects)
        if recording:
            # step events emitted after the loop: the float() fetches are
            # the epoch-end fetch the uninstrumented path already pays.
            # tm = the step's dispatch start (see the device path above)
            for (step, t0, dispatch_s, fenced_s, data_wait_s,
                 step_comm), loss_v in zip(raw, losses):
                extra_fields = {}
                if step_comm is not None:
                    # None-not-0 convention: strategies without host
                    # collectives simply omit the comm fields
                    wait_s, active_s = step_comm
                    extra_fields["comm_wait_s"] = wait_s
                    # 1 - wait/active: the fraction of the step's wire
                    # time the host did NOT sit blocked for (0 for fully
                    # synchronous collectives); meaningless when the
                    # collectives cost ~nothing, absent when active is 0
                    if active_s > 0:
                        extra_fields["overlap_frac"] = max(
                            0.0, 1.0 - wait_s / active_s
                        )
                self.recorder.record(
                    "step", step=step, epoch=self._epoch,
                    loss=float(loss_v), dispatch_s=dispatch_s,
                    data_wait_s=data_wait_s, fenced_s=fenced_s, tm=t0,
                    **extra_fields,
                )
        # parity quirk kept: sum of batch-mean losses / dataset size
        train_loss = total_loss / len(self.training_set)
        train_acc = total_correct / len(self.training_set)
        if self.guard is not None:
            self.guard.check(self.opt_state)
        self.recorder.record(
            "epoch", epoch=self._epoch, steps=len(losses),
            loss=train_loss, acc=train_acc,
            wall_s=time.perf_counter() - t_epoch, path="host",
            tm=t_epoch,
        )
        return train_loss, train_acc

    def _evaluate(self, dataset, formatter, epoch=None):
        """Full-dataset evaluation in one batch (reference loads val/test
        with batch_size=len(dataset), base.py:53-54)."""
        # cache holds (dataset, batch): the strong reference keeps id()
        # stable (a collected dataset's id could be reused by a new one)
        key = id(dataset)
        cached = self._eval_data_cache.get(key)
        if cached is None or cached[0] is not dataset:
            features, labels = dataset[np.arange(len(dataset))]
            cached = (dataset, self._prepare_batch(features, labels))
            self._eval_data_cache[key] = cached
        batch = cached[1]
        # the float() fetch below fences the eval program, so the span's
        # extent is the honest wall time of the whole evaluation
        with self.recorder.span("eval", cat="eval", epoch=epoch):
            loss, metrics = self._eval_step_fn(self.params, batch)
            eval_loss = float(loss)  # one batch -> already the mean
            total_correct = float(metrics["correct"])
        num_examples = len(dataset)
        accuracy = total_correct / num_examples
        self.recorder.record(
            "eval", epoch=epoch, loss=eval_loss, acc=accuracy
        )
        logging.info(
            formatter.evaluation_message(
                accuracy, num_examples, epoch, eval_loss,
                _correct_count(total_correct)
            )
        )
        return eval_loss, accuracy

    # -- checkpointing -------------------------------------------------------

    def _checkpoint_state(self):
        """Hook: the (params, opt_state) a checkpoint writes.  Sharded
        strategies override to gather cross-process state first - such a
        gather is a COLLECTIVE, so this hook runs on every process
        unconditionally; only :meth:`_should_write_checkpoint` gates the
        file write."""
        return self.params, self.opt_state

    def _should_write_checkpoint(self) -> bool:
        """Hook: whether THIS process writes the file (multi-process
        strategies restrict to rank 0)."""
        return True

    def _checkpoint_template_state(self):
        """Hook: the (params, opt_state) TEMPLATE a gathered checkpoint
        deserializes into.  Sharded-update strategies return the
        standard unsharded layout (flax ``from_bytes`` only reads the
        tree structure, so abstract leaves are fine); everyone else
        restores straight into the live state."""
        return self.params, self.opt_state

    def _adopt_restored_state(self, params, opt_state):
        """Hook: install state restored in the UNSHARDED checkpoint
        layout.  Sharded-update strategies convert ``opt_state`` back to
        their live sharded layout here."""
        self.params, self.opt_state = params, opt_state

    def _save_checkpoint(self, epoch, loss, best=False):
        if self.checkpoint_dir is None:
            return
        t0 = time.perf_counter()
        self._write_checkpoint(epoch, loss, best)
        self.recorder.record(
            "checkpoint_save", epoch=epoch, best=bool(best),
            seconds=time.perf_counter() - t0,
            format=self.checkpoint_format,
            # an async sharded save only DISPATCHES here; the drain at
            # the next save / train end is where the rest of the cost
            # lands (inside the timed region either way)
            asynchronous=self.checkpoint_async,
        )

    def _write_checkpoint(self, epoch, loss, best=False):
        if self.checkpoint_format == "sharded":
            from pytorch_distributed_rnn_tpu.training.sharded_checkpoint import (  # noqa: E501 - lazy: orbax import is heavy
                save_sharded,
            )

            # no _checkpoint_state() gather and no rank gate: every
            # process hands orbax its OWN shards and rank coordination is
            # orbax's (meta sidecar written by process 0 inside).  At most
            # one save in flight: wait on the previous async write first
            # (orbax serializes on device arrays; overlapping two saves
            # of best-model would also race the directory rename).
            self._drain_checkpoint()
            self._pending_ckpt = save_sharded(
                self.checkpoint_dir, epoch, self.params, self.opt_state,
                loss, best=best, async_=self.checkpoint_async,
            )
            return
        params, opt_state = self._checkpoint_state()
        if not self._should_write_checkpoint():
            return
        save_checkpoint(
            self.checkpoint_dir, epoch, params, opt_state, loss, best=best
        )
        if not best and self.keep_checkpoints:
            # rotation only ever DELETES strictly-older epoch files, so
            # running it after each periodic write keeps exactly the
            # newest N without touching best-model.ckpt
            rotate_checkpoints(self.checkpoint_dir, self.keep_checkpoints)

    def _drain_checkpoint(self):
        """Block until the in-flight async sharded save (if any) is
        durable; called before the next save and at train end."""
        if self._pending_ckpt is not None:
            self._pending_ckpt.wait()
            self._pending_ckpt = None

    def resume_from(self, checkpoint_path, advance_epoch: bool = False):
        """Restore params/optimizer state (new capability; the reference's
        checkpoints were write-only).  Returns the checkpoint metadata.

        Dispatches on the path's shape: a ``.orbax`` DIRECTORY restores
        shard-by-shard onto the live state's shardings (no gather); a
        file is the gathered single-file format.

        ``advance_epoch=True`` (the auto-resume path) additionally makes
        ``train()`` continue from the checkpoint's epoch instead of
        retraining from epoch 0 on top of the restored state - a run
        killed after epoch E and restarted covers exactly the remaining
        epochs, reproducing the uninterrupted run."""
        from pytorch_distributed_rnn_tpu.training.sharded_checkpoint import (
            is_sharded_checkpoint,
            restore_sharded,
        )

        t0 = time.perf_counter()
        if is_sharded_checkpoint(checkpoint_path):
            self.params, self.opt_state, meta = restore_sharded(
                checkpoint_path, self.params, self.opt_state
            )
        elif Path(checkpoint_path).is_dir():
            # e.g. --resume models/ (the parent) - neither format; say so
            # instead of handing a directory to the single-file loader
            raise ValueError(
                f"{checkpoint_path} is a directory but not a sharded "
                "checkpoint - pass the .orbax dir itself (sharded) or "
                "the .ckpt file (gathered)"
            )
        else:
            template_p, template_st = self._checkpoint_template_state()
            params, opt_state, meta = load_checkpoint(
                checkpoint_path, template_p, template_st
            )
            self._adopt_restored_state(params, opt_state)
        self._resume_best_loss = meta["loss"]
        if advance_epoch:
            self._start_epoch = int(meta["epoch"])
        self.recorder.record(
            "checkpoint_restore", path=str(checkpoint_path),
            epoch=int(meta["epoch"]),
            seconds=time.perf_counter() - t0,
        )
        return meta
