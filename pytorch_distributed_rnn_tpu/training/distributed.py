"""Distributed (SPMD) trainers: DDP-flavor and Horovod-flavor strategies.

Capability parity with the reference's strategy stack
(``/root/reference/src/motion/trainer/distributed.py``, ``ddp.py``,
``horovod.py``): global-batch semantics (per-rank batch = batch_size //
world_size, ``distributed.py:48-49``), epoch-seeded sharded sampling,
rank-tagged logging, rank-0-only evaluation and checkpointing, and the two
allreduce flavors (DDP: sync after backward; Horovod: sync inside the
optimizer step, with parameter broadcast at ``train()`` entry,
``horovod.py:33-42``).

TPU-native design: "ranks" are positions along the mesh's ``dp`` axis under
one controller - process-per-rank MPI topology is replaced by ONE jitted
SPMD program whose gradient ``pmean`` lowers to XLA AllReduce over ICI.
Each global batch is assembled rank-major from the per-rank sampler shards,
so device r's shard of the batch is exactly what MPI rank r would have
loaded.  Consciously fixed (documented in PARITY.md): train metrics are
global (the reference under-reports per-rank accuracy by world_size,
``base.py:128-129``); evaluation runs once on the controller, equivalent to
the reference's rank-0-only evaluation (``distributed.py:20-22``).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_rnn_tpu.data.sampler import DistributedSampler
from pytorch_distributed_rnn_tpu.parallel.dp import (
    make_spmd_epoch_fn,
    make_spmd_idx_train_step,
    make_spmd_run_fn,
    make_spmd_train_step,
)
from pytorch_distributed_rnn_tpu.parallel.mesh import make_mesh
from pytorch_distributed_rnn_tpu.parallel.sharded_update import ShardedUpdate
from pytorch_distributed_rnn_tpu.training.base import Trainer
from pytorch_distributed_rnn_tpu.training.formatter import TrainingMessageFormatter


class SpmdTrainer(Trainer):
    """Shared machinery for the mesh-data-parallel strategies."""

    # grad accumulation lives in _make_grad_step; the SPMD step factories
    # (parallel/dp.py) bypass it, so reject the flag instead of silently
    # ignoring it
    SUPPORTS_GRAD_ACCUM = False
    # pure-DP: the whole optimizer state is redundantly replicated, so
    # the cross-replica sharded update (2004.13336) applies verbatim
    SUPPORTS_SHARDED_UPDATE = True

    SYNC = "backward"

    def __init__(
        self,
        model,
        training_set,
        batch_size: int,
        learning_rate: float,
        validation_set=None,
        test_set=None,
        checkpoint_dir=None,
        seed: int | None = None,
        mesh=None,
        axis: str = "dp",
        checkpoint_every: int = 0,
        grad_accum: int = 1,
        fuse_run: bool = False,
        checkpoint_format: str = "gathered",
        checkpoint_async: bool = False,
        **kwargs,  # resilience knobs (faults/max_bad_steps/keep_checkpoints)
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.axis = axis
        world_size = self._data_world_size()

        sampler = DistributedSampler(
            len(training_set), num_replicas=world_size, rank=0, seed=seed or 0
        )
        super().__init__(
            model=model,
            training_set=training_set,
            validation_set=validation_set,
            test_set=test_set,
            batch_size=batch_size,
            learning_rate=learning_rate,
            checkpoint_dir=checkpoint_dir,
            sampler=sampler,
            seed=seed,
            checkpoint_every=checkpoint_every,
            grad_accum=grad_accum,
            fuse_run=fuse_run,
            checkpoint_format=checkpoint_format,
            checkpoint_async=checkpoint_async,
            **kwargs,
        )
        self.world_size = world_size
        # single controller: one process reports as rank 0.  In a
        # multi-controller world (PDRNN_COORDINATOR set, mesh spanning
        # processes) each process tags its logs with its process index and
        # only process 0 checkpoints / writes history - the reference's
        # rank-0-only convention (distributed.py:60-62).  Every process
        # MUST still execute the identical device-program sequence (the
        # collectives are global), so datasets are not dropped on
        # non-zero ranks; host-side evaluation is process-local.
        self.rank = jax.process_index()

    def _data_world_size(self) -> int:
        """How many equal shards each global batch splits into - the
        sampler/loader "world".  Default: the dp axis; strategies that
        shard data over MORE axes (the moe dp x ep layout) override."""
        return self.mesh.shape[self.axis]

    def _get_formatter(self, epochs):
        return TrainingMessageFormatter(epochs, self.rank)

    def _should_write_checkpoint(self) -> bool:
        # rank-0-only writes (reference distributed.py:60-62); the
        # _checkpoint_state hook still runs on every process first, so a
        # sharded strategy's collective gather cannot deadlock here
        return self.rank == 0

    def _fold_rank(self, key):
        # independent dropout mask per dp shard (torch DDP has one RNG
        # stream per rank); the grad pmean keeps params identical anyway
        return jax.random.fold_in(key, jax.lax.axis_index(self.axis))

    def _init_opt_state(self):
        # --sharded-update (2004.13336): optimizer state as ONE flat
        # padded vector sharded along the dp axis, initialized in place
        # on the mesh so the full mu/nu never materialize per device.
        # The guard-wrapped optimizer needs the cross-shard poison psum
        # (see ShardedUpdate) so its skip decision stays global.
        self._shard_update = None
        if self.sharded_update and self.SUPPORTS_SHARDED_UPDATE:
            self._shard_update = ShardedUpdate(
                self.optimizer,
                self.params,
                self.mesh.shape[self.axis],
                axis=self.axis,
                poison_nonfinite=self.guard is not None,
            )
            return self._shard_update.init_opt_state(self.params,
                                                     mesh=self.mesh)
        return super()._init_opt_state()

    def _checkpoint_state(self):
        # checkpoints always carry the UNSHARDED layout so --resume,
        # the PS, serving, and streaming consumers are layout-agnostic
        if self._shard_update is not None:
            return self.params, self._shard_update.replicated_opt_state(
                self.opt_state
            )
        return super()._checkpoint_state()

    def _checkpoint_template_state(self):
        if self._shard_update is not None:
            return self.params, jax.eval_shape(
                self.optimizer.init, self.params
            )
        return super()._checkpoint_template_state()

    def _adopt_restored_state(self, params, opt_state):
        if self._shard_update is not None:
            self.params = params
            self.opt_state = self._shard_update.flat_opt_state(opt_state)
        else:
            super()._adopt_restored_state(params, opt_state)

    def _build_train_step(self):
        return make_spmd_train_step(
            self._loss_and_metrics,
            self.optimizer,
            self.mesh,
            axis=self.axis,
            sync=self.SYNC,
            with_key=self._dropout > 0.0,
            sharded=self._shard_update,
        )

    def _build_idx_train_step(self):
        return make_spmd_idx_train_step(
            self._loss_and_metrics,
            self.optimizer,
            self.mesh,
            axis=self.axis,
            sync=self.SYNC,
            with_key=self._dropout > 0.0,
            sharded=self._shard_update,
        )

    def _build_epoch_fn(self):
        return make_spmd_epoch_fn(
            self._loss_and_metrics,
            self.optimizer,
            self.mesh,
            axis=self.axis,
            sync=self.SYNC,
            with_key=self._dropout > 0.0,
            sharded=self._shard_update,
        )

    def _build_run_fn(self):
        return make_spmd_run_fn(
            self._weighted_loss_and_metrics,
            self.optimizer,
            self.mesh,
            axis=self.axis,
            sync=self.SYNC,
            with_key=self._dropout > 0.0,
            sharded=self._shard_update,
        )

    def _data_sharding(self):
        # dataset replicated over the mesh; per-batch index vectors shard
        # along dp so each device gathers its rank's micro-batch locally
        return NamedSharding(self.mesh, P())

    def _epoch_index_batches(self):
        """Rank-major global-batch index vectors: device r's shard of each
        batch is exactly what MPI rank r would have loaded (per-rank batch
        = batch_size // world_size, reference ``distributed.py:48-49``)."""
        per_rank_bs = max(1, self.batch_size // self.world_size)
        shards = self.sampler.global_indices()  # (world, num_samples)
        num_samples = shards.shape[1]
        return [
            shards[:, start : start + per_rank_bs].reshape(-1)
            for start in range(0, num_samples, per_rank_bs)
        ]

    def _pad_batch(self, b, full_size):
        """Rank-major padding: each rank's chunk is padded independently so
        sharding the padded batch along ``dp`` keeps rank alignment (and
        every rank carries the same number of live examples, which makes
        the pmean of local weighted means exact)."""
        if len(b) == full_size:
            return b, np.ones(full_size, np.float32)
        world = self.world_size
        per_rank_full = full_size // world
        chunk = b.reshape(world, -1)
        pad = per_rank_full - chunk.shape[1]
        idx = np.concatenate(
            [chunk, np.zeros((world, pad), dtype=b.dtype)], axis=1
        ).reshape(-1)
        w = np.concatenate(
            [
                np.ones_like(chunk, dtype=np.float32),
                np.zeros((world, pad), np.float32),
            ],
            axis=1,
        ).reshape(-1)
        return idx, w

    def _train_loader(self):
        """Yield rank-major global batches.

        Per-rank batch size is ``batch_size // world_size``
        (reference semantics); each yielded global batch stacks every
        rank's equally-sized chunk, so sharding its leading dim along
        ``dp`` reproduces exactly the per-rank loads of the MPI layout -
        including the final (smaller but still equal-per-rank) batch from
        the wrap-padded shards.
        """
        per_rank_bs = max(1, self.batch_size // self.world_size)
        shards = self.sampler.global_indices()  # (world, num_samples)
        features = self.training_set.features
        labels = self.training_set.labels

        def generator():
            num_samples = shards.shape[1]
            for start in range(0, num_samples, per_rank_bs):
                chunk = shards[:, start : start + per_rank_bs]  # (world, bs_r)
                idx = chunk.reshape(-1)  # rank-major
                yield features[idx], labels[idx]

        class _Loader:
            def __iter__(self):
                return generator()

            def __len__(self):
                return -(-shards.shape[1] // per_rank_bs)

        return _Loader()


class DDPTrainer(SpmdTrainer):
    """``distributed`` strategy: gradients allreduced right after backward
    (torch DDP reducer analogue, ``/root/reference/src/motion/trainer/
    ddp.py:19``).  Parameter sync at construction is implicit: the SPMD
    program holds ONE replicated copy of the params - the broadcast that
    DDP's wrapper performs is structural here."""

    SYNC = "backward"


class HorovodTrainer(SpmdTrainer):
    """``horovod`` strategy: raw local gradients are handed to a
    distributed optimizer that allreduces inside its update step
    (``hvd.DistributedOptimizer`` analogue), and parameters are
    re-synchronized at ``train()`` entry (``hvd.broadcast_parameters``
    analogue, ``/root/reference/src/motion/trainer/horovod.py:40-42``)."""

    SYNC = "step"
