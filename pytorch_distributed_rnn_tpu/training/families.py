"""Shared model-family construction for the native-transport strategies.

The registry's ``train()`` builds families for the in-process strategies;
``distributed-native`` and ``parameter-server`` have their own entrypoints
(world topology from env / explicit ranks) and previously hard-coded the
motion RNN - the strategy x family matrix hole VERDICT r2 weak #6 called
out: the two strategies that exercise the C++ TCP transport never saw the
models that stress it.  This module gives them the same family surface
(``rnn``, ``char``, ``attention``, and dense-exact ``moe`` - expert
gradients are ordinary pytree leaves over the wire; expert PARALLELISM
stays the mesh strategy's ``ep`` axis) with the same loud flag rejects.

Contract: ``load_datasets`` returns family-appropriate (train, valid,
test); ``build_model`` returns the model with every unsupported flag
rejected loudly; ``wrap_trainer`` mixes the family's loss surface over
the strategy's Trainer class (the char-LM's next-token loss,
``training/lm.py``) - classification families pass through.
"""

from __future__ import annotations


def family_of(args) -> str:
    return getattr(args, "model", "rnn")


def require_family(args, allowed, strategy: str):
    """Early, loud gate for strategies that wire a subset of families -
    fails before any dataset/backend work."""
    fam = family_of(args)
    if fam not in allowed:
        raise SystemExit(
            f"{strategy} trains the {'/'.join(allowed)} families - "
            f"--model {fam} is not wired here"
        )


def load_datasets(args):
    """(train, validation, test) for the selected family."""
    if family_of(args) == "char":
        from pytorch_distributed_rnn_tpu.data.text import TextDataset

        seq_length = getattr(args, "seq_length", None)
        if seq_length is None:
            seq_length = 128
        elif seq_length < 1:
            raise SystemExit(
                f"--seq-length must be >= 1, got {seq_length}"
            )
        return TextDataset.load(
            args.dataset_path,
            seq_length=seq_length,
            validation_fraction=args.validation_fraction,
            seed=args.seed,
        )
    if getattr(args, "seq_length", None) is not None:
        raise SystemExit(
            "--seq-length only applies to --model char (motion/attention "
            "sequence length is a property of the HAR data)"
        )
    from pytorch_distributed_rnn_tpu.data import MotionDataset

    return MotionDataset.load(
        args.dataset_path,
        output_path=args.output_path,
        validation_fraction=args.validation_fraction,
        seed=args.seed,
    )


def build_model(args, training_set):
    """The family's model from the CLI flags, rejecting what it cannot
    honor (the PARITY.md dead-flag principle)."""
    from pytorch_distributed_rnn_tpu.data import MotionDataset

    fam = family_of(args)
    if fam == "char":
        from pytorch_distributed_rnn_tpu.models import CharRNN

        return CharRNN(
            vocab_size=training_set.vocab_size,
            embed_dim=args.hidden_units,
            hidden_dim=args.hidden_units,
            layer_dim=args.stacked_layer,
            cell=getattr(args, "cell", "lstm"),
            precision=getattr(args, "precision", "f32"),
            remat=getattr(args, "remat", False),
            dropout=getattr(args, "dropout", 0.0) or 0.0,
        )
    if fam == "attention":
        from pytorch_distributed_rnn_tpu.models import AttentionClassifier

        if getattr(args, "cell", "lstm") != "lstm":
            raise SystemExit(
                "--model attention does not support: --cell gru "
                "(the encoder has no recurrent cell)"
            )
        return AttentionClassifier(
            input_dim=training_set.num_features,
            dim=args.hidden_units,
            depth=args.stacked_layer,
            num_heads=getattr(args, "num_heads", 4),
            output_dim=len(MotionDataset.LABELS),
            dropout=getattr(args, "dropout", 0.0) or 0.0,
            precision=getattr(args, "precision", "f32"),
            remat=getattr(args, "remat", False),
        )
    if fam == "moe":
        from pytorch_distributed_rnn_tpu.models import MoEClassifier

        if getattr(args, "dropout", 0.0):
            raise SystemExit(
                "--model moe does not support: --dropout "
                "(pass --dropout 0; the CLI default 0.1 mirrors the "
                "reference surface)"
            )
        return MoEClassifier(
            input_dim=training_set.num_features,
            hidden_dim=args.hidden_units,
            layer_dim=args.stacked_layer,
            output_dim=len(MotionDataset.LABELS),
            num_experts=getattr(args, "num_experts", 4),
            num_selected=getattr(args, "moe_top_k", 1),
            router_type=getattr(args, "moe_router", "token"),
            capacity_factor=getattr(args, "moe_capacity_factor", 2.0),
            group_size=getattr(args, "moe_group_size", None),
            cell=getattr(args, "cell", "lstm"),
            precision=getattr(args, "precision", "f32"),
            remat=getattr(args, "remat", False),
        )
    if fam != "rnn":
        raise SystemExit(
            f"--model {fam} is not wired into this strategy - supported "
            "here: rnn, char, attention, moe"
        )
    from pytorch_distributed_rnn_tpu.models import MotionModel

    return MotionModel(
        input_dim=training_set.num_features,
        hidden_dim=args.hidden_units,
        layer_dim=args.stacked_layer,
        output_dim=len(MotionDataset.LABELS),
        cell=getattr(args, "cell", "lstm"),
        precision=getattr(args, "precision", "f32"),
        remat=getattr(args, "remat", False),
        dropout=getattr(args, "dropout", 0.0) or 0.0,
    )


def wrap_trainer(args, trainer_class):
    """The strategy's Trainer class with the family's loss mixed in.

    The mesh strategy's factory carries ``OWNS_LM_LOSS``/``OWNS_MOE_LOSS``
    markers (its shard_mapped programs wire the family loss themselves) -
    those pass through unwrapped; rnn/attention always pass through (the
    base classification loss is theirs already)."""
    if family_of(args) == "char" and not getattr(
        trainer_class, "OWNS_LM_LOSS", False
    ):
        from pytorch_distributed_rnn_tpu.training.lm import wrap_lm_trainer

        return wrap_lm_trainer(trainer_class)
    if family_of(args) == "moe" and not getattr(
        trainer_class, "OWNS_MOE_LOSS", False
    ):
        from pytorch_distributed_rnn_tpu.training.moe import wrap_moe_trainer

        return wrap_moe_trainer(trainer_class)
    return trainer_class
