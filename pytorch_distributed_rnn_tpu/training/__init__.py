"""Trainer registry: strategy selection by CLI subcommand.

Mirrors the reference's inversion (``/root/reference/src/motion/trainer/
__init__.py:10-18``): subcommands map to Trainer classes; everything else -
dataset loading, model construction, training, history dump - is shared.
"""

from __future__ import annotations

import json
import logging

from pytorch_distributed_rnn_tpu.training.base import Trainer
from pytorch_distributed_rnn_tpu.training.distributed import (
    DDPTrainer,
    HorovodTrainer,
    SpmdTrainer,
)
from pytorch_distributed_rnn_tpu.training.mesh import MeshTrainer

__all__ = [
    "Trainer",
    "SpmdTrainer",
    "DDPTrainer",
    "HorovodTrainer",
    "MeshTrainer",
    "add_sub_commands",
    "train",
]


def _zero_trainer():
    from pytorch_distributed_rnn_tpu.training.zero import ZeroTrainer

    return ZeroTrainer


def add_sub_commands(sub_parser):
    for name, cls in (
        ("local", Trainer),
        ("distributed", DDPTrainer),
        ("horovod", HorovodTrainer),
    ):
        parser = sub_parser.add_parser(name)
        parser.set_defaults(func=lambda args, cls=cls: train(args, cls))

    # ZeRO/FSDP sharded-state strategy (new capability: the reference
    # keeps a full replica per rank, ddp.py:19; SURVEY parallelism
    # checklist's one empty row)
    fsdp = sub_parser.add_parser("fsdp")
    fsdp.set_defaults(func=lambda args: train(args, _zero_trainer()))

    # process-per-rank DDP over the native TCP collectives (the mpirun
    # analogue); world topology from MASTER_ADDR/PORT/RANK/WORLD_SIZE env
    native = sub_parser.add_parser("distributed-native")

    def _native(args):
        from pytorch_distributed_rnn_tpu.training.native_ddp import execute

        return execute(args)

    native.set_defaults(func=_native)

    # composed-mesh strategy: dp plus one of sp/tp/pp on the same shared
    # loop (new capability; the reference's only axis is DP - SURVEY §2
    # parallelism checklist)
    mesh_p = sub_parser.add_parser("mesh")
    mesh_p.add_argument(
        "--mesh", default="dp=-1", metavar="SPEC",
        help="mesh axes, e.g. dp=2,sp=4 (sp: time-sharded wavefront LSTM; "
        "tp: Megatron gate/head sharding; pp: GPipe stages; -1 = all "
        "remaining devices)",
    )
    mesh_p.add_argument(
        "--sp-schedule", choices=["wavefront", "sequential"],
        default="wavefront",
    )
    mesh_p.add_argument("--num-microbatches", type=int, default=4)
    mesh_p.add_argument(
        "--pp-schedule", choices=["gpipe", "1f1b", "interleaved"],
        default="gpipe",
        help="pipeline schedule for pp meshes: gpipe (fill-drain forward, "
        "XLA-transposed backward), 1f1b (PipeDream-flush: each "
        "microbatch's backward interleaves right after its forward, "
        "bounding live activations to the in-flight limit), or "
        "interleaved (Megatron virtual stages: each device owns "
        "--pp-chunks model chunks placed round-robin, shrinking the "
        "pipeline bubble; motion + char families)",
    )
    mesh_p.add_argument(
        "--pp-chunks", type=int, default=2, metavar="V",
        help="virtual model chunks per device for --pp-schedule "
        "interleaved (pp x V must divide --stacked-layer)",
    )

    def _mesh(args):
        from pytorch_distributed_rnn_tpu.training.mesh import (
            mesh_trainer_factory,
        )

        return train(args, mesh_trainer_factory(args))

    mesh_p.set_defaults(func=_mesh)


def train(args, trainer_class):
    # basicConfig (not just setLevel): module-level loggers like the
    # dataset's need a root handler installed or their records vanish into
    # logging.lastResort at WARNING.
    logging.basicConfig(level=args.log)
    logging.getLogger().setLevel(args.log)

    # ONE family-generic path for all four CLI families (rnn, char,
    # attention, moe): families.load_datasets rejects --seq-length
    # off-char; build_model carries every family's loud flag rejects (the
    # ONE construction path, shared with distributed-native and the
    # parameter server); wrap_trainer mixes in the char-LM / moe loss
    # surface where the strategy does not own it (the mesh factory's
    # OWNS_*_LOSS markers pass through).
    from pytorch_distributed_rnn_tpu.training import families

    training_set, validation_set, test_set = _log_and_trim_datasets(
        args, *families.load_datasets(args)
    )
    model = families.build_model(args, training_set)
    return _run_trainer(
        args, families.wrap_trainer(args, trainer_class), model,
        (training_set, validation_set, test_set),
    )


def _log_and_trim_datasets(args, training_set, validation_set, test_set):
    """Shared dataset logging + ``--no-validation`` trimming for every
    model family's CLI path."""
    logging.info(f"Training set of size {len(training_set)}")
    if args.no_validation:
        return training_set, None, None
    logging.info(f"Validation set of size {len(validation_set)}")
    logging.info(f"Test set of size {len(test_set)}")
    return training_set, validation_set, test_set


def _run_trainer(args, trainer_class, model, datasets):
    """The strategy-independent tail of every CLI run: construct, resume,
    (optionally trace,) train, dump rank-0 history."""
    import jax

    from pytorch_distributed_rnn_tpu.obs import (
        MetricsRecorder,
        StepTraceCapture,
    )
    from pytorch_distributed_rnn_tpu.resilience import FaultSchedule

    # resolve() also bridges net events onto the transport's
    # PDRNN_FAULT_* contract before any communicator is constructed
    faults = FaultSchedule.resolve(args)
    if faults is not None:
        logging.warning(f"chaos schedule active: {faults}")

    # structured telemetry (obs/): --metrics flag beats the PDRNN_METRICS
    # env; rank-tagged per controller process so multi-controller worlds
    # never share a sidecar.  NULL recorder (zero overhead) when off.
    recorder = MetricsRecorder.resolve(args, rank=jax.process_index())
    profile_steps = StepTraceCapture.resolve(args)

    # live plane (obs/live.py): --live / PDRNN_LIVE - rank 0 serves the
    # /metrics + /health aggregator, every rank runs the watchdog; None
    # (nothing constructed, no threads) when live export is off
    plane = None
    if recorder.enabled:
        from pytorch_distributed_rnn_tpu.obs.live import LivePlane
        from pytorch_distributed_rnn_tpu.obs.watchdog import (
            install_stack_dump_handler,
        )

        # kill -USR2 <pid>: all-thread stack dump next to the sidecar
        install_stack_dump_handler(recorder.path)
        plane = LivePlane.resolve(
            args, recorder, rank=jax.process_index(), role="trainer",
            faults=faults,
        )

    training_set, validation_set, test_set = datasets
    trainer = trainer_class(
        model=model,
        training_set=training_set,
        validation_set=validation_set,
        test_set=test_set,
        batch_size=args.batch_size,
        learning_rate=args.learning_rate,
        checkpoint_dir=args.checkpoint_directory,
        seed=args.seed,
        checkpoint_every=getattr(args, "checkpoint_every", 0),
        grad_accum=getattr(args, "grad_accum", 1),
        fuse_run=getattr(args, "fuse_run", False),
        checkpoint_format=getattr(args, "checkpoint_format", "gathered"),
        checkpoint_async=getattr(args, "checkpoint_async", False),
        faults=faults,
        max_bad_steps=getattr(args, "max_bad_steps", 0),
        keep_checkpoints=getattr(args, "keep_checkpoints", 0),
        recorder=recorder,
        profile_steps=profile_steps,
        sharded_update=getattr(args, "sharded_update", True),
    )

    resume = getattr(args, "resume", None)
    if resume is not None and str(resume) == "auto":
        # crash-restart contract: newest VALID checkpoint wins, corrupt
        # files fall back to the previous one, none = fresh start
        from pytorch_distributed_rnn_tpu.resilience import resume_latest

        meta = resume_latest(trainer, args.checkpoint_directory)
        if meta is None:
            logging.info(
                "--resume auto: no usable checkpoint in "
                f"{args.checkpoint_directory}; starting fresh"
            )
    elif resume:
        meta = trainer.resume_from(resume)
        logging.info(f"Resumed from {resume} at epoch {meta['epoch']}")

    logging.info(f"Training model for {args.epochs} epochs...")
    import contextlib

    profile_dir = getattr(args, "profile", None)
    if profile_dir and profile_steps is None:
        # step-level device tracing (new capability - the reference only
        # had whole-run wall-clock + RSS, SURVEY.md §5 "Tracing").  With
        # --profile-steps the capture is step-bounded and owned by the
        # trainer's StepTraceCapture instead of a whole-run trace.
        trace_cm = jax.profiler.trace(str(profile_dir))
    else:
        trace_cm = contextlib.nullcontext()
    try:
        with trace_cm:
            _, train_history, validation_history = trainer.train(
                epochs=args.epochs
            )
    finally:
        # the writer thread must drain even when training raises - the
        # partial telemetry of a crashed run is exactly what the perf-line
        # pipeline always lost.  Plane closes AFTER the recorder so the
        # final (finished) digest lands before the HTTP server goes away.
        recorder.close()
        if plane is not None:
            plane.close()
    history = {
        "train_history": train_history,
        "validation_history": validation_history,
    }
    if jax.process_index() == 0:  # rank-0-only output in a world
        with open("history.json", "w") as file:
            json.dump(history, file)
    return trainer
