"""Scaling-study plots (the Experiments.ipynb plotting cells, scriptable).

Reproduces the reference's figure set — training time vs node count per
trainer, rank-0 and aggregate memory vs node count — from the measurement
dataframe, writing PNG/PDF instead of living in a notebook.
"""

from __future__ import annotations

import matplotlib

matplotlib.use("Agg")

import matplotlib.pyplot as plt

from pytorch_distributed_rnn_tpu.evaluation.analysis import (
    aggregate_measurements,
)


def plot_scaling(df, path, batch_size=None):
    """Write a 3-panel scaling figure: duration, throughput, memory vs
    device count, one line per trainer.  Returns the figure path."""
    agg = aggregate_measurements(df)
    if batch_size is not None:
        agg = agg[agg["batch_size"] == batch_size]
    if agg.empty:
        raise ValueError("no rank-0 measurements to plot")

    fig, axes = plt.subplots(1, 3, figsize=(15, 4))
    for trainer, group in agg.groupby("trainer"):
        group = group.sort_values("devices")
        axes[0].plot(group["devices"], group["duration_s"], "o-", label=trainer)
        axes[1].plot(group["devices"], group["seq_per_sec"], "o-", label=trainer)
        axes[2].plot(group["devices"], group["memory_mb"], "o-", label=trainer)

    for ax, ylabel in zip(
        axes, ["training duration (s)", "throughput (seq/s)", "rank-0 RSS (MB)"]
    ):
        ax.set_xlabel("devices")
        ax.set_ylabel(ylabel)
        ax.legend()
        ax.grid(True, alpha=0.3)
    title = "scaling study" + (
        f" (batch size {batch_size})" if batch_size is not None else ""
    )
    fig.suptitle(title)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def plot_network(df, path):
    """Write the network-perturbation figure (the Experiments_network.ipynb
    plots): per-run training duration (slowest reporting rank - PS masters
    never train, so rank 0 may not report) vs injected delay (ms) and loss
    probability, mean over repeated runs, one panel per rule type.
    Returns the figure path."""
    # PS runs emit perf lines from worker ranks (the master never trains),
    # so "the run's duration" is the slowest reporting rank, not rank 0
    faulted = df[df["rule_type"].notna()]
    if faulted.empty:
        raise ValueError("no measurements with fault rules to plot")

    rule_types = sorted(faulted["rule_type"].unique())
    fig, axes = plt.subplots(1, len(rule_types), figsize=(5 * len(rule_types), 4))
    if len(rule_types) == 1:
        axes = [axes]
    for ax, rule in zip(axes, rule_types):
        sub = faulted[faulted["rule_type"] == rule]
        for trainer, group in sub.groupby("trainer"):
            # slowest rank within each run, then mean over repeated runs
            # (same repeat handling as the scaling figure's aggregation)
            per_run = group.groupby(["rule_value", "run"])["duration_s"].max()
            agg = per_run.groupby("rule_value").mean().reset_index()
            ax.plot(agg["rule_value"], agg["duration_s"], "o-", label=trainer)
        ax.set_xlabel("delay (ms)" if rule == "delay" else "loss probability")
        ax.set_ylabel("training duration (s)")
        ax.set_title(f"injected {rule}")
        ax.legend()
        ax.grid(True, alpha=0.3)
    fig.suptitle("network perturbation (native-transport fault injection)")
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def plot_bubble_fractions(path, *, stages: int = 4,
                          microbatches=(2, 4, 8, 16)):
    """Pipeline-schedule slot-bubble accounting across microbatch counts:
    gpipe vs 1f1b vs interleaved (2 and 4 virtual chunks per device).
    Pure timetable math (``parallel/pp.py:pp_schedule_stats``) - the
    figure the collective report's per-program schedule rows come from.
    Note each interleaved tick covers 1/V of a device's layers, so equal
    slot-bubble at higher V still means less wall-clock bubble."""
    from pytorch_distributed_rnn_tpu.parallel.pp import pp_schedule_stats

    series = (
        ("gpipe", dict(schedule="gpipe")),
        ("1f1b", dict(schedule="1f1b")),
        ("interleaved V=2", dict(schedule="interleaved", num_chunks=2)),
        ("interleaved V=4", dict(schedule="interleaved", num_chunks=4)),
    )
    fig, ax = plt.subplots(figsize=(6, 4))
    for label, kw in series:
        fracs = [
            pp_schedule_stats(stages, m, **kw)["bubble_fraction"]
            for m in microbatches
        ]
        ax.plot(microbatches, fracs, "o-", label=label)
    ax.set_xlabel("microbatches M")
    ax.set_ylabel("bubble fraction (idle device-ticks / total)")
    ax.set_title(f"pipeline schedule bubble, S={stages} stages")
    ax.set_xscale("log", base=2)
    ax.set_xticks(list(microbatches))
    ax.set_xticklabels([str(m) for m in microbatches])
    ax.legend()
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path
