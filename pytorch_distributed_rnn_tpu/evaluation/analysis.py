"""Perf-line parsing and measurement aggregation.

The regex is the notebooks' own (``Experiments.ipynb`` cell 2), extended to
capture every rank's line rather than only rank 0's so per-node and
aggregate memory plots (cells 5-7) are both derivable.
"""

from __future__ import annotations

import json
import re
from pathlib import Path

import pandas as pd

# The machine-readable telemetry contract (formatter.py:27).  Rank is part
# of the line; the notebooks anchored on rank 0 ('0: Memory Usage: ...').
# The value pattern is wider than the notebooks' \d+\.\d+ on purpose:
# performance_message formats RAW floats, so a sub-millisecond duration
# renders as '5e-05' and an integer-valued memory as '700' - the original
# regex silently dropped both (the formatter<->parser round-trip test in
# tests/test_evaluation.py pins the contract).
_FLOAT = r"\d+(?:\.\d+)?(?:[eE][+-]?\d+)?"
PERF_LINE_RE = re.compile(
    rf"(\d+): Memory Usage: ({_FLOAT}), Training Duration: ({_FLOAT})"
)

TRAIN_SIZE_RE = re.compile(r"Training set of size (\d+)")

# The benchmark workload's training-set size (BASELINE.md): used to derive
# seq/s when a run's log does not state its dataset size.
DEFAULT_NUM_SEQUENCES = 6912


def parse_perf_lines(text: str):
    """All ``(rank, memory_mb, duration_s)`` tuples in a captured stream."""
    return [
        (int(rank), float(mem), float(dur))
        for rank, mem, dur in PERF_LINE_RE.findall(text or "")
    ]


def _structured_measurements(run):
    """``[(rank, memory_mb, duration_s, extras), ...]`` from the run's
    metrics JSONL sidecar (``obs/``), or ``None`` when the run carries no
    usable sidecar - the caller then falls back to the perf-line regex.

    The sidecar is the structured-first path: unlike the regex it
    survives crashed runs' partial telemetry, and it carries the numbers
    the perf line never had (step times, data-wait fraction, collective
    traffic, HBM peaks), surfaced as extra dataframe columns.
    """
    path = run.get("metrics_path") or (
        (run.get("parameters") or {}).get("metrics")
    )
    if not path:
        return None
    from pytorch_distributed_rnn_tpu.obs.summary import (
        MalformedMetricsError,
        summarize_events,
    )
    from pytorch_distributed_rnn_tpu.obs.timeline import (
        attribute_rank,
        load_run,
    )

    # one parse per rank file: summary and phase attribution both fold
    # off the same in-memory event lists (per-step sidecars get large)
    try:
        by_rank = load_run(path)
    except MalformedMetricsError:
        return None
    summaries = []
    attributions = {}
    for rank in sorted(by_rank):
        summaries.append(summarize_events(by_rank[rank], path=path))
        # per-rank phase attribution (obs/timeline.py): where the
        # sampled step time went - surfaced as phase_* fraction columns
        # so sweep dataframes can separate input-bound from
        # exchange-bound rows
        attr = attribute_rank(by_rank[rank])
        if attr is not None:
            attributions[rank] = attr["fractions"]
    measurements = []
    for s in summaries:
        if s.get("duration_s") is None or s.get("memory_mb") is None:
            continue  # run died before its run_summary event
        phases = {
            f"phase_{name}_frac": frac
            for name, frac in attributions.get(s["rank"], {}).items()
        }
        measurements.append((
            s["rank"], s["memory_mb"], s["duration_s"],
            {
                "step_s_mean": s.get("step_s_mean"),
                "data_wait_frac": s.get("data_wait_frac"),
                "collective_bytes_per_step": s.get(
                    "collective_bytes_per_step"
                ),
                "device_peak_mb": s.get("device_peak_mb"),
                "telemetry": True,
                **phases,
            },
        ))
    return measurements or None


def create_measurement_df(results) -> pd.DataFrame:
    """Measurement dataframe from launcher results (the ``create_measurement_df``
    analogue, one row per (run, rank)).

    ``results`` is the list the launcher appends to ``results_*.json`` — or a
    path to such a file.  Structured-first: a run whose entry names a
    metrics sidecar (``metrics_path`` / the ``--metrics`` parameter) is
    measured from the sidecar, no regex involved; legacy stderr-only
    entries fall back to the perf-line regex.  Runs with neither (crashes
    predating telemetry) are dropped, exactly as the notebooks' regex
    silently skipped them.
    """
    if isinstance(results, (str, Path)):
        with open(results) as f:
            results = json.load(f)

    rows = []
    for run_id, run in enumerate(results):
        text = (run.get("stderr") or "") + "\n" + (run.get("stdout") or "")
        structured = _structured_measurements(run)
        if structured is not None:
            perf = [(r, m, d) for r, m, d, _ in structured]
            extras = [e for _, _, _, e in structured]
        else:
            perf = parse_perf_lines(text)
            extras = [{} for _ in perf]
        size_match = TRAIN_SIZE_RE.search(text)
        num_sequences = (
            int(size_match.group(1)) if size_match else DEFAULT_NUM_SEQUENCES
        )
        params = run.get("parameters", {})
        epochs = int(params.get("epochs", 1))
        for (rank, memory, duration), extra in zip(perf, extras):
            rows.append(
                {
                    "run": run_id,  # position in the results file: repeated
                    # sweep runs of the same config stay distinguishable
                    "trainer": run.get("trainer"),
                    "devices": run.get("devices", 1),
                    "slots": run.get("slots", 1),
                    "world": run.get("devices", 1) * run.get("slots", 1),
                    "batch_size": params.get("batch-size"),
                    # model family ("rnn" = the reference's motion model);
                    # seq/s is NOT comparable across families
                    "model": params.get("model", "rnn"),
                    "rule_type": run.get("rule_type"),
                    "rule_value": run.get("rule_value"),
                    "rank": rank,
                    "memory_mb": memory,
                    "duration_s": duration,
                    "num_sequences": num_sequences,
                    "seq_per_sec": num_sequences * epochs / duration
                    if duration > 0
                    else float("nan"),
                    **extra,
                }
            )
    return pd.DataFrame(rows)


def aggregate_measurements(df: pd.DataFrame) -> pd.DataFrame:
    """Mean over repeats of rank-0 rows, grouped by run configuration —
    the number the reference reported (rank 0's line, BASELINE.md)."""
    if df.empty:
        return df
    rank0 = df[df["rank"] == 0]
    grouped = (
        rank0.groupby(
            ["trainer", "devices", "slots", "batch_size"], dropna=False
        )
        .agg(
            duration_s=("duration_s", "mean"),
            memory_mb=("memory_mb", "mean"),
            seq_per_sec=("seq_per_sec", "mean"),
            repeats=("duration_s", "size"),
        )
        .reset_index()
    )
    return grouped


def scaling_table(df: pd.DataFrame, baseline_trainer: str = "local") -> pd.DataFrame:
    """Scaling study: speedup and efficiency vs the 1-device baseline.

    Mirrors the derived figures in BASELINE.md ("DDP scaling efficiency
    1→8 nodes"): for each (trainer, batch_size), speedup = t_baseline / t_N
    and efficiency = speedup / N.  The baseline is the ``local`` trainer at
    the same batch size when present, else the trainer's own 1-device row.
    """
    agg = aggregate_measurements(df)
    if agg.empty:
        return agg

    baselines = {}
    for _, row in agg.iterrows():
        if row["trainer"] == baseline_trainer and row["devices"] == 1:
            baselines[row["batch_size"]] = row["duration_s"]

    def _baseline_for(row):
        if row["batch_size"] in baselines:
            return baselines[row["batch_size"]]
        own = agg[
            (agg["trainer"] == row["trainer"])
            & (agg["devices"] == 1)
            & (agg["batch_size"] == row["batch_size"])
        ]
        return own["duration_s"].iloc[0] if len(own) else float("nan")

    agg = agg.copy()
    agg["speedup"] = agg.apply(
        lambda r: _baseline_for(r) / r["duration_s"], axis=1
    )
    agg["efficiency"] = agg["speedup"] / (agg["devices"] * agg["slots"])
    return agg
