"""Evaluation CLI: results JSON → scaling tables, CSV, plots.

Example:
  python -m pytorch_distributed_rnn_tpu.evaluation results.json \
      --csv scaling.csv --plot scaling.png
"""

from __future__ import annotations

import argparse
import sys

from pytorch_distributed_rnn_tpu.evaluation.analysis import (
    create_measurement_df,
    scaling_table,
)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="pytorch_distributed_rnn_tpu.evaluation"
    )
    parser.add_argument("results", nargs="*", help="results_*.json files")
    parser.add_argument("--csv", default=None, help="write scaling table CSV")
    parser.add_argument("--plot", default=None, help="write scaling figure")
    parser.add_argument("--network-plot", default=None,
                        help="write the delay/loss perturbation figure "
                        "(needs results with fault rules)")
    parser.add_argument("--bubble-plot", default=None,
                        help="write the pipeline-schedule bubble-fraction "
                        "figure (pure timetable accounting - needs no "
                        "results files)")
    parser.add_argument("--batch-size", type=int, default=None,
                        help="restrict the plot to one batch size")
    args = parser.parse_args(argv)

    if args.bubble_plot:
        from pytorch_distributed_rnn_tpu.evaluation.plots import (
            plot_bubble_fractions,
        )

        plot_bubble_fractions(args.bubble_plot)
        print(f"wrote {args.bubble_plot}")
        if not args.results:
            return 0
    if not args.results:
        parser.error("results files required (or pass --bubble-plot)")

    import pandas as pd

    # run ids restart at 0 in each results file: offset per file so
    # repeats of the same config in different files stay distinct runs
    frames, offset = [], 0
    for path in args.results:
        frame = create_measurement_df(path)
        if not frame.empty:
            frame["run"] = frame["run"] + offset
            offset = int(frame["run"].max()) + 1
        frames.append(frame)
    df = pd.concat(frames, ignore_index=True)
    if df.empty:
        print("no perf lines found in the given results files")
        return 1

    table = scaling_table(df)
    with pd.option_context("display.width", 120, "display.precision", 3):
        print(table.to_string(index=False))

    if args.csv:
        table.to_csv(args.csv, index=False)
        print(f"wrote {args.csv}")
    if args.plot:
        from pytorch_distributed_rnn_tpu.evaluation.plots import plot_scaling

        plot_scaling(df, args.plot, batch_size=args.batch_size)
        print(f"wrote {args.plot}")
    if args.network_plot:
        from pytorch_distributed_rnn_tpu.evaluation.plots import plot_network

        plot_network(df, args.network_plot)
        print(f"wrote {args.network_plot}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
