"""Evaluation layer: results-JSON → measurement tables → scaling analysis.

Capability parity with the reference's evaluation notebooks
(``/root/reference/evaluation/Experiments.ipynb`` cell 2 and the plotting
cells): regex-parse the rank-tagged perf line out of each run's captured
stderr, build a measurement dataframe, aggregate means over repeats, and
derive the scaling/efficiency study (training time and memory vs device
count, per trainer and batch size).

The data contract is preserved byte-for-byte: the same
``'{rank}: Memory Usage: {m}, Training Duration: {d}'`` line
(``src/motion/trainer/formatter.py:27``) in stderr of the same append-only
results JSON the launcher writes — so the reference's own notebooks parse
this framework's results unchanged.
"""

from pytorch_distributed_rnn_tpu.evaluation.analysis import (
    PERF_LINE_RE,
    aggregate_measurements,
    create_measurement_df,
    parse_perf_lines,
    scaling_table,
)
from pytorch_distributed_rnn_tpu.evaluation.plots import plot_scaling

__all__ = [
    "PERF_LINE_RE",
    "aggregate_measurements",
    "create_measurement_df",
    "parse_perf_lines",
    "scaling_table",
    "plot_scaling",
]
