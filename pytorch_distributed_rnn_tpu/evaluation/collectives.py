"""HLO collective-traffic report: the communication side of the scaling
model, measured from the COMPILED programs instead of wall-clock.

One chip (or a virtual CPU mesh) cannot measure scaling wall-clock - 8
virtual devices share the same host cores, so the r2 "scaling study" had no
scaling signal (VERDICT.md weak #3).  What the compiled program DOES pin
down exactly, on any backend, is how many bytes each training step moves
through each collective: XLA's post-optimization HLO carries every
``all-reduce`` / ``all-gather`` / ``reduce-scatter`` /
``collective-permute`` / ``all-to-all`` with concrete shapes.  Those bytes
plus a link bandwidth ARE the communication term of the scaling model (the
"How to Scale Your Model" recipe: count bytes, divide by ICI/DCN
bandwidth, compare with compute time).

``collective_stats`` parses a compiled module's text; ``report_programs``
compiles the framework's flagship SPMD programs on a virtual mesh and
returns one stats row per program.
"""

from __future__ import annotations

import re

# bytes per element for the dtypes XLA prints in shape strings
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)

# `f32[8,128]{1,0} all-reduce(` and tuple-shaped variants
_OP_RE = re.compile(
    r"=\s*(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<op>" + "|".join(_COLLECTIVES) + r")(?:-start|-done)?\("
)
_SHAPE_RE = re.compile(r"(?P<dtype>[a-z]+\d*)\[(?P<dims>[\d,]*)\]")


def _shape_bytes(shape_str: str) -> int:
    """Total bytes of one HLO shape string (tuples summed)."""
    total = 0
    for m in _SHAPE_RE.finditer(shape_str):
        dtype = m.group("dtype")
        width = _DTYPE_BYTES.get(dtype)
        if width is None:
            continue  # token[] and friends carry no data
        dims = m.group("dims")
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * width
    return total


def collective_stats(hlo_text: str) -> dict:
    """{op_kind: {"count": N, "bytes": output bytes per step}} over a
    compiled module's text.  ``-start``/``-done`` async pairs count once,
    via the ``-done`` side: a ``-start`` result tuple bundles operand
    aliases WITH the result buffers, so summing it would double-count the
    transfer, while the ``-done`` result is exactly the transferred
    data.

    CAVEAT: text parsing sees each op ONCE even when it sits inside a
    ``while`` body (a ``lax.scan`` - e.g. the sp relay's per-turn
    ppermute), so loop-executed collectives are understated by the trip
    count.  :func:`trace_collective_stats` counts from the jaxpr, where
    scan lengths are static - use that for per-step traffic totals."""
    stats: dict = {}
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if not m or "-start(" in line:
            continue
        op = m.group("op")
        entry = stats.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += _shape_bytes(m.group("shape"))
    return stats


def compiled_text(fn, *args) -> str:
    import jax

    return jax.jit(fn).lower(*args).compile().as_text()


# jax collective primitives -> the HLO op names the rest of the report uses
_COLLECTIVE_PRIMS = {
    "psum": "all-reduce",
    "pmax": "all-reduce",
    "pmin": "all-reduce",
    "ppermute": "collective-permute",
    "all_to_all": "all-to-all",
    "all_gather": "all-gather",
    # jax.lax.psum_scatter traces as the reduce_scatter primitive
    "reduce_scatter": "reduce-scatter",
}


def trace_collective_stats(fn, *args) -> dict:
    """Per-step collective traffic counted from the JAXPR (trace only, no
    compile): every collective primitive's result bytes, with enclosing
    ``lax.scan`` trip counts multiplied in - the count HLO text parsing
    gets wrong for loop-executed collectives (the sp relay's per-turn
    ppermute compiles to ONE collective-permute inside a ``while`` body
    but executes ``sp`` times per step).  Gradient collectives are
    included when ``fn`` contains the grad (trace the full train step).

    Bytes are per-device result sizes (the same convention as the HLO
    parse).  XLA may later merge small same-operand collectives, so the
    compiled COUNT can be lower; the traced BYTES are the semantic
    per-step traffic the scaling model needs.
    """
    import jax

    return closed_jaxpr_collective_stats(jax.make_jaxpr(fn)(*args))


def closed_jaxpr_collective_stats(closed) -> dict:
    """:func:`trace_collective_stats` on an already-made ClosedJaxpr -
    shared with the lint deep pass (``lint/jaxpr_pass.py``), which has
    the traced step in hand and reports per-entry collective traffic in
    its CI artifact."""
    import numpy as np

    jaxpr_cls = type(closed.jaxpr)
    closed_cls = type(closed)
    stats: dict = {}

    def add(op, count, nbytes):
        entry = stats.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += count
        entry["bytes"] += nbytes

    def aval_bytes(var):
        aval = getattr(var, "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            return 0
        if not hasattr(aval, "dtype"):
            return 0
        n = int(np.prod(aval.shape, dtype=np.int64)) if aval.shape else 1
        return n * aval.dtype.itemsize

    def subjaxprs(params):
        found = []

        def maybe(x):
            if isinstance(x, closed_cls):
                found.append(x.jaxpr)
            elif isinstance(x, jaxpr_cls):
                found.append(x)

        for value in params.values():
            maybe(value)
            if isinstance(value, (tuple, list)):
                for item in value:
                    maybe(item)
        return found

    def visit(jaxpr, mult):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in _COLLECTIVE_PRIMS:
                nbytes = sum(aval_bytes(v) for v in eqn.outvars)
                add(_COLLECTIVE_PRIMS[name], mult, nbytes * mult)
            sub_mult = mult
            if name == "scan":
                sub_mult = mult * int(eqn.params.get("length", 1))
            elif name == "while":
                # dynamic trip count: cannot be known from the trace -
                # count once and surface the uncertainty
                add("while-body(unknown-trip-count)", 1, 0)
            for sub in subjaxprs(eqn.params):
                visit(sub, sub_mult)

    visit(closed.jaxpr, 1)
    if stats.get("while-body(unknown-trip-count)", {}).get("count") == 0:
        stats.pop("while-body(unknown-trip-count)", None)
    return stats


def _motion_dp_program(n: int):
    """Data-parallel motion step on a dp=n mesh (the DDP strategy's
    gradient psum -> XLA AllReduce)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pytorch_distributed_rnn_tpu.models import MotionModel
    from pytorch_distributed_rnn_tpu.ops import cross_entropy_loss
    from pytorch_distributed_rnn_tpu.parallel import (
        make_mesh,
        make_spmd_train_step,
    )

    mesh = make_mesh({"dp": n})
    model = MotionModel(input_dim=9, hidden_dim=32, layer_dim=2,
                        output_dim=6, impl="scan")
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adam(2.5e-3)
    opt_state = opt.init(params)

    def loss_and_metrics(p, batch):
        x, y = batch
        logits = model.apply(p, x)
        return cross_entropy_loss(logits, y), {
            "correct": jnp.sum(jnp.argmax(logits, axis=1) == y)
        }

    step = make_spmd_train_step(loss_and_metrics, opt, mesh, donate=False)
    rng = np.random.RandomState(0)
    batch = (
        jnp.asarray(rng.randn(2 * n, 16, 9).astype(np.float32)),
        jnp.asarray(rng.randint(0, 6, size=2 * n)),
    )
    return step, (params, opt_state, batch), params


def _fsdp_program(n: int):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pytorch_distributed_rnn_tpu.models import CharRNN
    from pytorch_distributed_rnn_tpu.parallel import make_mesh
    from pytorch_distributed_rnn_tpu.parallel.zero import (
        init_sharded,
        init_sharded_opt_state,
        make_fsdp_train_step,
    )

    mesh = make_mesh({"dp": n})
    lm = CharRNN(vocab_size=32, embed_dim=16, hidden_dim=16 * n,
                 layer_dim=1, impl="scan")
    params, shard = init_sharded(lm, jax.random.PRNGKey(3), mesh)
    opt = optax.adam(1e-3)
    state, oshard = init_sharded_opt_state(opt, params, mesh)
    step = make_fsdp_train_step(lm.loss, opt, mesh, shard, oshard,
                                donate=False)
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, 32, size=(n, 8)), jnp.int32)
    return step, (params, state, tok), params


def _char_sp_program(dp: int, sp: int):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pytorch_distributed_rnn_tpu.models import CharRNN
    from pytorch_distributed_rnn_tpu.parallel import make_mesh
    from pytorch_distributed_rnn_tpu.parallel.strategy import (
        make_char_mesh_loss_fn,
        make_mesh_grad_step,
    )

    axes = {"dp": dp, "sp": sp}
    mesh = make_mesh(axes)
    lm = CharRNN(vocab_size=32, embed_dim=8, hidden_dim=8, layer_dim=2,
                 impl="scan")
    params = lm.init(jax.random.PRNGKey(4))
    opt = optax.adam(1e-3)
    state = opt.init(params)
    loss_fn = make_char_mesh_loss_fn(mesh, axes)
    step = make_mesh_grad_step(loss_fn, opt)
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 32, size=(2 * dp, 16)), jnp.int32)
    batch = (toks, jnp.zeros(2 * dp, jnp.int32))
    return jax.jit(step), (params, state, batch), params


def _motion_pp_program(dp: int, pp: int, schedule: str = "gpipe",
                       num_microbatches: int = 2, num_chunks: int = 1):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pytorch_distributed_rnn_tpu.models import MotionModel
    from pytorch_distributed_rnn_tpu.parallel import make_mesh
    from pytorch_distributed_rnn_tpu.parallel.strategy import (
        make_mesh_grad_step,
        make_motion_mesh_loss_fn,
        make_motion_pp_1f1b_loss_fn,
    )

    axes = {"dp": dp, "pp": pp}
    mesh = make_mesh(axes)
    model = MotionModel(input_dim=9, hidden_dim=8,
                        layer_dim=pp * num_chunks, output_dim=6)
    params = model.init(jax.random.PRNGKey(6))
    opt = optax.adam(1e-3)
    state = opt.init(params)
    if schedule in ("1f1b", "interleaved"):
        loss_fn = make_motion_pp_1f1b_loss_fn(
            mesh, axes, num_microbatches=num_microbatches,
            num_chunks=num_chunks)
    else:
        loss_fn = make_motion_mesh_loss_fn(
            mesh, axes, num_microbatches=num_microbatches)
    step = make_mesh_grad_step(loss_fn, opt)
    rng = np.random.RandomState(0)
    bsz = 2 * num_microbatches * dp
    batch = (
        jnp.asarray(rng.randn(bsz, 16, 9).astype(np.float32)),
        jnp.asarray(rng.randint(0, 6, size=bsz)),
    )
    return jax.jit(step), (params, state, batch), params


def _moe_ep_program(dp: int, ep: int, group_size: int | None = None):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pytorch_distributed_rnn_tpu.models import MoEClassifier
    from pytorch_distributed_rnn_tpu.parallel import make_mesh
    from pytorch_distributed_rnn_tpu.parallel.strategy import (
        make_mesh_grad_step,
        make_moe_mesh_loss_fn,
    )

    mesh = make_mesh({"dp": dp, "ep": ep})
    model = MoEClassifier(input_dim=9, hidden_dim=16, layer_dim=1,
                          output_dim=6, num_experts=ep * 2,
                          group_size=group_size)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adam(1e-3)
    state = opt.init(params)
    step = make_mesh_grad_step(make_moe_mesh_loss_fn(model, mesh), opt)
    rng = np.random.RandomState(0)
    batch = (
        jnp.asarray(rng.randn(2 * dp * ep, 12, 9).astype(np.float32)),
        jnp.asarray(rng.randint(0, 6, size=2 * dp * ep)),
    )
    return jax.jit(step), (params, state, batch), params


def param_bytes(params) -> int:
    import jax
    import numpy as np

    return int(sum(
        np.prod(p.shape) * p.dtype.itemsize for p in jax.tree.leaves(params)
    ))


def report_programs(n_devices: int = 8) -> list[dict]:
    """Trace the flagship SPMD programs on an ``n_devices`` virtual mesh
    and report each one's per-step collective traffic (jaxpr-counted, so
    scan-executed collectives carry their trip counts - see
    :func:`trace_collective_stats`)."""
    if n_devices < 4 or n_devices % 4:
        raise ValueError(
            f"collective-report needs a multiple of 4 devices (the sp/ep "
            f"rows factor the mesh as dp x 4), got {n_devices}"
        )
    from pytorch_distributed_rnn_tpu.parallel.pp import pp_schedule_stats

    rows = []
    for name, build, extra in (
        (f"motion dp={n_devices} (DDP grad psum)",
         lambda: _motion_dp_program(n_devices), None),
        (f"char fsdp dp={n_devices} (ZeRO gather/scatter)",
         lambda: _fsdp_program(n_devices), None),
        (f"char mesh dp={n_devices // 4},sp=4 (relay ppermute)",
         lambda: _char_sp_program(n_devices // 4, 4), None),
        (f"moe mesh dp={n_devices // 4},ep=4 (all_to_all dispatch)",
         lambda: _moe_ep_program(n_devices // 4, 4), None),
        # grouped routing: per-shard 24 tokens in four groups of 6 - the
        # all_to_all slot dim grows to groups x per-group-capacity (the
        # padded-slot wire-bytes trade the ep docstring documents) while
        # dispatch compute shrinks; this row makes the trade measurable
        (f"moe mesh dp={n_devices // 4},ep=4 (grouped routing, G=6)",
         lambda: _moe_ep_program(n_devices // 4, 4, group_size=6), None),
        (f"motion mesh dp={n_devices // 2},pp=2 (GPipe stage ppermute)",
         lambda: _motion_pp_program(n_devices // 2, 2),
         {"schedule": [pp_schedule_stats(2, m, "gpipe")
                       for m in (2, 4, 8)]}),
        (f"motion mesh dp={n_devices // 2},pp=2 (1F1B self-scheduled)",
         lambda: _motion_pp_program(n_devices // 2, 2, schedule="1f1b"),
         {"schedule": [pp_schedule_stats(2, m, "1f1b")
                       for m in (2, 4, 8)]}),
        (f"motion mesh dp={n_devices // 2},pp=2 (interleaved, 2 chunks)",
         lambda: _motion_pp_program(n_devices // 2, 2,
                                    schedule="interleaved", num_chunks=2),
         {"schedule": [pp_schedule_stats(2, m, "interleaved",
                                         num_chunks=2)
                       for m in (2, 4, 8)]}),
    ):
        fn, call_args, params = build()
        # Two complementary views, each honest about its blind spot:
        # - traced: jaxpr collectives with scan trip counts multiplied in
        #   (the semantic per-step traffic), but BLIND to GSPMD-inserted
        #   collectives - sharding-annotation programs like the ZeRO step
        #   trace as empty because the compiler inserts their gathers;
        # - compiled: the post-optimization HLO ops (GSPMD included), but
        #   a collective inside a while body (a lax.scan) is counted once
        #   regardless of trip count.
        # Read per-op totals as max(traced, compiled).
        rows.append({
            "program": name,
            "param_bytes": param_bytes(params),
            "traced": trace_collective_stats(fn, *call_args),
            "compiled": collective_stats(compiled_text(fn, *call_args)),
        })
        if extra:
            # pp rows carry the schedule timetable accounting: ticks,
            # busy/idle stage-slots and the bubble fraction per
            # microbatch count (idle shrinks as M grows)
            rows[-1].update(extra)
    return rows
