"""Deterministic, seedable fault-injection schedules.

The reference benchmarked under injected network faults (``tc netem``
delay 0-400 ms / loss 0-15 % around every run, fabfile.py:130-191); the
TPU port reproduces that half through the native transport's
``PDRNN_FAULT_DELAY_MS`` / ``PDRNN_FAULT_LOSS_PROB`` env contract
(``runtime/native.py``).  A :class:`FaultSchedule` extends the same idea
to the rest of the stack - data pipeline, gradients, process lifetime -
with triggers addressed to exact steps/epochs (or seeded per-step
probabilities), so a chaos run is exactly reproducible.

Spec grammar (``--faults`` flag / ``PDRNN_CHAOS`` env)::

    event[,event...]
    event := step:<n>:<action>[:<arg>]      fire at optimizer step n (0-based,
                                            run-relative)
           | epoch:<n>:<action>[:<arg>]     fire at the start of epoch n
           | prob:<p>:<action>[:<arg>]      fire each step with probability p
                                            (seeded, per-step deterministic)
           | net:delay:<ms>                 transport delay (PDRNN_FAULT_* bridge)
           | net:loss:<prob>                transport loss (PDRNN_FAULT_* bridge)
           | net:flap:<s>                   periodic connection drop: every s
                                            seconds the process's serving
                                            listeners close every open peer
                                            connection (PDRNN_FAULT_FLAP_S
                                            bridge) - a FLAKY replica/link,
                                            distinct from kill: the process
                                            survives, its connections do not
           | seed:<int>                     RNG seed for prob events (default 0)
    action := nan                           corrupt the step's batch to NaN
                                            (non-finite grads; pairs with the
                                            NonFiniteGuard skip path)
            | stall[:<seconds>]             data-loader stall (default 0.25 s)
            | slow[:<frac>]                 SUSTAINED straggler: from the
                                            addressed step/epoch on, every
                                            producer item is delayed by frac x
                                            the time since the previous one
                                            (default 0.5) - a degraded node,
                                            not a hung one; fires (and is
                                            counted/recorded) once, at
                                            activation
            | exc                           data-loader exception (ChaosError)
            | kill                          SIGKILL this process (simulated
                                            preemption; pairs with --resume auto)
            | respawn                       abrupt crash exit (nonzero, no
                                            cleanup): the death an elastic
                                            supervisor respawns - pairs with
                                            parameter-server --elastic to drill
                                            kill -> respawn -> REGISTER rejoin
            | preempt                       SIGTERM this process (graceful
                                            preemption notice): a PS worker
                                            drains - flushes its in-flight
                                            gradient, DEREGISTERs, exits 0

An event may carry an ``@<rank>`` suffix (``epoch:1:kill@2``): it then
fires only in the process bound to that rank via :meth:`FaultSchedule.
for_rank` (the parameter-server runner binds each worker's rank), so a
multi-process chaos run can preempt ONE worker while the rest survive.
Unsuffixed events fire everywhere.

Example: ``step:3:nan,step:7:stall:0.5,epoch:2:kill@1,net:delay:100,seed:7``.
"""

from __future__ import annotations

import logging
import os
import random
import signal
import time
from dataclasses import dataclass

log = logging.getLogger(__name__)

CHAOS_ENV = "PDRNN_CHAOS"
# the native transport's netem-analogue contract (runtime/native.py reads
# these at Communicator construction; launcher/commands.py exports them
# around benchmark runs) - the ONE mechanism chaos and bench share
FAULT_DELAY_ENV = "PDRNN_FAULT_DELAY_MS"
FAULT_LOSS_ENV = "PDRNN_FAULT_LOSS_PROB"
# connection-flap half of the same contract: consumers that own peer
# connections (the serving TCP front end; reusable by MPMD/PS link
# tests) drop every open connection each period - the flaky-replica
# mode the router drill needs, distinct from killing the process
FAULT_FLAP_ENV = "PDRNN_FAULT_FLAP_S"

_ACTIONS = ("nan", "stall", "slow", "exc", "kill", "respawn", "preempt")
_TRIGGERS = ("step", "epoch", "prob")
_DEFAULT_STALL_S = 0.25
_DEFAULT_SLOW_FRAC = 0.5
# a sustained-slow delay is proportional to the inter-item gap; cap it so
# a one-off long gap (checkpoint, compile) cannot snowball into a stall
_SLOW_DELAY_CAP_S = 1.0
# process-lifetime actions (maybe_kill handles all three): how each dies
_LIFETIME_ACTIONS = ("kill", "respawn", "preempt")
# the respawn action's abrupt-crash exit code: nonzero so a supervisor
# classifies it as a death (respawn), never as completion/drain
RESPAWN_EXIT_CODE = 17


class ChaosError(RuntimeError):
    """An injected data-pipeline failure (the ``exc`` action)."""


def fault_env(fault_type: str | None, fault_value: float) -> dict[str, str]:
    """The ``PDRNN_FAULT_*`` env for one netem-analogue rule - shared by
    the bench sweep's command synthesis and :meth:`FaultSchedule.network_env`
    so the two can never drift apart."""
    if not fault_type or not fault_value:
        return {}
    if fault_type == "delay":
        return {FAULT_DELAY_ENV: str(fault_value)}
    if fault_type == "loss":
        return {FAULT_LOSS_ENV: str(fault_value)}
    if fault_type == "flap":
        return {FAULT_FLAP_ENV: str(fault_value)}
    raise ValueError(
        f"unknown fault type {fault_type!r} (delay|loss|flap)"
    )


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault: ``trigger`` addresses when, ``action`` what."""

    trigger: str  # step | epoch | prob
    at: float  # step/epoch index, or probability for prob triggers
    action: str  # nan | stall | exc | kill
    arg: float | None = None  # stall seconds
    rank: int | None = None  # only fire in the process bound to this rank

    def __str__(self):
        base = f"{self.trigger}:{self.at:g}:{self.action}"
        if self.arg is not None:
            base += f":{self.arg:g}"
        if self.rank is not None:
            base += f"@{self.rank}"
        return base


class FaultSchedule:
    """A parsed chaos spec; owns trigger matching and action execution.

    Deterministic by construction: step/epoch triggers are exact
    addresses, and ``prob`` triggers draw from ``random.Random((seed,
    step, event_index))`` - stateless per (step, event), so concurrent
    queries from the producer thread and the consumer loop cannot
    reorder draws.
    """

    def __init__(self, events: list[FaultEvent], network=(), seed: int = 0,
                 rank: int | None = None):
        for e in events:
            if e.trigger not in _TRIGGERS:
                raise ValueError(f"unknown trigger {e.trigger!r}")
            if e.action not in _ACTIONS:
                raise ValueError(f"unknown action {e.action!r}")
        self.events = tuple(events)
        self.network = tuple(network)  # ((type, value), ...)
        self.seed = int(seed)
        # the process's rank for @rank-qualified events: None (unbound)
        # fires only unqualified events
        self.rank = rank
        # observability: {action: count} of faults actually fired
        self.fired: dict[str, int] = {}
        # structured telemetry (obs/recorder.py): the trainer binds its
        # recorder here so every fired fault becomes a 'fault' event; a
        # late attribute (not a constructor arg) so resilience stays
        # importable without the obs package in the picture
        self.recorder = None
        # sustained-straggler state (`slow` action): 0.0 = inactive;
        # once an event's address matches, the fraction sticks for the
        # rest of this incarnation
        self._slow_frac = 0.0
        self._slow_prev_tm: float | None = None

    # -- construction --------------------------------------------------------

    @classmethod
    def parse(cls, spec: str) -> "FaultSchedule":
        events, network = [], []
        seed = 0
        for raw in str(spec).split(","):
            part = raw.strip()
            if not part:
                continue
            body, _, rank_s = part.partition("@")
            fields = body.split(":")
            kind = fields[0]
            try:
                rank = int(rank_s) if rank_s else None
                if kind == "seed":
                    (seed,) = fields[1:]
                    seed = int(seed)
                elif kind == "net":
                    _, net_type, net_value = fields
                    fault_env(net_type, float(net_value) or 1e-9)  # validate
                    network.append((net_type, float(net_value)))
                elif kind in _TRIGGERS:
                    at = float(fields[1])
                    action = fields[2]
                    arg = float(fields[3]) if len(fields) > 3 else None
                    if action == "stall" and arg is None:
                        arg = _DEFAULT_STALL_S
                    if action == "slow" and arg is None:
                        arg = _DEFAULT_SLOW_FRAC
                    events.append(FaultEvent(kind, at, action, arg, rank))
                else:
                    raise ValueError(f"unknown trigger {kind!r}")
            except (IndexError, ValueError) as exc:
                raise ValueError(
                    f"bad fault event {part!r} in spec {spec!r}: {exc}"
                ) from exc
        return cls(events, network, seed)

    @classmethod
    def from_env(cls, env=None) -> "FaultSchedule | None":
        """The ``PDRNN_CHAOS`` contract: a schedule for every run in the
        process, without touching the CLI (how the chaos CI job and the
        bench harness inject)."""
        spec = (env if env is not None else os.environ).get(CHAOS_ENV)
        return cls.parse(spec) if spec else None

    @classmethod
    def resolve(cls, args, rank: int | None = None) -> "FaultSchedule | None":
        """The ONE CLI resolution path (``--faults`` flag beats the
        ``PDRNN_CHAOS`` env), shared by every strategy entry point so a
        flag can never be silently dropped by one of them: binds the
        rank (for ``@rank`` events) and exports net events onto the
        transport contract as a side effect."""
        spec = getattr(args, "faults", None)
        faults = cls.parse(spec) if spec else cls.from_env()
        if faults is None:
            return None
        if rank is not None:
            faults = faults.for_rank(rank)
        faults.export_network()
        return faults

    def __str__(self):
        parts = [str(e) for e in self.events]
        parts += [f"net:{t}:{v:g}" for t, v in self.network]
        if self.seed:
            parts.append(f"seed:{self.seed}")
        return ",".join(parts)

    # -- network bridge ------------------------------------------------------

    def network_env(self) -> dict[str, str]:
        """``PDRNN_FAULT_*`` vars for this schedule's net events."""
        env: dict[str, str] = {}
        for net_type, value in self.network:
            env.update(fault_env(net_type, value))
        return env

    def export_network(self, env=None):
        """Export net events into ``env`` (default ``os.environ``) so
        communicators constructed after this point - including ones in
        spawned child processes - pick the faults up."""
        target = os.environ if env is None else env
        for key, value in self.network_env().items():
            target[key] = value

    # -- rank binding --------------------------------------------------------

    def for_rank(self, rank: int) -> "FaultSchedule":
        """Bind the schedule to one process's rank so ``@rank``-qualified
        events can fire there (the parameter-server runner binds each
        worker).  Counters are fresh - each process owns its own."""
        bound = FaultSchedule(list(self.events), self.network, self.seed,
                              rank=int(rank))
        bound.recorder = self.recorder
        return bound

    def for_rejoin(self) -> "FaultSchedule":
        """The schedule for a RESPAWNED incarnation (elastic supervisor
        relaunch): deterministic step/epoch-addressed process-lifetime
        events (kill/respawn/preempt) are dropped - they already fired
        in the incarnation they terminated, and fault step/epoch
        addresses are run-relative, so replaying them would kill every
        respawn at the same address and no rejoin drill could ever
        reach completion.  Probabilistic lifetime events (a flaky
        worker) and all data-path events persist."""
        kept = [
            e for e in self.events
            if not (e.action in _LIFETIME_ACTIONS
                    and e.trigger in ("step", "epoch"))
        ]
        bound = FaultSchedule(kept, self.network, self.seed, rank=self.rank)
        bound.recorder = self.recorder
        return bound

    # -- trigger matching ----------------------------------------------------

    @property
    def has_step_events(self) -> bool:
        return any(
            e.trigger in ("step", "prob") for e in self.events
            if e.rank is None or e.rank == self.rank
        )

    def _matches(self, trigger_kinds, index: int):
        for i, e in enumerate(self.events):
            if e.rank is not None and e.rank != self.rank:
                continue
            if e.trigger in ("step", "epoch") and e.trigger in trigger_kinds:
                if int(e.at) == index:
                    yield e
            elif e.trigger == "prob" and "prob" in trigger_kinds:
                # stateless integer mix (NOT a shared RNG stream): the
                # draw for (seed, step, event) is the same whatever order
                # the producer thread and consumer loop ask in
                mixed = (self.seed * 1_000_003 + index) * 1_000_003 + i
                if random.Random(mixed).random() < e.at:
                    yield e

    def fired_snapshot(self) -> dict[str, int]:
        """Copy of the fired-fault counters (``{action: count}``).  The
        anomaly watchdog (``obs/watchdog.py``) stamps this onto every
        alert it emits as ``chaos_fired``, so a drill's INJECTED stall
        is distinguishable from an organic hang in the event stream -
        the watchdog <-> chaos contract."""
        return dict(self.fired)

    def _fire(self, event: FaultEvent, where: str):
        self.fired[event.action] = self.fired.get(event.action, 0) + 1
        log.warning(f"chaos: injecting {event} at {where}")
        if self.recorder is not None and self.recorder.enabled:
            self.recorder.record(
                "fault", action=event.action, trigger=event.trigger,
                where=where,
            )
            if event.action in ("kill", "respawn"):
                # SIGKILL/_exit joins no flush thread: drain NOW or the
                # event (the whole point of chaos telemetry) dies with us
                self.recorder.flush()

    # -- action execution ----------------------------------------------------

    def _timed_stall(self, event: FaultEvent, **where):
        """Sleep out a stall fault; with a recorder bound, the stall's
        extent lands as a ``fault_stall`` span on the trace timeline's
        resilience row (the fault mark says WHEN, the span says HOW
        LONG the pipeline was held)."""
        t0 = time.perf_counter()
        time.sleep(event.arg or _DEFAULT_STALL_S)
        rec = self.recorder
        if rec is not None and rec.enabled:
            rec.emit_span(
                "fault_stall", t0, time.perf_counter() - t0,
                cat="resilience", **where,
            )

    def on_producer_item(self, step: int):
        """Data-pipeline faults for the batch feeding step ``step`` -
        called in the loader/prefetch PRODUCER so stalls and exceptions
        originate where real loader failures do (and must propagate
        through the prefetch thread to the consumer)."""
        for e in self._matches(("step", "prob"), step):
            if e.action == "stall":
                self._fire(e, f"loader step {step}")
                self._timed_stall(e, step=step)
            elif e.action == "slow":
                self._activate_slow(e, f"loader step {step}")
            elif e.action == "exc":
                self._fire(e, f"loader step {step}")
                raise ChaosError(
                    f"injected data-loader failure at step {step} ({e})"
                )
        self._apply_slow()

    def _activate_slow(self, event: FaultEvent, where: str):
        """Latch a sustained-straggler fraction.  Fires (counter +
        telemetry) once per activation, not per delayed item - the
        degradation is continuous, the event marks its onset."""
        frac = float(event.arg or _DEFAULT_SLOW_FRAC)
        if frac > self._slow_frac:
            self._fire(event, where)
            self._slow_frac = frac
            self._slow_prev_tm = time.perf_counter()

    def _apply_slow(self):
        """Delay this producer item by ``frac`` x the inter-item gap -
        a node running at 1/(1+frac) speed, not a one-shot hang."""
        if not self._slow_frac:
            return
        now = time.perf_counter()
        if self._slow_prev_tm is not None:
            delay = min(self._slow_frac * (now - self._slow_prev_tm),
                        _SLOW_DELAY_CAP_S)
            if delay > 0:
                time.sleep(delay)
        self._slow_prev_tm = time.perf_counter()

    @property
    def slow_active(self) -> bool:
        """Whether a sustained ``slow`` fault has latched (observability
        for drills asserting the straggler actually degraded)."""
        return self._slow_frac > 0

    def corrupt_batch(self, step: int, batch):
        """Non-finite-gradient injection: replace step ``step``'s features
        with NaN (NaN activations -> NaN loss -> NaN grads), exercising
        the NonFiniteGuard skip path end to end."""
        for e in self._matches(("step", "prob"), step):
            if e.action == "nan":
                self._fire(e, f"step {step}")
                import jax.numpy as jnp

                features, labels = batch
                return jnp.full_like(features, jnp.nan), labels
        return batch

    def maybe_kill(self, *, step: int | None = None,
                   epoch: int | None = None):
        """Process-lifetime faults at the addressed step/epoch:

        - ``kill``: SIGKILL - no cleanup, no atexit, exactly like a
          preempted VM (pairs with --resume auto);
        - ``respawn``: abrupt nonzero exit - the crash an elastic
          supervisor respawns into the same worker-id;
        - ``preempt``: SIGTERM - the graceful preemption notice.  A PS
          worker's DrainSignal turns it into a drain (flush in-flight
          gradient, DEREGISTER, exit 0); processes without a handler
          die with the default disposition.

        Epoch triggers fire at epoch START (work since the last
        checkpoint is lost, the case auto-resume exists for)."""
        if step is not None:
            events = [e for e in self._matches(("step", "prob"), step)
                      if e.action in _LIFETIME_ACTIONS]
            where = f"step {step}"
        else:
            events = [e for e in self._matches(("epoch",), epoch)
                      if e.action in _LIFETIME_ACTIONS]
            where = f"epoch {epoch}"
        for e in events:
            self._fire(e, where)
            if e.action == "preempt":
                # deliverable mid-run: the handler only sets a flag, so
                # the step in flight completes before the drain
                os.kill(os.getpid(), signal.SIGTERM)
                continue
            logging.shutdown()  # flush handlers; SIGKILL/_exit won't
            if e.action == "respawn":
                os._exit(RESPAWN_EXIT_CODE)
            os.kill(os.getpid(), signal.SIGKILL)

    def on_epoch_start(self, epoch: int):
        """Epoch-granularity faults (kill/stall/exc; nan is per-step)."""
        self.maybe_kill(epoch=epoch)
        for e in self._matches(("epoch",), epoch):
            if e.action == "stall":
                self._fire(e, f"epoch {epoch}")
                self._timed_stall(e, epoch=epoch)
            elif e.action == "slow":
                self._activate_slow(e, f"epoch {epoch}")
            elif e.action == "exc":
                self._fire(e, f"epoch {epoch}")
                raise ChaosError(f"injected failure at epoch {epoch} ({e})")
