"""Non-finite-step guard and checkpoint auto-resume.

Two recovery mechanisms the reference lacked (its checkpoints were
write-only and a NaN batch poisoned the run):

- :class:`NonFiniteGuard` wraps the optimizer in
  ``optax.apply_if_finite`` so a step whose gradients contain NaN/Inf is
  SKIPPED inside the compiled program (params untouched, counters
  advance), and the host aborts loudly only after K consecutive bad
  steps - transient bad batches are survived, a persistently diverging
  run still fails fast.
- :func:`resume_latest` restores a trainer from the newest VALID
  checkpoint in a directory, falling back across corrupt/truncated files
  (``training/checkpoint.py`` CRC verification) - the restart half of
  the kill/preemption faults in ``resilience/faults.py``.
"""

from __future__ import annotations

import logging

import optax

log = logging.getLogger(__name__)

# apply_if_finite's own give-up threshold is disabled (it ACCEPTS the bad
# update once exceeded, poisoning params); the abort decision is the
# host-side guard's, which raises instead
_NEVER_ACCEPT = 2**30


class NonFiniteAbort(RuntimeError):
    """Raised when more than ``limit`` consecutive steps were non-finite."""


class NonFiniteGuard:
    """Skip-and-count non-finite update steps; abort past ``limit``
    consecutive ones.

    ``wrap`` must be applied to the trainer's optimizer at construction
    (it changes the opt_state pytree: ``ApplyIfFiniteState`` around the
    inner state).  ``check`` reads the counters off the live opt_state -
    call it at step granularity on per-batch paths and at epoch
    boundaries on scanned paths; the compiled program has already
    rejected the bad updates either way, so a later check only delays
    the abort, never corrupts state.
    """

    def __init__(self, limit: int):
        if limit < 1:
            raise ValueError(f"bad-step limit must be >= 1, got {limit}")
        self.limit = int(limit)
        self.total_skipped = 0
        # structured telemetry (obs/recorder.py): bound by the trainer so
        # every newly observed skip becomes a 'nan_skip' event; a late
        # attribute so resilience needs no obs import
        self.recorder = None

    def wrap(self, optimizer):
        return optax.apply_if_finite(
            optimizer, max_consecutive_errors=_NEVER_ACCEPT
        )

    def check(self, opt_state):
        """Inspect the ``ApplyIfFiniteState`` counters; raise
        :class:`NonFiniteAbort` past the consecutive limit."""
        if isinstance(opt_state, list):
            # bucketed native-ring state: a LIST (never a tuple - optax
            # states are NamedTuples) of one wrapped state per gradient
            # bucket, all fed the SAME global skip verdict (the poison
            # broadcast), so every bucket's counters are identical -
            # bucket 0 speaks for the step
            opt_state = opt_state[0]
        consecutive = int(opt_state.notfinite_count)
        total = int(opt_state.total_notfinite)
        if total > self.total_skipped:
            log.warning(
                f"non-finite gradients: skipped {total - self.total_skipped} "
                f"step(s) (total {total}, consecutive {consecutive})"
            )
            if self.recorder is not None and self.recorder.enabled:
                self.recorder.record(
                    "nan_skip", new=total - self.total_skipped,
                    total=total, consecutive=consecutive,
                )
            self.total_skipped = total
        if consecutive > self.limit:
            raise NonFiniteAbort(
                f"{consecutive} consecutive non-finite update steps "
                f"(limit {self.limit}, {total} skipped in total): the run "
                "is diverging, not glitching - aborting instead of "
                "training in place"
            )


def resume_latest(trainer, checkpoint_dir):
    """Auto-resume: restore ``trainer`` from the newest valid checkpoint
    under ``checkpoint_dir`` (``--resume auto``).

    Candidates are tried newest-first; a corrupt/truncated file is
    logged and skipped so resume falls back to the previous valid one.
    Every skip additionally lands as a structured ``checkpoint_fallback``
    event (path, reason, chosen fallback) on the trainer's metrics
    sidecar, so chaos drills assert the fallback from telemetry instead
    of grepping stderr.  Returns the checkpoint metadata, or ``None``
    when no usable checkpoint exists (fresh start).
    """
    from pytorch_distributed_rnn_tpu.training.checkpoint import (
        CheckpointCorruptError,
        checkpoint_candidates,
    )

    skipped: list[tuple[str, str]] = []

    def _record_fallbacks(chosen):
        recorder = getattr(trainer, "recorder", None)
        if recorder is None or not recorder.enabled:
            return
        for path, reason in skipped:
            recorder.record(
                "checkpoint_fallback", path=path, reason=reason,
                chosen=chosen,
            )

    for path in checkpoint_candidates(checkpoint_dir):
        try:
            meta = trainer.resume_from(path, advance_epoch=True)
        except CheckpointCorruptError as exc:
            log.warning(
                f"auto-resume: skipping corrupt checkpoint {path}: {exc}"
            )
            skipped.append((str(path), str(exc)))
            continue
        log.info(
            f"auto-resume: restored {path} (epoch {meta['epoch']}, "
            f"loss {meta['loss']:.6f})"
        )
        _record_fallbacks(str(path))
        return meta
    _record_fallbacks(None)
    return None
