"""Resilience layer: deterministic chaos injection and the hardening
that makes the training stack survive it.

The reference cluster treated failure as a benchmark axis - its fabfile
wrapped every run in ``tc netem`` delay/loss (SURVEY §L4) - but never
implemented the recovery half: checkpoints were write-only, a straggler
killed the run.  This package supplies both sides:

- ``faults``: a seedable :class:`FaultSchedule` (``--faults`` /
  ``PDRNN_CHAOS``) that injects data-loader stalls/exceptions, non-finite
  gradients, simulated preemption (SIGKILL), and network delay/loss -
  the latter bridged onto the native transport's ``PDRNN_FAULT_*``
  contract so the bench netem sweep and the chaos tests share one
  mechanism.  The live anomaly watchdog (``obs/watchdog.py``) closes
  the loop from the other side: every alert it emits carries the
  schedule's :meth:`FaultSchedule.fired_snapshot`, so injected faults
  and organic anomalies are distinguishable in the event stream - the
  chaos ``stall`` drill is the live plane's acceptance test.
- ``guard``: the :class:`NonFiniteGuard` (XLA-level skip of non-finite
  updates, host-level abort after K consecutive bad steps) and
  checkpoint auto-resume with fallback across corrupt files.
- ``retry``: exponential backoff with deterministic jitter (and an
  optional total wall-clock deadline) for transport-level operations
  (the parameter-server worker's push/pull).
- ``membership``: elastic world membership - the master-side
  :class:`Roster` (stable worker-ids, joined/drained/dead lifecycle,
  push-seq watermarks surviving respawns) and the worker-side
  :class:`DrainSignal` (SIGTERM as a preemption notice: flush,
  deregister, exit 0).
"""

from pytorch_distributed_rnn_tpu.resilience.faults import (
    ChaosError,
    FaultEvent,
    FaultSchedule,
    fault_env,
)
from pytorch_distributed_rnn_tpu.resilience.guard import (
    NonFiniteAbort,
    NonFiniteGuard,
    resume_latest,
)
from pytorch_distributed_rnn_tpu.resilience.membership import (
    DrainRequested,
    DrainSignal,
    Member,
    Roster,
)
from pytorch_distributed_rnn_tpu.resilience.retry import retry_transport

__all__ = [
    "ChaosError",
    "DrainRequested",
    "DrainSignal",
    "FaultEvent",
    "FaultSchedule",
    "fault_env",
    "Member",
    "NonFiniteAbort",
    "NonFiniteGuard",
    "Roster",
    "resume_latest",
    "retry_transport",
]
