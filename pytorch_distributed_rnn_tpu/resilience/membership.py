"""Elastic world membership: the roster, its lifecycle, and drain signals.

PR 2 made the parameter-server world *shrinkable* (quorum degrade sheds
dead workers); this module is the other half - membership as a first-
class, mutable object, so a world can also GROW back (Podracer-style
actor pools under preemption, PAPERS.md).  Three pieces:

- :class:`Member` / :class:`Roster` - the master's live membership
  table.  A member has a stable **worker-id** decoupled from its
  transport **rank**: the rank is a socket slot (reused when a
  supervisor respawns the worker), the worker-id is the logical
  participant whose gradient stream, push-seq watermark and incarnation
  count survive the respawn.  State machine::

      joined --(DEREGISTER)--> drained     (voluntary, exits 0)
      joined --(transport death)--> dead --(REGISTER)--> joined
      joined --(DONE)--> done

  Every transition emits a structured obs event (``member_join`` /
  ``member_drain`` / ``member_dead``) carrying the roster counts, so
  ``pdrnn-metrics`` and the trace timeline's membership lane read the
  whole story from the sidecar.

- push-seq high-water dedupe (:meth:`Roster.note_push`): the per-member
  watermark persists across service-thread incarnations, which is what
  guarantees a rejoining worker's stale in-flight push is DROPPED, not
  double-averaged - the join-protocol extension of the retry dedupe in
  ``param_server/protocol.py``.

- :class:`DrainSignal` - the worker-side half of preemption-aware
  drain: a SIGTERM handler that *requests* a drain instead of dying, so
  the worker can flush its in-flight gradient, DEREGISTER, and exit 0
  (distinguishable in telemetry from a crash).
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from dataclasses import dataclass, field

from pytorch_distributed_rnn_tpu.utils import threadcheck

log = logging.getLogger(__name__)

# member lifecycle states
JOINED = "joined"
DRAINED = "drained"
DEAD = "dead"
DONE = "done"

_TERMINAL = (DRAINED, DONE)


class DrainRequested(Exception):
    """A voluntary-leave request (SIGTERM / chaos ``preempt``) observed
    at a step boundary: the worker has flushed its in-flight gradient
    and should DEREGISTER and exit 0."""


@dataclass
class Member:
    """One logical participant of an elastic world."""

    worker_id: int
    rank: int
    state: str = JOINED
    incarnation: int = 1  # bumped on every (re)join
    push_seq: int = 0  # high-water APPLIED push seq (dedupe + progress)
    synced: bool = True  # has pushed since (re)join: counted in rounds
    died_tm: float | None = None  # monotonic death stamp (rejoin window)
    error: str | None = None

    @property
    def terminal(self) -> bool:
        return self.state in _TERMINAL


class Roster:
    """The master's live membership table, keyed by worker-id.

    Thread-safe at the method level (service threads, the elastic
    acceptor and the completion waiter all touch it); the internal lock
    is a leaf - no method calls out while holding it - so it composes
    under the master's round lock.
    """

    def __init__(self, recorder=None):
        from pytorch_distributed_rnn_tpu.obs.recorder import NULL_RECORDER

        self.recorder = recorder if recorder is not None else NULL_RECORDER
        # a LEAF lock by contract: roster methods never call out
        # while holding it (the master/learner take their round
        # lock first, never the other way around)
        self._lock = threadcheck.lock(threading.Lock(), "roster.members")  # guards: _members, _by_rank
        self._members: dict[int, Member] = {}
        self._by_rank: dict[int, int] = {}
        self.rejoins = 0

    # -- construction --------------------------------------------------------

    def bootstrap(self, ranks, quiet: bool = False) -> None:
        """Seed the roster with the launch-time workers: worker-id ==
        initial rank (the ids only *diverge* from ranks for members that
        join later or respawn into reused slots).  ``quiet`` suppresses
        the per-member ``member_join`` events - a fixed (non-elastic)
        world's launch set is not membership telemetry."""
        for rank in ranks:
            self.join(int(rank), int(rank), event="bootstrap", quiet=quiet)

    # -- transitions ---------------------------------------------------------

    def join(self, worker_id: int, rank: int,
             event: str = "register", quiet: bool = False) -> Member:
        """(Re)join: a fresh member enters ``joined``; a known one - the
        respawn path - re-enters it with its incarnation bumped and its
        push-seq watermark PRESERVED (the double-count guard).  Any
        member arriving via REGISTER - fresh or respawned - enters the
        NEXT sync round (synced only after its first push), so an
        in-flight round never blocks on a joiner's data load + model
        build; only launch-time bootstrap members are expected from
        round one."""
        with self._lock:
            member = self._members.get(worker_id)
            if member is None:
                member = Member(worker_id=worker_id, rank=rank,
                                synced=(event == "bootstrap"))
                self._members[worker_id] = member
                rejoin = False
            else:
                member.incarnation += 1
                member.state = JOINED
                member.rank = rank
                member.died_tm = None
                member.error = None
                # the rejoiner enters the NEXT sync round: it is not
                # counted in the rendezvous until its first push lands,
                # so an in-flight round never blocks on its model build
                member.synced = False
                rejoin = True
                self.rejoins += 1
            self._by_rank[rank] = worker_id
            counts = self._counts_locked()
        if not quiet:
            self._emit("member_join", member, via=event, rejoin=rejoin,
                       **counts)
        return member

    def drain(self, rank: int, seq: int | None = None) -> Member | None:
        """Voluntary leave (DEREGISTER): terminal, exits the quorum
        denominator without burning its budget."""
        member = self._transition(rank, DRAINED)
        if member is not None:
            self._emit("member_drain", member, seq=seq, **self.counts())
        return member

    def mark_dead(self, rank: int, error: str | None = None) -> Member | None:
        """Involuntary loss (transport death): the member stays on the
        roster as ``dead`` and may re-enter - only via REGISTER."""
        member = self._transition(rank, DEAD)
        if member is not None:
            member.died_tm = time.perf_counter()
            member.error = error
            self._emit("member_dead", member, error=error, **self.counts())
        return member

    def complete(self, rank: int) -> Member | None:
        """Normal completion (DONE op): terminal, successful."""
        return self._transition(rank, DONE)

    def _transition(self, rank: int, state: str) -> Member | None:
        with self._lock:
            worker_id = self._by_rank.get(rank)
            member = self._members.get(worker_id)
            if member is None:
                return None
            member.state = state
            return member

    # -- push-seq watermark --------------------------------------------------

    def note_push(self, rank: int, seq: int) -> bool:
        """Advance the member's push-seq high-water mark.  Returns False
        for a DUPLICATE (seq at or below the watermark): a retried
        exchange whose original applied, or a rejoined worker's stale
        in-flight push - either way the gradient must not be applied
        again.  A member's first post-join push also marks it synced
        (counted in sync-round rendezvous from the next round on)."""
        with self._lock:
            member = self._members.get(self._by_rank.get(rank))
            if member is None:
                return True  # unrostered comms (unit-scripted) pass through
            if seq <= member.push_seq:
                return False
            member.push_seq = seq
            member.synced = True
            return True

    def watermarks(self) -> dict[int, int]:
        """Per-worker-id push-seq watermark snapshot - what a streaming
        learner persists alongside its params so the exactly-once
        guarantee survives ITS OWN restart, not just the pushers'."""
        with self._lock:
            return {m.worker_id: m.push_seq for m in self._members.values()}

    def restore_watermarks(self, watermarks: dict) -> None:
        """Re-seed watermarks from a checkpoint (the learner-failover
        inverse of :meth:`watermarks`).  Known members only RAISE their
        mark; unknown worker-ids are pre-rostered as ``dead`` (rankless)
        so they re-enter only via REGISTER - and their first post-restart
        push dedupes against the restored mark instead of re-applying
        experience the dead incarnation already trained on."""
        now = time.perf_counter()
        with self._lock:
            for worker_id, seq in watermarks.items():
                worker_id, seq = int(worker_id), int(seq)
                member = self._members.get(worker_id)
                if member is None:
                    member = Member(worker_id=worker_id, rank=-1,
                                    state=DEAD, synced=False, died_tm=now)
                    self._members[worker_id] = member
                member.push_seq = max(member.push_seq, seq)

    # -- queries -------------------------------------------------------------

    def member_for_rank(self, rank: int) -> Member | None:
        with self._lock:
            return self._members.get(self._by_rank.get(rank))

    def get(self, worker_id: int) -> Member | None:
        with self._lock:
            return self._members.get(worker_id)

    def members(self) -> list[Member]:
        with self._lock:
            return list(self._members.values())

    def round_ranks(self) -> set[int]:
        """Ranks expected in a sync-round rendezvous: joined AND synced
        (a just-rejoined member is excluded until its first push)."""
        with self._lock:
            return {
                m.rank for m in self._members.values()
                if m.state == JOINED and m.synced
            }

    def dead_members(self) -> list[Member]:
        with self._lock:
            return [m for m in self._members.values() if m.state == DEAD]

    def all_terminal(self) -> bool:
        with self._lock:
            return all(m.terminal for m in self._members.values())

    def counts(self) -> dict:
        with self._lock:
            return self._counts_locked()

    def _counts_locked(self) -> dict:
        counts = dict.fromkeys((JOINED, DRAINED, DEAD, DONE), 0)
        for m in self._members.values():
            counts[m.state] += 1
        return {
            "joined": counts[JOINED], "drained": counts[DRAINED],
            "dead": counts[DEAD], "done": counts[DONE],
        }

    # -- telemetry -----------------------------------------------------------

    def _emit(self, kind: str, member: Member, **fields) -> None:
        log.info(
            f"membership: {kind} worker_id={member.worker_id} "
            f"rank={member.rank} incarnation={member.incarnation}"
        )
        if self.recorder is not None and self.recorder.enabled:
            self.recorder.record(
                kind, worker_id=member.worker_id, rank_slot=member.rank,
                incarnation=member.incarnation, **fields,
            )


class DrainSignal:
    """Worker-side preemption notice: SIGTERM sets a flag; the training
    loop observes it at the next step boundary (after the in-flight
    gradient exchange completed) and raises :class:`DrainRequested`.

    The handler itself does no I/O and never raises - a signal landing
    mid-``send`` must not tear the wire protocol; the *flush* semantics
    come from checking only between exchanges.
    """

    def __init__(self):
        self.requested = False
        self._installed = False

    def install(self) -> "DrainSignal":
        """Install the SIGTERM handler (main thread only - spawned
        strategy processes qualify).  Idempotent."""
        if not self._installed:
            signal.signal(signal.SIGTERM, self._on_sigterm)
            self._installed = True
        return self

    def _on_sigterm(self, signum, frame):
        self.requested = True
        log.warning(
            "SIGTERM: drain requested - will flush the in-flight "
            "gradient, deregister, and exit 0 at the next step boundary"
        )

    def check(self) -> None:
        """Raise :class:`DrainRequested` if a drain was requested."""
        if self.requested:
            raise DrainRequested("SIGTERM drain requested")
