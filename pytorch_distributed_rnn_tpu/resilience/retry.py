"""Exponential backoff with deterministic jitter for transport calls.

The parameter-server worker's push/pull rides the native TCP transport;
under injected faults (and on real preemptible clusters) an exchange can
fail transiently.  ``retry_transport`` re-runs the exchange with
exponential backoff plus seeded jitter - deterministic for a given
(seed, attempt), so chaos runs replay exactly, while distinct workers
(distinct seeds) still decorrelate their retry storms.
"""

from __future__ import annotations

import logging
import random
import time

log = logging.getLogger(__name__)


def backoff_delays(retries: int, base_delay: float = 0.05,
                   max_delay: float = 2.0, seed: int = 0):
    """The retry sleep sequence: ``base * 2**attempt`` capped at
    ``max_delay``, plus up to 50 % seeded jitter."""
    rng = random.Random(seed)
    return [
        min(base_delay * (2 ** attempt), max_delay) * (1.0 + 0.5 * rng.random())
        for attempt in range(retries)
    ]


def retry_transport(fn, *, retries: int = 3, base_delay: float = 0.05,
                    max_delay: float = 2.0, seed: int = 0,
                    retryable=(RuntimeError, OSError), what: str = "exchange",
                    sleep=time.sleep, on_retry=None):
    """Run ``fn()``; on a retryable transport error, back off and re-run.

    Raises the FIRST error (the diagnostic one, matching the trainer's
    compile-retry convention) once ``retries`` re-attempts are exhausted.
    ``on_retry(attempt, exc)`` (if given) is called before each backoff
    sleep - the telemetry hook counting retries per exchange.
    """
    delays = backoff_delays(retries, base_delay, max_delay, seed)
    first_exc = None
    for attempt in range(retries + 1):
        try:
            return fn()
        except retryable as exc:
            first_exc = first_exc or exc
            if attempt == retries:
                raise first_exc
            delay = delays[attempt]
            log.warning(
                f"transport {what} failed ({type(exc).__name__}: {exc}); "
                f"retry {attempt + 1}/{retries} in {delay:.3f}s"
            )
            if on_retry is not None:
                on_retry(attempt + 1, exc)
            sleep(delay)
