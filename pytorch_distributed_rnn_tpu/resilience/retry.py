"""Exponential backoff with deterministic jitter for transport calls.

The parameter-server worker's push/pull rides the native TCP transport;
under injected faults (and on real preemptible clusters) an exchange can
fail transiently.  ``retry_transport`` re-runs the exchange with
exponential backoff plus seeded jitter - deterministic for a given
(seed, attempt), so chaos runs replay exactly, while distinct workers
(distinct seeds) still decorrelate their retry storms.

Two independent caps bound a storm:

- the ATTEMPT cap (``retries``): how many re-runs before giving up;
- the DEADLINE budget (``deadline_s``): total wall clock the storm may
  consume.  The backoff schedule is pre-trimmed so its sleep sum stays
  under the budget, and elapsed time (the attempts themselves cost
  wall clock too) is checked before every sleep.  The PS worker derives
  it from ``--ps-sync-timeout``, so a retry storm can never outlive the
  sync round it is retrying into - without it, worst-case retries could
  keep a zombie exchange alive long after the master's round degraded
  past this worker.
"""

from __future__ import annotations

import logging
import random
import time

log = logging.getLogger(__name__)


def backoff_delays(retries: int, base_delay: float = 0.05,
                   max_delay: float = 2.0, seed: int = 0,
                   deadline_s: float | None = None):
    """The retry sleep sequence: ``base * 2**attempt`` capped at
    ``max_delay``, plus up to 50 % seeded jitter.  With ``deadline_s``
    the sequence is TRIMMED so its cumulative sum never exceeds the
    budget - the property the deadline contract rests on (sleeping the
    full schedule can never outlive the round being retried into)."""
    rng = random.Random(seed)
    delays = [
        min(base_delay * (2 ** attempt), max_delay) * (1.0 + 0.5 * rng.random())
        for attempt in range(retries)
    ]
    if deadline_s is None:
        return delays
    trimmed, total = [], 0.0
    for delay in delays:
        if total + delay > deadline_s:
            break
        trimmed.append(delay)
        total += delay
    return trimmed


def retry_transport(fn, *, retries: int = 3, base_delay: float = 0.05,
                    max_delay: float = 2.0, seed: int = 0,
                    retryable=(RuntimeError, OSError), what: str = "exchange",
                    sleep=time.sleep, on_retry=None,
                    deadline_s: float | None = None,
                    clock=time.monotonic):
    """Run ``fn()``; on a retryable transport error, back off and re-run.

    Raises the FIRST error (the diagnostic one, matching the trainer's
    compile-retry convention) once ``retries`` re-attempts are exhausted
    OR the ``deadline_s`` wall-clock budget is spent - whichever comes
    first.  ``on_retry(attempt, exc)`` (if given) is called before each
    backoff sleep - the telemetry hook counting retries per exchange.
    """
    delays = backoff_delays(retries, base_delay, max_delay, seed,
                            deadline_s=deadline_s)
    t_start = clock() if deadline_s is not None else 0.0
    first_exc = None
    for attempt in range(retries + 1):
        try:
            return fn()
        except retryable as exc:
            first_exc = first_exc or exc
            if attempt >= len(delays):
                # attempt cap, or the deadline trimmed the schedule
                raise first_exc
            delay = delays[attempt]
            if deadline_s is not None and (
                clock() - t_start + delay > deadline_s
            ):
                # the attempts themselves burned the budget: stop now
                # rather than sleep past the round being retried into
                log.warning(
                    f"transport {what} retry deadline ({deadline_s:g}s) "
                    f"exhausted after {attempt + 1} attempt(s); giving up"
                )
                raise first_exc
            log.warning(
                f"transport {what} failed ({type(exc).__name__}: {exc}); "
                f"retry {attempt + 1}/{retries} in {delay:.3f}s"
            )
            if on_retry is not None:
                on_retry(attempt + 1, exc)
            sleep(delay)