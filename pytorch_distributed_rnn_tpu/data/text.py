"""Token-window dataset for the char-LM family (new capability - the
reference's only dataset is UCI HAR motion windows,
``/root/reference/src/motion/processor.py:80-93``; it has no text/LM path).

A corpus (any bytes file) is tokenized at the byte level and cut into
non-overlapping ``(seq_length + 1)``-token windows: the ``+1`` carries the
final target so ``CharRNN.loss`` can shift inside the window
(``tokens[:, :-1] -> tokens[:, 1:]``).  Without a corpus file the loader
falls back to the synthetic motif stream (``data/synthetic.py``), the same
stand-in policy as the HAR path (real download absent in the image).

The dataset exposes the ``features`` / ``labels`` / ``__len__`` surface the
sampler, loaders, and device-resident epoch programs already consume -
``labels`` are dummy zeros (the LM derives targets from the window itself),
so every distribution strategy shards LM batches exactly like motion
batches.
"""

from __future__ import annotations

import logging
from pathlib import Path

import numpy as np

log = logging.getLogger(__name__)

VOCAB_SIZE = 256  # byte-level


class TextDataset:
    """``features``: (N, seq_length + 1) int32 token windows."""

    def __init__(self, windows: np.ndarray):
        windows = np.asarray(windows)
        if windows.ndim != 2 or windows.shape[1] < 2:
            raise ValueError(
                f"windows must be (N, seq_length + 1 >= 2), got {windows.shape}"
            )
        self.features = windows.astype(np.int32)
        self.labels = np.zeros(len(windows), np.int32)  # loader/sampler compat
        self.seq_length = self.features.shape[1] - 1
        self.vocab_size = VOCAB_SIZE

    def __getitem__(self, index):
        return self.features[index], self.labels[index]

    def __len__(self):
        return len(self.features)

    @classmethod
    def resolve_corpus(cls, dataset_path):
        """The ONE "does this path hold a corpus" rule: the file itself,
        or ``corpus.txt`` under a directory; ``None`` when
        ``dataset_path`` is None or holds neither."""
        if dataset_path is None:
            return None
        path = Path(dataset_path)
        if path.is_file():
            return path
        if (path / "corpus.txt").is_file():
            return path / "corpus.txt"
        return None

    @classmethod
    def load(
        cls,
        dataset_path,
        seq_length: int = 128,
        validation_fraction: float = 0.05,
        test_fraction: float = 0.1,
        seed: int | None = None,
        synthetic_sequences: int = 2048,
    ):
        """(train, validation, test) token-window datasets.

        ``dataset_path`` may be a bytes/text file, or a directory holding
        ``corpus.txt``; otherwise the synthetic motif stream is generated
        (deterministic in ``seed``).  Windows are shuffled with ``seed``
        before the split so the three sets are i.i.d. slices of the corpus.
        """
        corpus_file = cls.resolve_corpus(dataset_path)
        if corpus_file is None and dataset_path is not None:
            # A given path that resolves to nothing must not SILENTLY
            # train on synthetic data (a typo'd corpus path would look
            # like a real run) - warn loudly before falling back.  Not an
            # error: the launcher and the world tests pass the generic
            # data directory for every family, where "no corpus.txt" is
            # the normal synthetic-LM case.
            log.warning(
                "--dataset-path %s holds no corpus (no such file / no "
                "corpus.txt under it) - training on the SYNTHETIC motif "
                "corpus instead", dataset_path,
            )

        if corpus_file is not None:
            data = np.frombuffer(corpus_file.read_bytes(), dtype=np.uint8)
            num_windows = len(data) // (seq_length + 1)
            if num_windows < 3:
                raise ValueError(
                    f"{corpus_file} holds {len(data)} bytes - too short for "
                    f"3 windows of {seq_length + 1}"
                )
            windows = (
                data[: num_windows * (seq_length + 1)]
                .reshape(num_windows, seq_length + 1)
                .astype(np.int32)
            )
        else:
            from pytorch_distributed_rnn_tpu.data.synthetic import (
                generate_char_tokens,
            )

            windows = generate_char_tokens(
                synthetic_sequences, seq_length, VOCAB_SIZE, seed=seed or 0
            )

        rng = np.random.RandomState(seed if seed is not None else 0)
        windows = windows[rng.permutation(len(windows))]

        n = len(windows)
        n_test = max(1, int(n * test_fraction))
        n_valid = max(1, int(n * validation_fraction))
        test = cls(windows[:n_test])
        valid = cls(windows[n_test : n_test + n_valid])
        train = cls(windows[n_test + n_valid :])
        return train, valid, test
