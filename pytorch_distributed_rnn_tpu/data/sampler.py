"""Distributed sampler: epoch-seeded shuffle, pad-to-divisible, rank shard.

Capability parity with ``torch.utils.data.DistributedSampler`` as the
reference uses it (``/root/reference/src/motion/trainer/distributed.py:35-39``,
``base.py:73-75``): every rank sees a disjoint 1/world_size shard of an
epoch-seeded global permutation, padded by repeating leading samples so the
total divides evenly, and ``set_epoch`` reseeds the shuffle so epochs differ
but all ranks agree.

TPU-native note: under single-controller SPMD one process feeds all devices,
so the common path shards a *global batch* across mesh devices instead; this
sampler exists for (a) per-process data loading in true multi-host runs and
(b) exact reference-semantics tests.  The shard is rank-strided
(``indices[rank::world]``) like torch's.
"""

from __future__ import annotations

import numpy as np


class DistributedSampler:
    def __init__(
        self,
        dataset_size,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
    ):
        if hasattr(dataset_size, "__len__"):
            dataset_size = len(dataset_size)
        if rank >= num_replicas or rank < 0:
            raise ValueError(f"invalid rank {rank} for world size {num_replicas}")
        self.dataset_size = int(dataset_size)
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.num_samples = -(-self.dataset_size // num_replicas)  # ceil
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def indices(self) -> np.ndarray:
        """This rank's sample indices for the current epoch."""
        return self.global_indices()[self.rank]

    def global_indices(self) -> np.ndarray:
        """All ranks' shards as one (num_replicas, num_samples) matrix
        (row r == the ``indices()`` a rank-r sampler would produce).  Used
        by the single-controller SPMD trainers to assemble rank-major
        global batches."""
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            order = rng.permutation(self.dataset_size)
        else:
            order = np.arange(self.dataset_size)
        padding = self.total_size - self.dataset_size
        if padding > 0:
            # torch semantics: repeat the permutation as often as needed
            # (covers datasets smaller than the replica count)
            reps = -(-padding // len(order))
            order = np.concatenate([order, np.tile(order, reps)[:padding]])
        return order.reshape(self.num_samples, self.num_replicas).T

    def __iter__(self):
        return iter(self.indices())

    def __len__(self):
        return self.num_samples
