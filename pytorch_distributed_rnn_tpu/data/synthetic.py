"""Synthetic UCI-HAR-format data for tests and benches.

The reference assumes the real UCI HAR download on disk
(``/root/reference/src/motion/processor.py:40-58``).  This module fabricates
a statistically similar stand-in - per-class sinusoid motifs plus noise over
9 channels x 128 steps - both as arrays and as a raw-text directory tree in
the exact UCI layout, so the full processor -> cache -> trainer path is
exercisable anywhere.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from pytorch_distributed_rnn_tpu.data.processor import INPUT_SIGNAL_TYPES

NUM_CLASSES = 6


def generate_har_arrays(
    num_samples: int,
    seq_length: int = 128,
    num_features: int = 9,
    seed: int = 0,
    num_classes: int = NUM_CLASSES,
):
    """Class-dependent sinusoid + noise windows: X (N, T, F) float32,
    y (N, 1) int64 in [0, num_classes)."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, num_classes, size=(num_samples, 1)).astype(np.int64)
    t = np.arange(seq_length, dtype=np.float32)[None, :, None]
    freq = 0.05 + 0.04 * y[:, :, None].astype(np.float32)  # (N,1,1)
    phase = rng.uniform(0, 2 * np.pi, size=(num_samples, 1, num_features)).astype(
        np.float32
    )
    amplitude = 0.5 + 0.1 * np.arange(num_features, dtype=np.float32)
    X = amplitude * np.sin(freq * t + phase) + 0.1 * rng.randn(
        num_samples, seq_length, num_features
    ).astype(np.float32)
    return X.astype(np.float32), y


def write_synthetic_har_dataset(
    base_path,
    num_train: int = 256,
    num_test: int = 64,
    seq_length: int = 128,
    seed: int = 0,
):
    """Write a raw-text UCI HAR directory tree under ``base_path``."""
    base_path = Path(base_path)
    for split, num in (("train", num_train), ("test", num_test)):
        X, y = generate_har_arrays(num, seq_length, seed=seed + (split == "test"))
        signals_dir = base_path / split / "Inertial Signals"
        signals_dir.mkdir(parents=True, exist_ok=True)
        for f, signal in enumerate(INPUT_SIGNAL_TYPES):
            np.savetxt(signals_dir / f"{signal}{split}.txt", X[:, :, f], fmt="%.6e")
        # labels on disk are 1-based, as in the real dataset
        np.savetxt(base_path / split / f"y_{split}.txt", y + 1, fmt="%d")
    return base_path


def generate_char_tokens(num_sequences: int, seq_length: int,
                         vocab_size: int = 256, seed: int = 0):
    """Synthetic character streams for the char-RNN LM family: a mixture of
    repeated motifs and noise so a language model has real structure to
    learn (uniform-random tokens would pin the loss at log(vocab))."""
    rng = np.random.RandomState(seed)
    motifs = rng.randint(0, vocab_size, size=(8, 16))
    rows = []
    for _ in range(num_sequences):
        row = []
        while len(row) < seq_length + 1:
            if rng.rand() < 0.8:
                row.extend(motifs[rng.randint(len(motifs))])
            else:
                row.extend(rng.randint(0, vocab_size, size=4))
        rows.append(row[: seq_length + 1])
    return np.asarray(rows, dtype=np.int32)
