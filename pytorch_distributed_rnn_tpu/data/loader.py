"""Minimal batch loader feeding numpy batches to jitted steps.

The reference uses ``torch.utils.data.DataLoader`` with a sampler and an
optional final partial batch (``/root/reference/src/motion/trainer/base.py:
46-61``).  On TPU the equivalent is simple array slicing: batches are dense
numpy slices handed to jit-compiled steps (XLA requires static shapes, so a
partial final batch triggers exactly one extra compilation, cached across
epochs).
"""

from __future__ import annotations

import numpy as np

from pytorch_distributed_rnn_tpu.data.sampler import DistributedSampler


class DataLoader:
    def __init__(self, dataset, batch_size=None, sampler=None, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size if batch_size is not None else len(dataset)
        self.sampler = sampler
        self.drop_last = drop_last

    def __iter__(self):
        if self.sampler is not None:
            indices = np.asarray(self.sampler.indices())
        else:
            indices = np.arange(len(self.dataset))
        for start in range(0, len(indices), self.batch_size):
            batch_idx = indices[start : start + self.batch_size]
            if self.drop_last and len(batch_idx) < self.batch_size:
                return
            features, labels = self.dataset[batch_idx]
            yield features, labels

    def __len__(self):
        n = len(self.sampler) if self.sampler is not None else len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)
