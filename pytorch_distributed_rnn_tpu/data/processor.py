"""UCI HAR raw-text processor.

Capability parity with the reference ``MotionDataProcessor``
(``/root/reference/src/motion/processor.py:16-119``): reads the nine
inertial-signal text files for train/test, stacks them to float32 arrays of
shape (N, 128, 9), converts 1-based labels to 0-based int labels, carves a
validation split off the training set with a seeded permutation, and
truncates the training set to a multiple of 96 so runs with 1/2/4/8/12
workers x 1/2/4 slots consume identical data (``processor.py:63-66``).

TPU-native differences: outputs are numpy arrays (fed to jax as device
arrays by the loader), and the validation split takes an explicit ``seed``
so determinism does not depend on global RNG state.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

INPUT_SIGNAL_TYPES = [
    "body_acc_x_",
    "body_acc_y_",
    "body_acc_z_",
    "body_gyro_x_",
    "body_gyro_y_",
    "body_gyro_z_",
    "total_acc_x_",
    "total_acc_y_",
    "total_acc_z_",
]

# Training-set truncation keeps sample counts divisible for every node/slot
# combination benchmarked by the reference (1/2/4/8/12 nodes x 1/2/4 slots).
WORKER_DIVISOR = 96


class MotionDataProcessor:
    TRAIN = "train"
    TEST = "test"

    def __init__(self, seed: int | None = None):
        self.seed = seed

    def process_data(self, base_path, validation_fraction: float = 0.05):
        """Load the raw dataset under ``base_path``.

        Returns ``((X_train, y_train), (X_valid, y_valid), (X_test, y_test))``
        with X float32 (N, T, 9) and y int64 (N, 1).
        """
        base_path = Path(base_path)

        X_train = self._load_signals(base_path / self.TRAIN, "train")
        X_test = self._load_signals(base_path / self.TEST, "test")
        y_train = self._load_labels(base_path / self.TRAIN / "y_train.txt")
        y_test = self._load_labels(base_path / self.TEST / "y_test.txt")

        (X_train, y_train), valid = self._train_valid_split(
            X_train, y_train, validation_fraction
        )

        num_train = (len(X_train) // WORKER_DIVISOR) * WORKER_DIVISOR
        return (X_train[:num_train], y_train[:num_train]), valid, (X_test, y_test)

    def _load_signals(self, split_dir: Path, split: str) -> np.ndarray:
        """Stack the 9 per-signal text files into (N, T, 9) float32."""
        signals = []
        for signal in INPUT_SIGNAL_TYPES:
            path = split_dir / "Inertial Signals" / f"{signal}{split}.txt"
            signals.append(np.loadtxt(path, dtype=np.float32))  # (N, T)
        return np.stack(signals, axis=-1)

    def _load_labels(self, path: Path) -> np.ndarray:
        """1-based class ids in a text column -> 0-based int64 (N, 1)."""
        y = np.loadtxt(path, dtype=np.int64).reshape(-1, 1)
        return y - 1

    def _train_valid_split(self, features, labels, validation_fraction):
        assert len(features) == len(labels), "features/labels size mismatch"
        rng = np.random.RandomState(self.seed)
        indices = rng.permutation(len(features))
        num_valid = int(len(features) * validation_fraction)
        valid_idx, train_idx = indices[:num_valid], indices[num_valid:]
        return (
            (features[train_idx], labels[train_idx]),
            (features[valid_idx], labels[valid_idx]),
        )
