"""Host-path input pipeline: bounded lookahead over a batch iterator.

The torch-DataLoader-worker analogue for the host batch loop
(``training/base.py:_train_epoch_host``): items are pulled ``depth``
ahead of the consumer, so each batch's ``device_put`` dispatches (JAX
transfers are asynchronous) while the previous step is still running
on the device.  A synchronous deque - not a thread - keeps ordering
and error propagation deterministic; the overlap comes from XLA's
async dispatch, not host concurrency.
"""

from __future__ import annotations

from collections import deque
from collections.abc import Iterable, Iterator
from typing import TypeVar

T = TypeVar("T")


def prefetch(iterable: Iterable[T], depth: int = 2) -> Iterator[T]:
    """Yield from ``iterable`` in order, pulling ``depth`` items ahead.

    When the consumer holds item ``i``, items ``i+1 .. i+depth`` have
    already been pulled from the source (and, for device batches, their
    uploads dispatched).  ``depth`` must be >= 1.
    """
    if depth < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {depth}")
    buffer: deque[T] = deque()
    for item in iterable:
        buffer.append(item)
        if len(buffer) > depth:
            yield buffer.popleft()
    while buffer:
        yield buffer.popleft()
