"""Host-path input pipeline: bounded lookahead over a batch iterator.

The torch-DataLoader-worker analogue for the host batch loop
(``training/base.py:_train_epoch_host``): a producer THREAD pulls items
``depth`` ahead of the consumer, so batch prep (and, for device batches,
the async H2D upload JAX dispatches) overlaps the step running on the
device.

Lifecycle is explicit because chaos runs exit early by design
(``resilience/faults.py`` kills, injected exceptions, guard aborts): a
consumer that abandons the stream - ``close()``, ``with``-exit, garbage
collection, or just breaking out of its ``for`` loop - stops and joins
the producer thread instead of leaking it.  (The producer thread
deliberately holds no reference to the iterator, only to the shared
channel state - otherwise an abandoned iterator could never be
collected and its ``__del__`` cleanup would never run.)  A
producer-side exception is re-raised in the consumer AT ITS POSITION in
the stream, carrying the original traceback (the producer frames), so
loader bugs debug the same as they would un-prefetched.

Ordering is strict FIFO and the lookahead bound is exact: when the
consumer holds item ``i``, the producer has pulled at most items
``i+1 .. i+depth`` (a token semaphore, released as the consumer takes
each item, gates every source pull).
"""

from __future__ import annotations

import threading
from collections import deque
from collections.abc import Iterable, Iterator
from typing import Generic, TypeVar

T = TypeVar("T")

_JOIN_TIMEOUT_S = 5.0


class _Done:
    """Stream-end sentinel."""


class _Raised:
    def __init__(self, exc: BaseException):
        self.exc = exc


class _Channel:
    """The producer/consumer state, shared by the thread and the
    iterator.  Kept separate so the THREAD references only the channel:
    the iterator stays collectable while the thread runs, and its GC
    finalizer can stop the thread."""

    def __init__(self, depth: int):
        # producer acquires one token per source pull; consumer releases
        # one per item taken - so pulled <= consumed + depth, exactly
        self.tokens = threading.Semaphore(depth)
        self.buffer: deque = deque()
        self.available = threading.Semaphore(0)  # items in buffer
        self.stop = threading.Event()

    def emit(self, item):
        self.buffer.append(item)
        self.available.release()


def _produce(source, chan: _Channel, stage):
    try:
        while True:
            # poll the token so an abandoned consumer (stopped with a
            # full buffer) releases the thread promptly
            while not chan.tokens.acquire(timeout=0.1):
                if chan.stop.is_set():
                    return
            if chan.stop.is_set():
                return
            try:
                item = next(source)
                # staging runs HERE, on the producer thread, inside the
                # same try: a stage failure (device OOM, bad transfer)
                # re-raises at the consumer's position like any other
                # producer-side error
                if stage is not None:
                    item = stage(item)
            except StopIteration:
                chan.emit(_Done)
                return
            except BaseException as exc:  # noqa: BLE001 - re-raised in consumer
                # ship the exception OBJECT: its __traceback__ already
                # points at the producer frames, so the consumer-side
                # raise shows the original failure site
                chan.emit(_Raised(exc))
                return
            chan.emit(item)
    finally:
        close = getattr(source, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # pragma: no cover - best-effort cleanup
                pass


def prefetch(
    iterable: Iterable[T], depth: int = 2, stage=None
) -> "PrefetchIterator[T]":
    """Yield from ``iterable`` in order, pulling up to ``depth`` items
    ahead on a producer thread.

    When the consumer holds item ``i``, items up to ``i+depth`` have
    already been pulled from the source (and, for device batches, their
    uploads dispatched).  ``depth`` must be >= 1.

    ``stage`` (optional) is applied to every item ON THE PRODUCER
    THREAD before it enters the channel - the device-staging hook:
    ``training/base.py`` passes a blocking ``jax.device_put`` so each
    batch's H2D transfer completes off the consumer's critical path and
    ``__next__`` hands back device-resident buffers.  A ``stage``
    exception propagates to the consumer at that item's position, same
    as a source error.
    """
    if depth < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {depth}")
    return PrefetchIterator(iterable, depth, stage)


class PrefetchIterator(Generic[T]):
    """Iterator over a producer-thread-fed bounded channel."""

    def __init__(self, iterable: Iterable[T], depth: int, stage=None):
        self._chan = _Channel(depth)
        self._closed = False
        self._thread = threading.Thread(
            target=_produce, args=(iter(iterable), self._chan, stage),
            name="pdrnn-prefetch", daemon=True,
        )
        self._thread.start()

    def __iter__(self) -> Iterator[T]:
        return self

    def __next__(self) -> T:
        if self._closed:
            raise StopIteration
        self._chan.available.acquire()
        item = self._chan.buffer.popleft()
        if item is _Done:
            # latch exhaustion: the sentinel was consumed, so further
            # __next__ calls must short-circuit on _closed (re-acquiring
            # `available` on a dead producer would block forever)
            self._closed = True
            self._chan.available.release()
            raise StopIteration
        if isinstance(item, _Raised):
            self._chan.available.release()
            self._closed = True
            raise item.exc
        self._chan.tokens.release()
        return item

    def close(self):
        """Stop and join the producer thread; idempotent.  Called on
        ``with``-exit and GC too, so an early-exiting consumer (chaos
        kill path excepted - SIGKILL joins nothing) never leaks the
        thread.  A producer blocked inside the source (a stalled loader)
        is abandoned after a bounded join timeout; the thread is a
        daemon, so it cannot hold the process open either way."""
        self._closed = True
        self._chan.stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=_JOIN_TIMEOUT_S)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # pragma: no cover - GC timing is interpreter-specific
        try:
            self.close()
        except Exception:
            pass
