"""Motion dataset with preprocessing cache.

Capability parity with the reference ``MotionDataset``
(``/root/reference/src/motion/dataset.py:11-73``): six activity labels,
``seq_length``/``num_features`` derived from the array shape, and a
``load()`` that returns (train, validation, test), short-circuiting to
cached arrays when all six cache files exist and otherwise preprocessing the
raw text data and writing the cache.

TPU-native differences: the cache is ``.npy`` (numpy) instead of
``torch.save`` ``.pt`` tensors; arrays stay in host memory until the loader
stages batches to device.
"""

from __future__ import annotations

import logging
from pathlib import Path

import numpy as np

from pytorch_distributed_rnn_tpu.data.processor import MotionDataProcessor

log = logging.getLogger(__name__)


class MotionDataset:
    LABELS = [
        "WALKING",
        "WALKING_UPSTAIRS",
        "WALKING_DOWNSTAIRS",
        "SITTING",
        "STANDING",
        "LAYING",
    ]

    def __init__(self, features: np.ndarray, labels: np.ndarray):
        self.features = np.asarray(features, dtype=np.float32)
        self.labels = np.asarray(labels)
        self.seq_length = self.features.shape[1]
        self.num_features = self.features.shape[2]

    def __getitem__(self, index):
        return self.features[index], self.labels[index]

    def __len__(self):
        return len(self.features)

    # -- cache ---------------------------------------------------------------

    @classmethod
    def get_data_path(cls, base_path: Path, data_type: str):
        return base_path / f"X_{data_type}.npy", base_path / f"y_{data_type}.npy"

    @classmethod
    def processed_data_exists(cls, paths) -> bool:
        return all(Path(p).exists() for p in paths)

    @classmethod
    def load(
        cls,
        base_path,
        output_path=None,
        validation_fraction: float = 0.05,
        seed: int | None = None,
    ):
        """Return (train, validation, test) datasets, using the cache when
        complete, else preprocessing raw data and writing it."""
        base_path = Path(base_path)
        types = ["train", "validation", "test"]
        cached = []
        for data_type in types:
            feature_path, label_path = cls.get_data_path(base_path, data_type)
            if cls.processed_data_exists([feature_path, label_path]):
                cached.append(cls(np.load(feature_path), np.load(label_path)))

        if len(cached) == 3:
            log.info("Preprocessed data found. Skip preprocessing.")
            return cached

        if output_path is None:
            output_path = base_path
        output_path = Path(output_path)
        output_path.mkdir(parents=True, exist_ok=True)

        log.info("No processed data found. Preprocess raw data...")
        processor = MotionDataProcessor(seed=seed)
        splits = processor.process_data(base_path, validation_fraction)
        datasets = []
        for data_type, (features, labels) in zip(types, splits):
            np.save(output_path / f"X_{data_type}.npy", features)
            np.save(output_path / f"y_{data_type}.npy", labels)
            datasets.append(cls(features, labels))
        return datasets
