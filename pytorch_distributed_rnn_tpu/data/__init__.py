from pytorch_distributed_rnn_tpu.data.dataset import MotionDataset
from pytorch_distributed_rnn_tpu.data.loader import DataLoader
from pytorch_distributed_rnn_tpu.data.prefetch import prefetch
from pytorch_distributed_rnn_tpu.data.processor import MotionDataProcessor
from pytorch_distributed_rnn_tpu.data.sampler import DistributedSampler
from pytorch_distributed_rnn_tpu.data.synthetic import (
    generate_har_arrays,
    write_synthetic_har_dataset,
)

__all__ = [
    "MotionDataset",
    "DataLoader",
    "MotionDataProcessor",
    "DistributedSampler",
    "generate_har_arrays",
    "prefetch",
    "write_synthetic_har_dataset",
]
