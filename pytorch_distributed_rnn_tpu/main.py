"""CLI entrypoint: global flags + required strategy subcommand.

Capability parity with ``/root/reference/src/motion/main.py:15-43`` - same
flag surface and defaults, same dispatch shape (``args.func(args)``).
Subcommands: ``local``, ``distributed``, ``horovod``,
``parameter-server``.

Consciously fixed vs the reference (see PARITY.md): ``--validation-fraction``
is actually forwarded to the dataset split (the reference parses it but the
processor default silently governs); ``--seed`` seeds model init and the
sampler (there is no global mutable RNG in JAX to seed); ``--dropout`` is
REAL train-mode inter-layer dropout threaded through the models (the
reference parsed it but never used it, ``main.py:26``).  New flags:
``--cell {lstm,gru}`` and ``--resume PATH`` (checkpoint resume; reference
checkpoints were write-only).  ``--num-threads`` is accepted for CLI
compatibility only.

Run:
  python -m pytorch_distributed_rnn_tpu.main --epochs 2 --seed 123456789 local
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from pytorch_distributed_rnn_tpu.utils import apply_platform_overrides

DEFAULT_CHECKPOINT_DIR = Path("models")
DEFAULT_DATASET_PATH = Path("data")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="TPU-native distributed RNN trainer"
    )
    parser.add_argument(
        "--checkpoint-directory", default=DEFAULT_CHECKPOINT_DIR, type=Path
    )
    parser.add_argument("--dataset-path", default=DEFAULT_DATASET_PATH, type=Path)
    parser.add_argument("--output-path", default=None, type=Path)
    parser.add_argument("--stacked-layer", default=2, type=int)
    parser.add_argument("--hidden-units", default=32, type=int)
    parser.add_argument("--epochs", default=100, type=int)
    parser.add_argument("--validation-fraction", default=0.1, type=float)
    parser.add_argument("--batch-size", default=1440, type=int)
    parser.add_argument("--learning-rate", default=0.0025, type=float)
    parser.add_argument("--dropout", default=0.1, type=float)
    parser.add_argument("--log", default="INFO")
    parser.add_argument("--num-threads", default=4, type=int)
    parser.add_argument("--seed", default=None, type=int)
    parser.add_argument("--no-validation", action="store_true")
    parser.add_argument("--cell", default="lstm", choices=["lstm", "gru"])
    parser.add_argument(
        "--model", default="rnn",
        choices=["rnn", "attention", "char", "moe"],
        help="model family: stacked RNN (reference parity), the "
        "attention classifier (long-context family; composes the full "
        "dp x sp x tp mesh under the mesh strategy), the byte-level "
        "char LM (next-token loss on --dataset-path corpus.txt windows, "
        "synthetic motif stream when absent), or the MoE classifier "
        "(RNN backbone + Switch-routed expert FFN; experts shard over "
        "the ep mesh axis under the mesh strategy)",
    )
    parser.add_argument(
        "--seq-length", default=None, type=int, metavar="T",
        help="token-window length for --model char (default 128); "
        "motion/attention take their length from the HAR data",
    )
    parser.add_argument(
        "--num-heads", default=4, type=int,
        help="attention heads (--model attention; must divide "
        "--hidden-units)",
    )
    parser.add_argument(
        "--num-experts", default=4, type=int,
        help="expert count for --model moe (must shard over the ep mesh "
        "axis); expert FFN hidden dim defaults to 2 x --hidden-units",
    )
    parser.add_argument(
        "--moe-top-k", default=1, type=int, choices=[1, 2],
        help="experts per token for --model moe: 1 = Switch routing "
        "(raw max-gate combine weight), 2 = GShard (renormalized top-2 "
        "gates; capacity slots assigned choice-major so second choices "
        "drop first under pressure)",
    )
    parser.add_argument(
        "--moe-router", default="token", choices=["token", "expert"],
        help="--model moe routing direction: token (tokens pick experts "
        "- Switch/GShard, see --moe-top-k) or expert (expert-choice: "
        "each expert picks its top-C tokens - perfectly balanced by "
        "construction, no aux loss)",
    )
    parser.add_argument(
        "--moe-capacity-factor", default=2.0, type=float, metavar="F",
        help="per-expert slot budget for --model moe: capacity = "
        "ceil(tokens x selections x F / experts).  Applies to the "
        "dispatched paths: the ep mesh strategy (token-choice drops "
        "overflow past it, residual passes through) and expert-choice "
        "routing on every strategy (each expert fills exactly this many "
        "slots).  Token-choice on the non-mesh strategies runs the "
        "dense-exact path, which computes every expert and drops "
        "nothing - the flag has no effect there",
    )
    parser.add_argument(
        "--moe-group-size", default=None, type=int, metavar="G",
        help="token-choice --model moe on the ep mesh strategy: route "
        "each shard's tokens in independent groups of G (GShard grouped "
        "routing) - capacity becomes per-group, keeping the one-hot "
        "dispatch einsums linear in token count.  Default: one global "
        "group per shard (exact-union drop semantics)",
    )
    parser.add_argument(
        "--resume", default=None, type=Path, metavar="PATH|auto",
        help="restore params/optimizer state before training.  A path "
        "loads that checkpoint and retrains the full --epochs on top of "
        "it (historical behavior); the literal 'auto' finds the newest "
        "VALID checkpoint under --checkpoint-directory (corrupt/"
        "truncated files are skipped - resilience/guard.py), CONTINUES "
        "from its epoch, and starts fresh when none exists - the "
        "crash-restart contract",
    )
    parser.add_argument(
        "--checkpoint-every", default=0, type=int, metavar="N",
        help="also write checkpoint-epoch-N.ckpt every N epochs "
        "(0 = best-model-only, the reference's trigger)",
    )
    parser.add_argument(
        "--keep-checkpoints", default=0, type=int, metavar="N",
        help="rotate periodic epoch checkpoints, keeping only the newest "
        "N (0 = keep all; best-model.ckpt is never rotated)",
    )
    parser.add_argument(
        "--max-bad-steps", default=0, type=int, metavar="K",
        help="non-finite guard: skip (not apply) any update step whose "
        "gradients contain NaN/Inf, count it, and abort only after K "
        "consecutive bad steps; 0 disables the guard (historical "
        "behavior: a NaN poisons the params)",
    )
    parser.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="deterministic chaos schedule (resilience/faults.py), e.g. "
        "'step:3:nan,step:7:stall:0.5,epoch:2:kill,net:delay:100,"
        "seed:7'; also read from the PDRNN_CHAOS env when the flag is "
        "absent.  net:* events bridge onto the transport's "
        "PDRNN_FAULT_* contract (the bench netem analogue)",
    )
    parser.add_argument(
        "--grad-accum", default=1, type=int, metavar="K",
        help="accumulate gradients over K equal microbatches per optimizer "
        "step (local trainer; batch sizes must divide by K) - the "
        "activation-memory lever for batches that do not fit HBM",
    )
    parser.add_argument(
        "--sharded-update", default=True,
        action=argparse.BooleanOptionalAction,
        help="cross-replica sharded weight update (2004.13336) on the "
        "pure data-parallel strategies (distributed / horovod / "
        "distributed-native): reduce-scatter the gradient, apply a "
        "1/world-sharded optimizer update, allgather fresh params - "
        "~2x less update-phase collective bytes and 1/world the "
        "optimizer-state memory, bitwise-identical results.  Default "
        "on; --no-sharded-update restores the replicated full apply.  "
        "Inert on strategies that already shard the update (fsdp/mesh)",
    )
    parser.add_argument(
        "--bucketed-comm", default=True,
        action=argparse.BooleanOptionalAction,
        help="overlap gradient communication with the sharded optimizer "
        "apply on distributed-native: the flat gradient is split into "
        "--bucket-mb buckets whose reduce-scatters/allgathers stream on "
        "a comm worker thread while the host applies already-landed "
        "buckets - bitwise-identical to the monolithic schedule, same "
        "wire bytes.  Default on; --no-bucketed-comm restores the "
        "monolithic blocking collectives (the escape hatch if a "
        "transport misbehaves under concurrent handles).  Requires "
        "--sharded-update; inert elsewhere",
    )
    parser.add_argument(
        "--bucket-mb", default=25.0, type=float, metavar="MB",
        help="gradient bucket size in MiB of total wire traffic per "
        "bucket (default 25, torch DDP's bucket_cap_mb); smaller "
        "buckets start overlap earlier but pay more per-collective "
        "latency - tune down for slow links, up for tiny models",
    )
    parser.add_argument(
        "--precision", default="f32", choices=["f32", "bf16"],
        help="bf16: bfloat16 compute (full MXU rate, half the HBM "
        "traffic) with f32 parameters and optimizer state",
    )
    parser.add_argument(
        "--remat", action="store_true",
        help="recompute RNN activations during backward instead of "
        "saving them (trades FLOPs for HBM; for deep/long configs)",
    )
    parser.add_argument(
        "--checkpoint-format", default="gathered",
        choices=["gathered", "sharded"],
        help="gathered: reference-parity single file (state gathered to "
        "the writing host).  sharded: orbax per-shard writes - each "
        "process/device writes only the shards it owns, restore places "
        "them back without ever building a host-side replica (the scale "
        "path for fsdp/mesh layouts); --resume accepts the resulting "
        ".orbax directory",
    )
    parser.add_argument(
        "--checkpoint-async", action="store_true",
        help="hand sharded checkpoint writes to orbax's background "
        "thread so serialization overlaps training (drained before the "
        "next save and at train end); needs --checkpoint-format sharded",
    )
    parser.add_argument(
        "--fuse-run", action="store_true",
        help="compile the whole multi-epoch training run into ONE device "
        "program (lax.scan over epochs) even with INFO logging on; "
        "removes every per-epoch host round-trip (dominant on a "
        "remote-attached chip) at the cost of per-epoch Start-Epoch "
        "messages.  Needs --no-validation, no --checkpoint-every and "
        "--grad-accum 1; rejected loudly otherwise",
    )
    parser.add_argument(
        "--profile", default=None, type=Path, metavar="DIR",
        help="capture a step-level device trace of the training run into "
        "DIR (viewable in TensorBoard/Perfetto); the reference had only "
        "whole-run wall-clock + RSS",
    )
    parser.add_argument(
        "--profile-steps", default=None, metavar="A:B",
        help="bound the --profile capture to optimizer steps [A, B) "
        "instead of tracing the whole run (steady-state steps without "
        "the compile/warm-up noise); skipped gracefully on backends "
        "without profiler support",
    )
    parser.add_argument(
        "--metrics", default=None, type=Path, metavar="PATH",
        help="structured run telemetry (obs/): write rank-tagged JSONL "
        "events (per-step loss/timing/data-wait, collective traffic, "
        "memory peaks, checkpoint/chaos/guard events) to PATH, buffered "
        "off the hot path; summarize with pdrnn-metrics.  Also read "
        "from the PDRNN_METRICS env when the flag is absent.  The "
        "legacy perf line is emitted either way",
    )
    parser.add_argument(
        "--metrics-sample-every", default=None, type=int, metavar="N",
        help="telemetry fence cadence: every N-th step blocks on the "
        "step's outputs to measure true step wall time (default 16); "
        "the other steps stay fully async",
    )
    parser.add_argument(
        "--live", default=None, metavar="[HOST:]PORT",
        help="live observability plane (obs/live.py; needs --metrics): "
        "rank 0 serves GET /metrics (Prometheus text), /health "
        "(ok/stalled/dead/drained per rank), /events (recent alerts) "
        "and /fleet on this address; other ranks push digests to it.  "
        "Arms the anomaly watchdog (in-run stall detection with "
        "all-thread stack dumps, NaN streaks, loss spikes; tune via "
        "PDRNN_WATCHDOG_STALL seconds, disable with PDRNN_WATCHDOG=0).  "
        "Also read from the PDRNN_LIVE env when the flag is absent.  "
        "Watch it live with `pdrnn-metrics watch HOST:PORT`",
    )
    parser.add_argument(
        "--live-port-file", default=None, type=Path, metavar="PATH",
        help="write 'host port' of the live endpoint here once bound "
        "(how scripts and tests find a --live 0 ephemeral port)",
    )

    sub_parser = parser.add_subparsers(
        title="Available commands", metavar="command [options ...]"
    )
    sub_parser.required = True

    # imported lazily so --help works fast and the registries stay decoupled
    from pytorch_distributed_rnn_tpu import param_server, training

    param_server.add_sub_command(sub_parser)
    training.add_sub_commands(sub_parser)
    return parser


def main(argv=None):
    apply_platform_overrides()
    # parse first (no JAX computation happens there) so --help and bad
    # command lines fail fast instead of blocking on a rendezvous
    args = build_parser().parse_args(argv)
    from pytorch_distributed_rnn_tpu.utils import leakcheck

    # resolve PDRNN_LEAKCHECK before the first socket/thread/file
    leakcheck.maybe_install()
    # env-gated multi-host rendezvous (PDRNN_COORDINATOR, or MASTER_ADDR
    # under PDRNN_MULTIHOST=1): must run before the first JAX computation;
    # no-op single-controller otherwise.  The mpirun analogue - SURVEY.md §5.
    from pytorch_distributed_rnn_tpu.parallel.multihost import (
        initialize_multihost,
    )

    initialize_multihost()
    return args.func(args)


if __name__ == "__main__":
    sys.exit(0 if main() is not None else 1)
