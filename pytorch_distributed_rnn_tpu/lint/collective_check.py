"""CI gate for the per-entry collective-traffic artifact (deep lint).

``pdrnn-lint --deep`` emits per-entry traced collective traffic into
``lint-deep-report.json``.  This checker diffs the data-parallel
entries against the checked-in ``lint/collective_expectations.json``
so the sharded weight update's traffic shape (2004.13336) is a gated
contract, not a one-off claim:

- every expected entry is present with EXACTLY the expected per-op
  counts and bytes (any regrowth of update-phase traffic fails CI);
- relational invariants that must hold by construction:

  * a sharded SPMD entry moves gradients by reduce-scatter and params
    by allgather - per-device OUTPUT bytes (the artifact's convention)
    satisfy ``reduce_scatter.bytes * N == all_gather.bytes`` on the
    N-way lint mesh;
  * the matching replicated entry's gradient all-reduce carries the
    full parameter vector: ``all_reduce.bytes >= reduce_scatter.bytes
    * N`` (equality up to the loss/metric scalar all-reduces), i.e.
    the update-phase per-device bytes really dropped ~N/2-fold;
  * the native sharded update program has NO traced collectives (the
    ring runs on the host) and is a strictly smaller program than the
    replicated one (shard-sized operands).

Usage::

    python -m pytorch_distributed_rnn_tpu.lint.collective_check \
        lint-deep-report.json            # diff (CI gate; exit 1 on drift)
    python -m ... lint-deep-report.json --write   # regenerate expectations

Intentional traffic changes regenerate with ``--write`` and commit the
diff - exactly the lint-baseline workflow.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

EXPECTATIONS_PATH = Path(__file__).parent / "collective_expectations.json"

# the pure-DP surface this PR's contract covers; other families' traffic
# is tracked by the ordinary artifact diff in review
GATED_ENTRIES = (
    "dp.spmd_train_step",
    "dp.spmd_train_step_sharded",
    "dp.spmd_train_step_sharded_hvd",
    "dp.spmd_epoch_fn",
    "dp.spmd_epoch_fn_sharded",
    "native_ddp.apply_update",
    "native_ddp.apply_update_sharded",
    "native_ddp.apply_update_bucketed",
)

# the checked-in bucketed wire-shape binding (training/native_ddp.py
# overlapped path): the motion model's 662 params on the world-2 lint
# convention, bucket_mb small enough that the plan holds >1 bucket -
# the same binding the native_ddp.apply_update_bucketed trace entry
# registers.  f32 wire -> itemsize 4.
NATIVE_WIRE_CONFIG = {
    "size": 662, "world": 2, "itemsize": 4, "bucket_mb": 1e-3,
}

# sharded entry -> its replicated twin (for the bytes-drop relation)
SHARDED_TO_REPLICATED = {
    "dp.spmd_train_step_sharded": "dp.spmd_train_step",
    "dp.spmd_train_step_sharded_hvd": "dp.spmd_train_step",
    "dp.spmd_epoch_fn_sharded": "dp.spmd_epoch_fn",
}

# loss + metrics scalar all-reduces ride both flavors; the grad/update
# relation holds up to that slack per traced step
SCALAR_SLACK_BYTES = 64


def load_entries(report_path) -> dict:
    """entry name -> {"collectives": {...}, "eqns": int} from a deep
    report (the artifact CI uploads)."""
    report = json.loads(Path(report_path).read_text())
    deep = report.get("deep") or {}
    rows = deep.get("entries") or []
    out = {}
    for row in rows:
        out[row["entry"]] = {
            "collectives": row.get("collectives") or {},
            "eqns": int(row.get("eqns", 0)),
        }
    return out


def check(entries: dict, expectations: dict, mesh_n: int = 2) -> list[str]:
    """All contract violations (empty = gate passes)."""
    problems = []
    expected_entries = expectations.get("entries", {})
    for name in expectations.get("gated", GATED_ENTRIES):
        if name not in entries:
            problems.append(f"{name}: missing from the deep report "
                            "(entry unregistered or failed to trace)")
            continue
        got = entries[name]["collectives"]
        want = expected_entries.get(name, {}).get("collectives", {})
        if got != want:
            problems.append(
                f"{name}: collective traffic drifted\n"
                f"  expected: {json.dumps(want, sort_keys=True)}\n"
                f"  got:      {json.dumps(got, sort_keys=True)}\n"
                "  (intentional? regenerate with collective_check --write)"
            )

    # relational invariants - independent of the stored numbers, so a
    # --write can never silently launder a broken traffic shape
    for sharded, replicated in SHARDED_TO_REPLICATED.items():
        if sharded not in entries or replicated not in entries:
            continue
        sh = entries[sharded]["collectives"]
        rep = entries[replicated]["collectives"]
        rs = sh.get("reduce-scatter", {}).get("bytes", 0)
        ag = sh.get("all-gather", {}).get("bytes", 0)
        ar = rep.get("all-reduce", {}).get("bytes", 0)
        if not rs or not ag:
            problems.append(
                f"{sharded}: expected reduce-scatter + all-gather update "
                f"phase, got {json.dumps(sh, sort_keys=True)}"
            )
            continue
        if rs * mesh_n != ag:
            problems.append(
                f"{sharded}: reduce-scatter bytes ({rs}) x N ({mesh_n}) "
                f"!= all-gather bytes ({ag}) - the update phase no "
                "longer moves 1/N gradient shards against full params"
            )
        if not (0 <= ar - rs * mesh_n <= SCALAR_SLACK_BYTES * max(
                1, sh.get("reduce-scatter", {}).get("count", 1))):
            problems.append(
                f"{sharded} vs {replicated}: replicated grad all-reduce "
                f"({ar} B) should equal reduce-scatter x N ({rs * mesh_n} "
                "B) up to the loss/metric scalars - the per-device "
                "update-phase bytes did not drop as sharding promises"
            )

    problems += check_native_wire(expectations)
    bucketed_native = entries.get("native_ddp.apply_update_bucketed")
    if bucketed_native and bucketed_native["collectives"]:
        problems.append(
            "native_ddp.apply_update_bucketed: traced collectives "
            f"{json.dumps(bucketed_native['collectives'])} - the per-"
            "bucket update program must stay collective-free (the "
            "bucketed reduce-scatter/allgather ride the host ring's "
            "comm worker)"
        )
    sh_native = entries.get("native_ddp.apply_update_sharded")
    rep_native = entries.get("native_ddp.apply_update")
    if sh_native and rep_native:
        if sh_native["collectives"]:
            problems.append(
                "native_ddp.apply_update_sharded: traced collectives "
                f"{json.dumps(sh_native['collectives'])} - the native "
                "update program must stay collective-free (the ring "
                "reduce-scatter/allgather are host-side)"
            )
        if sh_native["eqns"] >= rep_native["eqns"]:
            problems.append(
                "native_ddp.apply_update_sharded: program not smaller "
                f"than the replicated update ({sh_native['eqns']} vs "
                f"{rep_native['eqns']} eqns) - shard-sized operands "
                "should shrink it"
            )
    return problems


def check_native_wire(expectations: dict) -> list[str]:
    """The bucketed native-ring wire contract: the checked-in per-bucket
    reduce-scatter/allgather byte counts must (a) match the plan
    recomputed fresh from the stored config and (b) SUM to exactly the
    monolithic collective's bytes - overlap must never change the wire
    traffic.  The sum is checked against the STORED numbers, so a
    tampered bucket row fails even before the plan comparison does."""
    from pytorch_distributed_rnn_tpu.parallel.bucketing import plan_buckets

    wire = expectations.get("native_wire")
    if wire is None:
        return ["native_wire: section missing from expectations - the "
                "bucketed wire contract is ungated (regenerate with "
                "collective_check --write)"]
    problems = []
    cfg = wire.get("config", {})
    stored_rs = sum(
        b.get("reduce_scatter_bytes", 0) for b in wire.get("buckets", [])
    )
    stored_ag = sum(
        b.get("allgather_bytes", 0) for b in wire.get("buckets", [])
    )
    mono = wire.get("monolithic", {})
    if stored_rs != mono.get("reduce_scatter_bytes"):
        problems.append(
            f"native_wire: per-bucket reduce-scatter bytes sum to "
            f"{stored_rs}, monolithic is {mono.get('reduce_scatter_bytes')}"
            " - bucketing changed the gradient wire traffic"
        )
    if stored_ag != mono.get("allgather_bytes"):
        problems.append(
            f"native_wire: per-bucket allgather bytes sum to {stored_ag}, "
            f"monolithic is {mono.get('allgather_bytes')} - bucketing "
            "changed the param wire traffic"
        )
    try:
        plan = plan_buckets(cfg["size"], cfg["world"], cfg["itemsize"],
                            cfg["bucket_mb"])
    except (KeyError, ValueError) as exc:
        problems.append(f"native_wire: unreplayable config {cfg}: {exc}")
        return problems
    fresh = plan.wire_expectations()
    if wire != fresh:
        problems.append(
            "native_wire: stored bucket layout drifted from the plan "
            "recomputed from its own config\n"
            f"  expected: {json.dumps(fresh, sort_keys=True)}\n"
            f"  got:      {json.dumps(wire, sort_keys=True)}\n"
            "  (intentional? regenerate with collective_check --write)"
        )
    return problems


def write_expectations(entries: dict, path=EXPECTATIONS_PATH) -> None:
    from pytorch_distributed_rnn_tpu.parallel.bucketing import plan_buckets

    payload = {
        "comment": "checked-in per-entry collective traffic for the "
                   "pure-DP entries; regenerate with "
                   "python -m pytorch_distributed_rnn_tpu.lint."
                   "collective_check <report> --write",
        "gated": list(GATED_ENTRIES),
        "entries": {
            name: {"collectives": entries[name]["collectives"]}
            for name in GATED_ENTRIES if name in entries
        },
        "native_wire": plan_buckets(**NATIVE_WIRE_CONFIG)
        .wire_expectations(),
    }
    Path(path).write_text(json.dumps(payload, indent=2, sort_keys=True)
                          + "\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="collective_check",
        description="diff the deep-lint per-entry collective artifact "
                    "against lint/collective_expectations.json",
    )
    ap.add_argument("report", help="lint-deep-report.json from "
                                   "pdrnn-lint --deep --format json")
    ap.add_argument("--write", action="store_true",
                    help="regenerate the expectation file from the report")
    ap.add_argument("--expectations", default=str(EXPECTATIONS_PATH))
    args = ap.parse_args(argv)

    entries = load_entries(args.report)
    if args.write:
        write_expectations(entries, args.expectations)
        print(f"wrote {args.expectations}")
        return 0
    expectations = json.loads(Path(args.expectations).read_text())
    problems = check(entries, expectations)
    for p in problems:
        print(f"collective-check: {p}", file=sys.stderr)
    if not problems:
        print(f"collective-check: {len(expectations.get('entries', {}))} "
              "entries match; sharded-update invariants hold")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
