"""The lifecycle rule plugins (PD4xx): wire-contract & resource lint.

Fourth lint layer, same machinery: pure ``ast`` like PD1xx/PD3xx
(never imports the checked code), registered through
:func:`lint.core.register` so ``# noqa``, the baseline,
``--select``/``--ignore`` and the JSON/SARIF reports apply unchanged.
The repo speaks four hand-rolled wire protocols (PS binary ops,
serving JSONL, framed MPMD links, the fleet router) and every one of
them grew by hand-reviewed convention: op-codes with handlers found by
grep, sockets whose timeout discipline lives in docstrings, resources
whose error-path cleanup was checked by eye.  These rules make the
wire and lifecycle contracts machine-checked.

Contracts are declared in source comments the rules parse (the same
idiom as PD3xx's ``# guards:`` / ``# lock-order:``):

- ``# protocol: <proto> op <NAME> [oneway]`` declares an op of wire
  protocol ``<proto>`` (trailing the op constant / documented op
  string in the protocol module).  ``oneway`` marks fire-and-forget
  ops that need no reply path.
- ``# protocol: <proto> handles <NAME>[, NAME...]`` registers the
  module (a dispatch loop) as a handler of the named ops.
- ``# protocol: <proto> request <NAME>`` marks a request-send site.
- ``# protocol: <proto> reply <NAME>[, NAME...]`` marks the matching
  reply/error-send site.
- ``# protocol: <proto> field <NAME>`` marks a site that writes or
  reads an OPTIONAL wire field riding the protocol's messages (the
  serve ``trace`` carry): fields have no handler obligation, but a
  field naming a protocol with no declared ops is a typo.
- ``# owner: <who>`` trailing a resource acquisition transfers
  ownership: someone else closes it, PD403 stands down.

Rules:

- **PD401 unhandled-protocol-op** - a declared op no registered
  handler dispatches, a request-send site with no reply/error path
  declared anywhere in the package, or a ``handles``/``request``/
  ``reply`` naming an op the protocol never declared (typo guard).
- **PD402 blocking-socket-no-timeout** - a blocking socket op
  (``recv``/``recv_into``/``accept``/``connect``/``sendall``) on a
  socket that was created without a timeout and never gets a
  ``settimeout``.  Deliberate deadline-free contracts (an accept loop
  unblocked by ``close()``, client-paced connection writes) are
  suppressed in place with ``# noqa: PD402`` plus a rationale comment.
- **PD403 resource-leak** - a ``socket``/``open``/
  ``TemporaryDirectory`` acquisition with an exit path that skips
  ``close``: a local whose only close is straight-line (an exception
  between acquire and close leaks it) or absent, and the
  partial-construction form - ``self.x = socket.socket(...)`` in
  ``__init__`` followed by fallible construction steps with no
  except/finally close.  ``with``, try/finally, a close-and-reraise
  handler, escape (returned/stored/passed on), or a declared
  ``# owner:`` transfer all satisfy it.
- **PD404 unjoined-thread** - a non-daemon ``threading.Thread`` that
  is ``start()``ed but never ``join()``ed (and never handed off).
- **PD405 swallowed-loop-exception** - an ``except`` inside a
  connection/ingest loop that neither re-raises, exits the loop,
  replies an error, records an event, nor feeds a failure counter -
  the handler that turns a systematic fault into silence.

The runtime half of this pass is ``utils/leakcheck.py``: the same
drain-by-exit contracts, enforced live on the repo's socket/thread/
file/tempdir factories when ``PDRNN_LEAKCHECK`` is set.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from pytorch_distributed_rnn_tpu.lint.core import (
    Finding,
    ModuleInfo,
    PackageIndex,
    register,
)

# rule codes this module registers, in one place for the CLI's layer
# label and the baseline preservation guard (mirrors concurrency_rules)
LIFECYCLE_RULES = ("PD401", "PD402", "PD403", "PD404", "PD405")


def lifecycle_rules() -> tuple[str, ...]:
    return LIFECYCLE_RULES


_PROTOCOL_RE = re.compile(
    r"#\s*protocol:\s*(?P<proto>[\w.-]+)\s+"
    r"(?P<verb>op|handles|request|reply|field)\s+"
    r"(?P<names>[A-Za-z_]\w*(?:\s*,\s*[A-Za-z_]\w*)*)"
    r"(?P<oneway>\s+oneway)?"
)
_OWNER_RE = re.compile(r"#\s*owner:\s*(\S.*)$")

_BLOCKING_SOCKET_TAILS = ("recv", "recv_into", "accept", "connect",
                          "sendall")
# calls that make a function "network code" for PD405's loop scan
_NET_TAILS = {
    "recv", "recv_into", "accept", "sendall", "send", "readline",
    "makefile", "create_connection", "connect",
    "recv_request", "recv_params", "recv_state_sync",
    "recv_experience_ext", "recv_experience_reply", "recv_params_at",
    "send_request", "send_params", "send_state_sync",
    "send_experience", "send_experience_reply", "send_params_at",
}
_COUNTER_NAME_RE = re.compile(
    r"(fail|error|drop|reject|poison|abort|shed|dedup)", re.I
)
_MUTATOR_METHODS = {"append", "add", "update", "setdefault", "extend"}


def _anchor(lineno: int) -> ast.AST:
    node = ast.Constant(value=None)
    node.lineno, node.col_offset = lineno, 0
    return node


def _has_owner(mod: ModuleInfo, lineno: int) -> bool:
    return bool(_OWNER_RE.search(mod.line_text(lineno)))


# ---------------------------------------------------------------------------
# PD401 unhandled-protocol-op


def _protocol_tables(index: PackageIndex) -> dict:
    """Package-wide ``# protocol:`` registry, cached on the index:
    ``proto -> {"ops": {name: (oneway, path, line)}, "handles":
    {name: [(path, line)]}, "requests": [(name, path, line)],
    "replies": {name: [(path, line)]},
    "fields": {name: [(path, line)]}}``."""
    cached = getattr(index, "_lifecycle_protocols", None)
    if cached is not None:
        return cached
    tables: dict = {}
    for mod in index.modules:
        for lineno, text in enumerate(mod.lines, start=1):
            m = _PROTOCOL_RE.search(text)
            if not m:
                continue
            proto = tables.setdefault(m.group("proto"), {
                "ops": {}, "handles": {}, "requests": [], "replies": {},
                "fields": {},
            })
            names = [n.strip() for n in m.group("names").split(",")
                     if n.strip()]
            verb = m.group("verb")
            for name in names:
                if verb == "op":
                    proto["ops"][name] = (
                        bool(m.group("oneway")), mod.path, lineno,
                    )
                elif verb == "handles":
                    proto["handles"].setdefault(name, []).append(
                        (mod.path, lineno))
                elif verb == "request":
                    proto["requests"].append((name, mod.path, lineno))
                elif verb == "field":
                    proto["fields"].setdefault(name, []).append(
                        (mod.path, lineno))
                else:
                    proto["replies"].setdefault(name, []).append(
                        (mod.path, lineno))
    index._lifecycle_protocols = tables  # type: ignore[attr-defined]
    return tables


@register(
    "PD401", "unhandled-protocol-op",
    "a declared protocol op with no registered handler, a request-send "
    "site with no reply/error path, or a `# protocol:` reference to an "
    "undeclared op (declare ops/handlers/requests/replies with "
    "`# protocol:` registry comments)",
)
def check_unhandled_protocol_op(mod: ModuleInfo,
                                index: PackageIndex) -> Iterator[Finding]:
    tables = _protocol_tables(index)
    for proto_name, proto in tables.items():
        ops = proto["ops"]
        for name, (oneway, path, lineno) in ops.items():
            if path != mod.path:
                continue
            if name not in proto["handles"]:
                yield mod.finding(
                    "PD401", _anchor(lineno),
                    f"protocol '{proto_name}' op {name} has no "
                    f"registered handler (`# protocol: {proto_name} "
                    f"handles {name}` at the dispatch site)",
                )
        for name, path, lineno in proto["requests"]:
            if path != mod.path:
                continue
            if name not in ops:
                yield mod.finding(
                    "PD401", _anchor(lineno),
                    f"request declares op {name} which protocol "
                    f"'{proto_name}' never declared (`# protocol: "
                    f"{proto_name} op {name}` in the protocol module)",
                )
            elif not ops[name][0] and name not in proto["replies"]:
                yield mod.finding(
                    "PD401", _anchor(lineno),
                    f"request-send of '{proto_name}' op {name} has no "
                    f"matching reply/error path anywhere (`# protocol: "
                    f"{proto_name} reply {name}` at the reply site, or "
                    f"declare the op oneway)",
                )
        for table in ("handles", "replies"):
            for name, sites in proto[table].items():
                if name in ops:
                    continue
                for path, lineno in sites:
                    if path != mod.path:
                        continue
                    yield mod.finding(
                        "PD401", _anchor(lineno),
                        f"`{table}` declares op {name} which protocol "
                        f"'{proto_name}' never declared (typo, or add "
                        f"`# protocol: {proto_name} op {name}`)",
                    )
        if not ops:
            # a field riding a protocol that declares no ops anywhere
            # is a misspelled protocol name, not an extension point
            for name, sites in proto["fields"].items():
                for path, lineno in sites:
                    if path != mod.path:
                        continue
                    yield mod.finding(
                        "PD401", _anchor(lineno),
                        f"field {name} rides protocol '{proto_name}' "
                        "which declares no ops anywhere (typo in the "
                        "protocol name?)",
                    )


# ---------------------------------------------------------------------------
# PD402 blocking-socket-no-timeout


def _socket_key(node: ast.AST) -> str | None:
    """A stable per-module key for a socket-holding expression: bare
    names key by name, attribute chains by the attribute tail (so
    ``self._listener`` and ``server._listener`` share discipline)."""
    if isinstance(node, ast.Name):
        return f"n:{node.id}"
    if isinstance(node, ast.Attribute):
        return f"a:{node.attr}"
    return None


def _socket_factory(mod: ModuleInfo, value: ast.AST) -> tuple | None:
    """``(kind, timed)`` when ``value`` constructs a socket: a bare
    ``socket.socket(...)`` is untimed; ``socket.create_connection``
    is timed iff a timeout argument rides the call."""
    if not isinstance(value, ast.Call):
        return None
    resolved = mod.resolve(value.func) or ""
    if resolved == "socket.socket":
        return ("socket", False)
    if resolved == "socket.create_connection":
        timed = len(value.args) >= 2 or any(
            kw.arg == "timeout" for kw in value.keywords)
        return ("create_connection", timed)
    return None


def _scopes_related(a: str, b: str) -> bool:
    """True when one qualname scope encloses the other (or matches):
    a binding is visible in nested closures, and a ``settimeout`` in
    either direction along the chain covers the binding."""
    return (a == b or a == "" or b == ""
            or a.startswith(b + ".") or b.startswith(a + "."))


def _module_sockets(mod: ModuleInfo) -> tuple[set, set, dict, dict]:
    """Socket bindings of this module.  Attribute sockets
    (``self._listener``) key by attribute tail module-wide (the repo's
    convention is one meaning per attr name per module); bare names are
    scoped by their enclosing function qualname so ``conn`` in one
    class's handler does not taint ``conn`` in another's."""
    attr_sockets: set[str] = set()
    attr_timed: set[str] = set()
    name_bindings: dict[str, list[str]] = {}
    name_timeouts: dict[str, list[str]] = {}

    def bind(target: ast.AST, node: ast.AST, timed: bool) -> None:
        if isinstance(target, ast.Attribute):
            attr_sockets.add(target.attr)
            if timed:
                attr_timed.add(target.attr)
        elif isinstance(target, ast.Name) and not timed:
            name_bindings.setdefault(target.id, []).append(
                mod.enclosing_function(node))

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign):
            fac = _socket_factory(mod, node.value)
            for target in node.targets:
                if fac is not None:
                    bind(target, node, fac[1])
                # x, addr = listener.accept() binds a fresh socket
                if (isinstance(target, ast.Tuple) and target.elts
                        and isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Attribute)
                        and node.value.func.attr == "accept"):
                    bind(target.elts[0], node, False)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = mod.enclosing_function(node)
            scope = f"{qual}.{node.name}" if qual else node.name
            for arg in (node.args.args + node.args.kwonlyargs):
                if arg.annotation is not None and (
                        mod.resolve(arg.annotation) == "socket.socket"):
                    name_bindings.setdefault(arg.arg, []).append(scope)
        elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and node.func.attr == "settimeout":
            base = node.func.value
            if isinstance(base, ast.Attribute):
                attr_timed.add(base.attr)
            elif isinstance(base, ast.Name):
                name_timeouts.setdefault(base.id, []).append(
                    mod.enclosing_function(node))
    return attr_sockets, attr_timed, name_bindings, name_timeouts


@register(
    "PD402", "blocking-socket-no-timeout",
    "blocking socket op (recv/recv_into/accept/connect/sendall) on a "
    "socket created without a timeout and never given a settimeout - "
    "a wedged peer then hangs the caller forever",
)
def check_blocking_socket_no_timeout(
        mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
    attr_sockets, attr_timed, name_bindings, name_timeouts = (
        _module_sockets(mod))
    if not attr_sockets and not name_bindings:
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute) \
                or func.attr not in _BLOCKING_SOCKET_TAILS:
            continue
        base = func.value
        if isinstance(base, ast.Attribute):
            if base.attr not in attr_sockets or base.attr in attr_timed:
                continue
            shown = base.attr
        elif isinstance(base, ast.Name):
            qual = mod.enclosing_function(node)
            if not any(_scopes_related(b, qual)
                       for b in name_bindings.get(base.id, ())):
                continue
            if any(_scopes_related(t, qual)
                   for t in name_timeouts.get(base.id, ())):
                continue
            shown = base.id
        else:
            continue
        yield mod.finding(
            "PD402", node,
            f".{func.attr}() on `{shown}` can block forever: the "
            f"socket has no timeout (settimeout it, pass timeout= at "
            f"create_connection, or state the deadline-free contract "
            f"with `# noqa: PD402` + a comment)",
        )


# ---------------------------------------------------------------------------
# PD403 resource-leak

_ACQUIRE_KINDS = {
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "open": "file",
    "tempfile.TemporaryDirectory": "tempdir",
}
_CLOSE_TAILS = ("close", "cleanup")


def _acquisition_kind(mod: ModuleInfo, value: ast.AST) -> str | None:
    if not isinstance(value, ast.Call):
        return None
    resolved = mod.resolve(value.func) or ""
    kind = _ACQUIRE_KINDS.get(resolved)
    if kind is not None:
        return kind
    if isinstance(value.func, ast.Attribute) \
            and value.func.attr == "accept":
        return "socket"
    return None


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _escaped_names(fn: ast.AST) -> set[str]:
    """Names whose object leaves the function's custody: returned or
    yielded, passed to another call, or stored into an attribute/
    subscript - the new owner closes it (PD403 stands down)."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            out |= _names_in(node.value)
        elif isinstance(node, (ast.Yield, ast.YieldFrom)) \
                and node.value is not None:
            out |= _names_in(node.value)
        elif isinstance(node, ast.Call):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                out |= _names_in(arg)
        elif isinstance(node, ast.Assign):
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in node.targets):
                out |= _names_in(node.value)
    return out


def _close_context(mod: ModuleInfo, node: ast.AST) -> str:
    """Where a close call sits: ``finally`` / ``except`` survive an
    exception between acquire and close, ``straight`` does not."""
    cur: ast.AST | None = node
    while cur is not None:
        par = mod.parents.get(cur)
        if isinstance(par, ast.Try) and cur in par.finalbody:
            return "finally"
        if isinstance(par, ast.ExceptHandler):
            return "except"
        cur = par
    return "straight"


def _close_calls(fn: ast.AST, name: str) -> list[ast.Call]:
    out = []
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _CLOSE_TAILS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == name):
            out.append(node)
    return out


def _function_defs(mod: ModuleInfo) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node  # type: ignore[misc]


@register(
    "PD403", "resource-leak",
    "socket/open/TemporaryDirectory acquired on a path that can skip "
    "its close: straight-line-only (or missing) close on a local, or "
    "a partially-constructed __init__ attribute with no except/finally "
    "close (use with/try-finally, close-and-reraise, or `# owner:`)",
)
def check_resource_leak(mod: ModuleInfo,
                        index: PackageIndex) -> Iterator[Finding]:
    # -- locals: acquire -> must close on every exit path ------------
    for fn in _function_defs(mod):
        nested = {n for sub in ast.walk(fn)
                  if isinstance(sub, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                  and sub is not fn
                  for n in ast.walk(sub)}
        escapes = _escaped_names(fn)
        for node in ast.walk(fn):
            if node in nested or not isinstance(node, ast.Assign):
                continue
            kind = _acquisition_kind(mod, node.value)
            if kind is None:
                continue
            target = node.targets[0]
            if isinstance(target, ast.Tuple) and target.elts:
                target = target.elts[0]
            if not isinstance(target, ast.Name):
                continue  # attribute targets: the __init__ prong below
            name = target.id
            if _has_owner(mod, node.lineno) or name in escapes:
                continue
            contexts = {_close_context(mod, c)
                        for c in _close_calls(fn, name)}
            if "finally" in contexts or "except" in contexts:
                continue
            if contexts:
                yield mod.finding(
                    "PD403", node,
                    f"`{name}` ({kind}) is closed only on the "
                    f"straight-line path - an exception between "
                    f"acquire and close leaks it (use `with` or "
                    f"try/finally)",
                )
            else:
                yield mod.finding(
                    "PD403", node,
                    f"`{name}` ({kind}) is acquired but never closed "
                    f"in `{fn.name}` (close it, use `with`, or "
                    f"declare the transfer with `# owner:`)",
                )
    # -- __init__: partial construction must not strand the resource -
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is None:
            continue
        protected = _init_protected_attrs(init)
        for idx, stmt in enumerate(init.body):
            if not isinstance(stmt, ast.Assign):
                continue
            kind = _acquisition_kind(mod, stmt.value)
            if kind is None:
                continue
            target = stmt.targets[0]
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            attr = target.attr
            if _has_owner(mod, stmt.lineno) or attr in protected:
                continue
            fallible = any(
                isinstance(sub, ast.Call)
                for later in init.body[idx + 1:]
                for sub in ast.walk(later)
            )
            if fallible:
                yield mod.finding(
                    "PD403", stmt,
                    f"`self.{attr}` ({kind}) leaks when a later "
                    f"__init__ step raises: the object is never "
                    f"published, nobody can close it (wrap the tail "
                    f"in try/except closing `self.{attr}`, or "
                    f"declare `# owner:`)",
                )


def _init_protected_attrs(init: ast.FunctionDef) -> set[str]:
    """self-attrs that some except/finally inside __init__ closes."""
    out: set[str] = set()
    for node in ast.walk(init):
        if not isinstance(node, ast.Try):
            continue
        regions = list(node.finalbody)
        for handler in node.handlers:
            regions.extend(handler.body)
        for region in regions:
            for sub in ast.walk(region):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in _CLOSE_TAILS
                        and isinstance(sub.func.value, ast.Attribute)
                        and isinstance(sub.func.value.value, ast.Name)
                        and sub.func.value.value.id == "self"):
                    out.add(sub.func.value.attr)
    return out


# ---------------------------------------------------------------------------
# PD404 unjoined-thread


def _is_thread_ctor(mod: ModuleInfo, value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    resolved = mod.resolve(value.func) or ""
    return resolved == "threading.Thread" \
        or resolved.rsplit(".", 1)[-1] == "Thread"


def _daemon_kwarg(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


@register(
    "PD404", "unjoined-thread",
    "non-daemon thread start()ed but never join()ed (and never handed "
    "off) - process exit then blocks on it forever",
)
def check_unjoined_thread(mod: ModuleInfo,
                          index: PackageIndex) -> Iterator[Finding]:
    # chained Thread(...).start() can never be joined at all
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "start"
                and _is_thread_ctor(mod, node.func.value)
                and not _daemon_kwarg(node.func.value)):
            yield mod.finding(
                "PD404", node,
                "non-daemon `Thread(...).start()` is unbound - it can "
                "never be joined (bind it and join, or daemon=True)",
            )
    # bound threads: started, non-daemon, no join on the binding name
    bindings: dict[str, tuple[ast.Assign, bool]] = {}
    daemon_marked: set[str] = set()
    started: set[str] = set()
    joined: set[str] = set()
    escaped: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign):
            if _is_thread_ctor(mod, node.value):
                for target in node.targets:
                    key = _socket_key(target)
                    if key is not None:
                        bindings[key] = (node, _daemon_kwarg(node.value))
            else:
                # t.daemon = True before start
                for target in node.targets:
                    if isinstance(target, ast.Attribute) \
                            and target.attr == "daemon":
                        key = _socket_key(target.value)
                        if key is not None and isinstance(
                                node.value, ast.Constant) \
                                and node.value.value:
                            daemon_marked.add(key)
                # ownership transfer: self.x = t / d[k] = t
                if any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in node.targets) \
                        and isinstance(node.value, ast.Name):
                    escaped.add(f"n:{node.value.id}")
        elif isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute):
                key = _socket_key(node.func.value)
                if key is not None:
                    if node.func.attr == "start":
                        started.add(key)
                    elif node.func.attr == "join":
                        joined.add(key)
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    escaped.add(f"n:{arg.id}")
                elif isinstance(arg, ast.Attribute):
                    escaped.add(f"a:{arg.attr}")
        elif isinstance(node, ast.Return) and node.value is not None:
            for n in _names_in(node.value):
                escaped.add(f"n:{n}")
    for key, (node, daemon) in bindings.items():
        if daemon or key in daemon_marked or key not in started:
            continue
        if key in joined or key in escaped:
            continue
        shown = key.split(":", 1)[1]
        yield mod.finding(
            "PD404", node,
            f"non-daemon thread `{shown}` is start()ed but never "
            f"join()ed (join it, mark daemon=True, or transfer "
            f"ownership)",
        )


# ---------------------------------------------------------------------------
# PD405 swallowed-loop-exception


def _is_net_function(mod: ModuleInfo, fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _NET_TAILS:
            return True
        resolved = mod.resolve(node.func) or ""
        if resolved.rsplit(".", 1)[-1] in _NET_TAILS and "." in resolved:
            return True
    return False


def _handler_accounts(handler: ast.ExceptHandler) -> bool:
    """A handler accounts for the failure when it re-raises, exits the
    loop, replies (send*), records an event, or feeds a counter whose
    name says failure."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Break)):
            return True
        if isinstance(node, ast.Call):
            func = node.func
            tail = None
            if isinstance(func, ast.Attribute):
                tail = func.attr
            elif isinstance(func, ast.Name):
                tail = func.id
            if tail is not None and (
                    tail == "record" or tail.startswith("send")
                    or tail.startswith("reply")):
                return True
            if isinstance(func, ast.Attribute) \
                    and func.attr in _MUTATOR_METHODS \
                    and isinstance(func.value, (ast.Name, ast.Attribute)):
                base = func.value
                name = base.id if isinstance(base, ast.Name) else base.attr
                if _COUNTER_NAME_RE.search(name):
                    return True
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for target in targets:
                base = target
                while isinstance(base, ast.Subscript):
                    # counters keyed by name: stats["recv_failures"] += 1
                    sl = base.slice
                    if isinstance(sl, ast.Constant) \
                            and isinstance(sl.value, str) \
                            and _COUNTER_NAME_RE.search(sl.value):
                        return True
                    base = base.value
                name = None
                if isinstance(base, ast.Name):
                    name = base.id
                elif isinstance(base, ast.Attribute):
                    name = base.attr
                if name is not None and _COUNTER_NAME_RE.search(name):
                    return True
    return False


@register(
    "PD405", "swallowed-loop-exception",
    "except inside a connection/ingest loop that neither re-raises, "
    "exits, replies an error, records an event, nor feeds a failure "
    "counter - a systematic fault becomes silence",
)
def check_swallowed_loop_exception(
        mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
    for fn in _function_defs(mod):
        if not _is_net_function(mod, fn):
            continue
        nested = {n for sub in ast.walk(fn)
                  if isinstance(sub, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                  and sub is not fn
                  for n in ast.walk(sub)}
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.While, ast.For)) \
                    or loop in nested:
                continue
            for stmt in loop.body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Try) or node in nested:
                        continue
                    for handler in node.handlers:
                        if not _handler_accounts(handler):
                            yield mod.finding(
                                "PD405", handler,
                                f"exception swallowed inside the "
                                f"connection/ingest loop of "
                                f"`{fn.name}`: count it "
                                f"(*_failed/errors), record() it, "
                                f"reply an error, or re-raise",
                            )
