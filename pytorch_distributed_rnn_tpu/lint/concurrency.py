"""The concurrency rule plugins (PD3xx): lock-discipline lint.

Third lint layer, same machinery: pure ``ast`` like PD1xx (never
imports the checked code), registered through :func:`lint.core.register`
so ``# noqa``, the baseline, ``--select``/``--ignore`` and the JSON
report apply unchanged.  The repo is a thread-heavy runtime - recorder
writer thread, aggregator HTTP handler threads, serving engine +
per-connection readers, PS/streaming service threads - and every
threading bug so far was caught by hand in review.  These rules make
the lock contracts machine-checked.

Contracts are declared in source comments the rules parse:

- ``# guards: attr, other_attr`` trailing a lock-attribute assignment
  declares the attributes that lock protects.  Declared attributes are
  enforced STRICTLY: every read or write outside a ``with self.<lock>:``
  block (past ``__init__``) is a PD301.  Undeclared locks get a
  write-only inference pass instead: an attribute assigned under the
  lock in one method and assigned without it in another is flagged.
- ``# lock-order: A.lock -> B._lock [-> C._mu]`` anywhere in a module
  declares cross-class acquisition edges the static nesting scan cannot
  see (e.g. "the master's round lock is taken before the Roster's").
  Declared edges join the statically-derived acquisition graph PD303
  runs cycle detection over, package-wide.
- ``# holds: lock`` trailing a ``def`` line declares a
  caller-holds-the-lock method: its body is analyzed as if the named
  lock(s) were held throughout.  Methods whose name ends in ``_locked``
  get the same treatment for every class lock (the repo's existing
  naming convention for must-hold helpers).

Rules:

- **PD301 unguarded-shared-attr** - access to a lock-guarded attribute
  without holding the lock (declared guards: any access; inferred
  guards: writes).
- **PD302 blocking-call-under-lock** - a blocking call (socket
  send/recv/accept, ``sendall``, the protocol send/recv helpers,
  ``fsync``, zero-argument ``.join()``, ``time.sleep``,
  ``block_until_ready``, checkpoint writes) inside a ``with
  self.<lock>:`` body - the exact bug class fixed twice already
  (checkpoint serialization inside the PS round lock, sends under the
  learner's version lock).  Deliberate hold-while-sending contracts are
  suppressed in place with ``# noqa: PD302`` plus a comment stating the
  rationale.
- **PD303 lock-order-inversion** - a cycle in the acquisition graph
  derived from syntactic ``with`` nesting, one level of intra-class
  call-through, and the ``# lock-order:`` declarations.
- **PD304 raw-acquire-release** - ``.acquire()``/``.release()`` on a
  lock attribute instead of a ``with`` statement (an exception between
  the pair leaks the lock); non-blocking/timeout forms, which ``with``
  cannot express, are exempt.
- **PD305 unguarded-module-global** - a mutable module-level global
  written from a thread-target function with no ``with <lock>:`` around
  the write.

The runtime half of this pass is ``utils/threadcheck.py``: the same
acquisition-order contracts, enforced live on the repo's wrapped locks
when ``PDRNN_THREADCHECK`` is set.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Iterator

from pytorch_distributed_rnn_tpu.lint.core import (
    Finding,
    ModuleInfo,
    PackageIndex,
    register,
)

# rule codes this module registers, in one place for the CLI's layer
# label and the baseline preservation guard (mirrors jaxpr_pass.deep_rules)
CONCURRENCY_RULES = ("PD301", "PD302", "PD303", "PD304", "PD305")


def concurrency_rules() -> tuple[str, ...]:
    return CONCURRENCY_RULES


_GUARDS_RE = re.compile(r"#\s*guards:\s*([A-Za-z_][\w,\s]*)")
_HOLDS_RE = re.compile(r"#\s*holds:\s*([A-Za-z_][\w,\s]*)")
_LOCK_ORDER_RE = re.compile(r"#\s*lock-order:\s*(.+)$")
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
# helpers that wrap-and-return a lock (utils/threadcheck.lock); the
# wrapped constructor is the first argument
_LOCK_WRAPPERS = {"lock"}

# blocking calls that must not run under a lock.  Attribute-call tails:
# anything socket-shaped, the repo's framed-protocol helpers, fsync,
# device fences, checkpoint writes.
_BLOCKING_TAILS = {
    "sendall", "recv", "accept", "connect", "recv_into",
    "send_params", "recv_params", "send_msg", "recv_msg",
    "send_frame", "recv_frame",
    "fsync", "block_until_ready", "sleep",
    "save_checkpoint", "write_checkpoint", "checkpoint_save",
}
# .join() with no positional args is a thread/process join; str.join and
# os.path.join always take one
_JOIN_TAIL = "join"

_MUTATOR_METHODS = {
    "append", "appendleft", "add", "update", "pop", "popleft",
    "setdefault", "extend", "remove", "discard", "clear", "insert",
}


# ---------------------------------------------------------------------------
# per-class lock model


@dataclass
class ClassLocks:
    node: ast.ClassDef
    # lock attr name -> assignment lineno
    locks: dict[str, int] = field(default_factory=dict)
    # condition attr -> the lock attr it wraps (Condition(self.lock))
    wraps: dict[str, str] = field(default_factory=dict)
    # declared: lock attr -> attrs from its "# guards:" comment
    declared: dict[str, set[str]] = field(default_factory=dict)
    # inferred: attr -> lock attrs it was WRITTEN under
    written_under: dict[str, set[str]] = field(default_factory=dict)
    # attr writes outside any lock: list of (attr, node, method name)
    unlocked_writes: list = field(default_factory=list)
    # attr reads/writes outside any lock (for declared enforcement)
    unlocked_access: list = field(default_factory=list)


def _lock_ctor_tail(mod: ModuleInfo, value: ast.AST) -> str | None:
    """The threading constructor tail for ``threading.Lock()`` /
    ``Condition(...)`` / ``threadcheck.lock(threading.Lock(), ...)``
    forms, else None."""
    if not isinstance(value, ast.Call):
        return None
    resolved = mod.resolve(value.func) or ""
    tail = resolved.rsplit(".", 1)[-1]
    if tail in _LOCK_WRAPPERS and value.args:
        return _lock_ctor_tail(mod, value.args[0])
    if tail in _LOCK_CTORS and (
            resolved.startswith("threading.") or resolved == tail):
        return tail
    return None


def _self_attr(node: ast.AST) -> str | None:
    """``self.X`` -> ``"X"``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _condition_wrapped_lock(value: ast.Call) -> str | None:
    """``threading.Condition(self.lock)`` -> ``"lock"``."""
    if value.args:
        return _self_attr(value.args[0])
    return None


def _with_lock_attrs(cls: ClassLocks, stmt: ast.With) -> list[str]:
    """Lock attrs this ``with`` acquires (conditions resolve to the
    lock they wrap, so ``with self._sync_cv`` counts as holding
    ``self.lock``)."""
    out = []
    for item in stmt.items:
        attr = _self_attr(item.context_expr)
        if attr and attr in cls.locks:
            out.append(cls.wraps.get(attr, attr))
            # holding a condition holds its wrapped lock AND counts as
            # the condition name itself for declared-guards lookups
            if attr != cls.wraps.get(attr, attr):
                out.append(attr)
    return out


def _parse_guards(mod: ModuleInfo, lineno: int) -> set[str]:
    m = _GUARDS_RE.search(mod.line_text(lineno))
    if not m:
        return set()
    return {a.strip() for a in m.group(1).split(",") if a.strip()}


def _class_locks(mod: ModuleInfo, node: ast.ClassDef) -> ClassLocks:
    cls = ClassLocks(node=node)
    for stmt in ast.walk(node):
        if not isinstance(stmt, ast.Assign):
            continue
        for target in stmt.targets:
            attr = _self_attr(target)
            if attr is None:
                continue
            tail = _lock_ctor_tail(mod, stmt.value)
            if tail is None:
                continue
            cls.locks[attr] = stmt.lineno
            if tail == "Condition" and isinstance(stmt.value, ast.Call):
                inner = stmt.value
                # unwrap threadcheck.lock(...) around the Condition call
                resolved = mod.resolve(inner.func) or ""
                if resolved.rsplit(".", 1)[-1] in _LOCK_WRAPPERS \
                        and inner.args and isinstance(inner.args[0],
                                                      ast.Call):
                    inner = inner.args[0]
                wrapped = _condition_wrapped_lock(inner)
                if wrapped:
                    cls.wraps[attr] = wrapped
            guards = _parse_guards(mod, stmt.lineno)
            if guards:
                cls.declared[attr] = guards
    return cls


def _methods(node: ast.ClassDef) -> list[ast.FunctionDef]:
    return [n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _method_holds(mod: ModuleInfo, cls: ClassLocks,
                  method: ast.FunctionDef) -> frozenset[str]:
    """Locks the method's CALLER holds by contract: a ``# holds: lock``
    trailing comment on the ``def`` line, or the ``_locked`` name
    suffix (held for every class lock)."""
    names: set[str] = set()
    m = _HOLDS_RE.search(mod.line_text(method.lineno))
    if m:
        names = {a.strip() for a in m.group(1).split(",") if a.strip()}
    if method.name.endswith("_locked"):
        names |= set(cls.locks)
    held: set[str] = set()
    for n in names & set(cls.locks):
        held.add(cls.wraps.get(n, n))
        held.add(n)
    return frozenset(held)


def _scan_accesses(mod: ModuleInfo, cls: ClassLocks) -> None:
    """Fill the per-class access tables: which self-attributes are
    read/written, and under which locks."""
    for method in _methods(cls.node):
        if method.name in ("__init__", "__post_init__", "__new__"):
            continue  # construction happens-before publication
        entry_held = _method_holds(mod, cls, method)

        def visit(node: ast.AST, held: frozenset[str]):
            if isinstance(node, ast.With):
                acquired = _with_lock_attrs(cls, node)
                inner = held | frozenset(acquired)
                for item in node.items:
                    visit(item.context_expr, held)
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not method:
                return  # nested defs run on their own schedule
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for target in targets:
                    base = target
                    # self.x[k] = v / self.x.y = v mutate self.x
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    attr = _self_attr(base)
                    if attr and attr not in cls.locks:
                        if held:
                            for lk in held:
                                cls.written_under.setdefault(
                                    attr, set()).add(lk)
                        else:
                            cls.unlocked_writes.append(
                                (attr, node, method.name))
                            cls.unlocked_access.append(
                                (attr, node, method.name))
            if isinstance(node, ast.Call):
                # self.x.append(...) and friends are writes to self.x
                func = node.func
                if isinstance(func, ast.Attribute) \
                        and func.attr in _MUTATOR_METHODS:
                    attr = _self_attr(func.value)
                    if attr and attr not in cls.locks:
                        if held:
                            for lk in held:
                                cls.written_under.setdefault(
                                    attr, set()).add(lk)
                        else:
                            cls.unlocked_writes.append(
                                (attr, node, method.name))
                            cls.unlocked_access.append(
                                (attr, node, method.name))
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                attr = _self_attr(node)
                if attr and attr not in cls.locks and not held:
                    cls.unlocked_access.append((attr, node, method.name))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in method.body:
            visit(stmt, entry_held)


# ---------------------------------------------------------------------------
# PD301 unguarded-shared-attr


@register(
    "PD301", "unguarded-shared-attr",
    "access to a lock-guarded attribute without holding the lock "
    "(declared `# guards:` attrs: any access; inferred: writes)",
)
def check_unguarded_shared_attr(mod: ModuleInfo,
                                index: PackageIndex) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cls = _class_locks(mod, node)
        if not cls.locks:
            continue
        _scan_accesses(mod, cls)

        declared_of: dict[str, str] = {}
        for lock, attrs in cls.declared.items():
            for attr in attrs:
                declared_of[attr] = lock

        seen: set[tuple[str, int]] = set()
        # declared guards: strict - reads and writes both need the lock
        for attr, site, method in cls.unlocked_access:
            lock = declared_of.get(attr)
            if lock is None:
                continue
            key = (attr, site.lineno)
            if key in seen:
                continue
            seen.add(key)
            yield mod.finding(
                "PD301", site,
                f"`self.{attr}` is declared `# guards:`-protected by "
                f"`self.{lock}` but accessed without holding it in "
                f"`{method}`",
            )
        # inferred guards: an attr written under a lock somewhere must
        # not be written lock-free elsewhere
        for attr, site, method in cls.unlocked_writes:
            locks = cls.written_under.get(attr)
            if not locks or attr in declared_of:
                continue
            key = (attr, site.lineno)
            if key in seen:
                continue
            seen.add(key)
            shown = ", ".join(f"self.{lk}" for lk in sorted(locks))
            yield mod.finding(
                "PD301", site,
                f"`self.{attr}` is written under {shown} elsewhere in "
                f"`{node.name}` but written lock-free in `{method}`",
            )


# ---------------------------------------------------------------------------
# PD302 blocking-call-under-lock


def _blocking_reason(mod: ModuleInfo, call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Attribute):
        if func.attr in _BLOCKING_TAILS:
            return f".{func.attr}() blocks"
        if func.attr == _JOIN_TAIL and not call.args:
            return ".join() waits on another thread"
    resolved = mod.resolve(func)
    if resolved is None:
        return None
    tail = resolved.rsplit(".", 1)[-1]
    if resolved in ("time.sleep",) or tail == "block_until_ready":
        return f"{tail}() blocks"
    if tail in _BLOCKING_TAILS and "." in resolved:
        return f"{tail}() blocks"
    return None


@register(
    "PD302", "blocking-call-under-lock",
    "blocking call (socket send/recv, protocol helpers, fsync, "
    ".join(), sleep, block_until_ready, checkpoint writes) inside a "
    "`with self.<lock>:` body",
)
def check_blocking_under_lock(mod: ModuleInfo,
                              index: PackageIndex) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cls = _class_locks(mod, node)
        if not cls.locks:
            continue
        for stmt in ast.walk(node):
            if not isinstance(stmt, ast.With):
                continue
            held = _with_lock_attrs(cls, stmt)
            if not held:
                continue
            for sub in ast.walk(stmt):
                if sub is stmt or isinstance(sub, ast.With):
                    # nested with blocks are themselves scanned; their
                    # bodies would double-report
                    continue
                if not isinstance(sub, ast.Call):
                    continue
                # cv.wait()/notify() release/own the lock by design
                if isinstance(sub.func, ast.Attribute) and sub.func.attr \
                        in ("wait", "wait_for", "notify", "notify_all"):
                    continue
                why = _blocking_reason(mod, sub)
                if why is not None:
                    shown = ", ".join(f"self.{lk}"
                                      for lk in sorted(set(held)))
                    yield mod.finding(
                        "PD302", sub,
                        f"{why} while holding {shown} (move the "
                        "blocking call outside the lock or state the "
                        "hold contract with `# noqa: PD302` + a "
                        "comment)",
                    )


# ---------------------------------------------------------------------------
# PD303 lock-order-inversion

def _qualify(cls_name: str, attr: str) -> str:
    return f"{cls_name}.{attr}"


def _declared_order_edges(mod: ModuleInfo) -> Iterator[tuple]:
    for lineno, text in enumerate(mod.lines, start=1):
        m = _LOCK_ORDER_RE.search(text)
        if not m:
            continue
        chain = [p.strip() for p in m.group(1).split("->")]
        chain = [p for p in chain if p]
        for a, b in zip(chain, chain[1:]):
            yield (a, b, mod.path, lineno)


def _nesting_edges(mod: ModuleInfo) -> Iterator[tuple]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cls = _class_locks(mod, node)
        if not cls.locks:
            continue
        # which locks each method acquires at its top scope (for the
        # one-level call-through edges)
        method_acquires: dict[str, set[str]] = {}
        for method in _methods(node):
            acq = set()
            for sub in ast.walk(method):
                if isinstance(sub, ast.With):
                    acq.update(_with_lock_attrs(cls, sub))
            method_acquires[method.name] = acq

        for method in _methods(node):
            def visit(n: ast.AST, held: tuple[str, ...]):
                if isinstance(n, ast.With):
                    acquired = _with_lock_attrs(cls, n)
                    for lk in acquired:
                        for h in held:
                            if h != lk:
                                yield (_qualify(node.name, h),
                                       _qualify(node.name, lk),
                                       mod.path, n.lineno)
                    inner = held + tuple(a for a in acquired
                                         if a not in held)
                    for item in n.items:
                        yield from visit(item.context_expr, held)
                    for child in n.body:
                        yield from visit(child, inner)
                    return
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)) and n is not method:
                    return
                if held and isinstance(n, ast.Call):
                    callee = _self_attr(n.func)
                    if callee and callee in method_acquires:
                        for lk in method_acquires[callee]:
                            for h in held:
                                if h != lk:
                                    yield (_qualify(node.name, h),
                                           _qualify(node.name, lk),
                                           mod.path, n.lineno)
                for child in ast.iter_child_nodes(n):
                    yield from visit(child, held)

            for stmt in method.body:
                yield from visit(stmt, ())


def _package_edges(index: PackageIndex) -> list[tuple]:
    # the acquisition graph is package-wide; computed once per run and
    # cached on the index object itself (per-module checks reuse it)
    cached = getattr(index, "_concurrency_edges", None)
    if cached is not None:
        return cached
    edges: list[tuple] = []
    for mod in index.modules:
        edges.extend(_nesting_edges(mod))
        edges.extend(_declared_order_edges(mod))
    index._concurrency_edges = edges  # type: ignore[attr-defined]
    return edges


def _reaches(adj: dict[str, set[str]], src: str, dst: str) -> bool:
    stack, seen = [src], set()
    while stack:
        cur = stack.pop()
        if cur == dst:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(adj.get(cur, ()))
    return False


@register(
    "PD303", "lock-order-inversion",
    "cycle in the statically-derived lock acquisition graph (with-"
    "nesting, intra-class call-through, and `# lock-order:` "
    "declarations)",
)
def check_lock_order_inversion(mod: ModuleInfo,
                               index: PackageIndex) -> Iterator[Finding]:
    edges = _package_edges(index)
    adj: dict[str, set[str]] = {}
    for a, b, _path, _line in edges:
        adj.setdefault(a, set()).add(b)
    reported: set[tuple[str, str, int]] = set()
    for a, b, path, lineno in edges:
        if path != mod.path:
            continue
        key = (a, b, lineno)
        if key in reported:
            continue
        # the edge a->b closes a cycle iff b already reaches a
        without = {k: set(v) for k, v in adj.items()}
        without.get(a, set()).discard(b)
        if _reaches(without, b, a):
            reported.add(key)
            anchor = ast.Constant(value=None)
            anchor.lineno, anchor.col_offset = lineno, 0
            yield mod.finding(
                "PD303", anchor,
                f"lock-order inversion: `{a}` -> `{b}` here, but the "
                f"acquisition graph also orders `{b}` before `{a}` "
                "(deadlock when both paths run concurrently)",
            )


# ---------------------------------------------------------------------------
# PD304 raw-acquire-release


@register(
    "PD304", "raw-acquire-release",
    "lock used via .acquire()/.release() instead of a with statement "
    "(an exception between the pair leaks the lock); non-blocking/"
    "timeout acquires are exempt",
)
def check_raw_acquire_release(mod: ModuleInfo,
                              index: PackageIndex) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cls = _class_locks(mod, node)
        if not cls.locks:
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr != "acquire":
                continue
            attr = _self_attr(func.value)
            if attr is None or attr not in cls.locks:
                continue
            if sub.args or sub.keywords:
                continue  # try-acquire / timeout: with cannot express
            yield mod.finding(
                "PD304", sub,
                f"raw `self.{attr}.acquire()` (pair can leak on an "
                "exception; use `with self." + attr + ":`)",
            )


# ---------------------------------------------------------------------------
# PD305 unguarded-module-global

_MUTABLE_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                  "OrderedDict", "Counter"}


def _module_globals(mod: ModuleInfo) -> dict[str, int]:
    """Mutable module-scope names -> definition line."""
    out: dict[str, int] = {}
    for stmt in mod.tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        value = stmt.value
        mutable = isinstance(value, (ast.Dict, ast.List, ast.Set))
        if isinstance(value, ast.Call):
            resolved = mod.resolve(value.func) or ""
            mutable = resolved.rsplit(".", 1)[-1] in _MUTABLE_CTORS
        if not mutable:
            continue
        for target in stmt.targets:
            if isinstance(target, ast.Name):
                out[target.id] = stmt.lineno
    return out


def _thread_target_functions(mod: ModuleInfo) -> set[str]:
    """Names of module functions (or methods) used as Thread targets."""
    out: set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = mod.resolve(node.func) or ""
        if resolved.rsplit(".", 1)[-1] != "Thread":
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            if isinstance(kw.value, ast.Name):
                out.add(kw.value.id)
            elif isinstance(kw.value, ast.Attribute):
                out.add(kw.value.attr)
    return out


@register(
    "PD305", "unguarded-module-global",
    "mutable module-level global written from a thread-target function "
    "without a `with <lock>:` guard",
)
def check_unguarded_module_global(mod: ModuleInfo,
                                  index: PackageIndex) -> Iterator[Finding]:
    globals_ = _module_globals(mod)
    if not globals_:
        return
    targets = _thread_target_functions(mod)
    if not targets:
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in targets:
            continue

        def visit(n: ast.AST, guarded: bool):
            if isinstance(n, ast.With):
                for child in n.body:
                    yield from visit(child, True)
                return
            hit = None
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                tgts = (n.targets if isinstance(n, ast.Assign)
                        else [n.target])
                for t in tgts:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Name) \
                            and base.id in globals_:
                        hit = base.id
            elif isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in _MUTATOR_METHODS \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id in globals_:
                hit = n.func.value.id
            if hit is not None and not guarded:
                yield mod.finding(
                    "PD305", n,
                    f"module global `{hit}` is mutated from thread "
                    f"target `{node.name}` with no lock held",
                )
            for child in ast.iter_child_nodes(n):
                yield from visit(child, guarded)

        for stmt in node.body:
            yield from visit(stmt, False)
