"""Jaxpr-level semantic analysis (the ``pdrnn-lint --deep`` pass).

The AST rules (PD1xx) can only see what the source text says; the bug
classes that cost real debugging time on hardware - unreduced
gradients, collectives over axes the mesh does not carry, silent f32
upcasts of bf16 activations, donation that XLA quietly drops - only
exist after tracing.  This pass traces every registered trainer entry
point (:mod:`.trace_registry`) with abstract inputs on CPU
(``jax.make_jaxpr``; no data, no compile, no TPU) and walks the closed
jaxpr:

- **PD200 trace-failure** - a registered entry no longer builds or
  traces.  Not a style issue: the entry IS the contract that the step
  stays traceable with the declared specs.
- **PD201 unreduced-gradient** - a train step whose updated-params
  outputs have no ``psum``/``pmean`` over the declared data axis on
  their backward slice (every shard applies its own local gradient:
  replicas silently diverge).  GSPMD-style entries (``gspmd=True``)
  must instead carry sharding annotations mentioning the data axis.
- **PD202 collective-axis-mismatch** - a collective over an axis name
  absent from the mesh the program was traced under (ground truth for
  the AST-level PD101).
- **PD203 dtype-promotion-leak** - bf16/f16 values flowing through
  ``convert_element_type`` to f32 outside an allowlisted accumulation
  (suppress intentional sites with ``# noqa: PD203`` and a comment
  stating the contract).
- **PD204 dead-computation** - DCE-removable equation clusters above a
  size threshold (traced-but-unused work: wasted compile time, and
  usually a forgotten output).
- **PD205 donation-mismatch** - a donated input buffer with no
  alias-compatible output (XLA drops the donation silently; the caller
  still treats the buffer as consumed) or donated but never read.

Findings anchor to the real source line of the offending equation via
jaxpr source provenance when available, so ``# noqa: PD2xx`` and the
shared baseline/fingerprint machinery apply exactly as for PD1xx.

This module imports jax lazily (inside functions), so rule listing and
CLI construction never pay the jax import.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterator

from pytorch_distributed_rnn_tpu.lint.core import Finding
from pytorch_distributed_rnn_tpu.lint.trace_registry import (
    TraceEntry,
    cpu_trace_session,
    load_entries,
)

# ---------------------------------------------------------------------------
# Deep-rule registry (mirrors lint.core's AST registry; separate because
# the check signature differs: rules see a traced entry, not a module)

_DEEP_REGISTRY: dict[str, "DeepRule"] = {}

DeepRuleFn = Callable[["TracedEntry"], Iterator[Finding]]


@dataclass(frozen=True)
class DeepRule:
    code: str
    name: str
    description: str
    check: DeepRuleFn


def register_deep(code: str, name: str, description: str):
    def deco(fn: DeepRuleFn) -> DeepRuleFn:
        if code in _DEEP_REGISTRY:
            raise ValueError(f"duplicate deep lint rule {code}")
        _DEEP_REGISTRY[code] = DeepRule(code=code, name=name,
                                        description=description, check=fn)
        return fn

    return deco


def deep_rules() -> dict[str, DeepRule]:
    return dict(_DEEP_REGISTRY)


# ---------------------------------------------------------------------------
# Traced entry: a registry entry + its closed jaxpr + lookup helpers

# dead-output elements at ONE source site that constitute a PD204
# finding.  Raw eqn counts are noise: autodiff leaves handfuls of
# scalar-sized residual guards (softmax jvp etc.) that XLA removes for
# free; a forgotten computation shows up as a *large* dead cluster
# anchored by compute-heavy primitives.
DEAD_ELEMS_THRESHOLD = 1024

# a dead cluster only counts when it contains real compute - autodiff
# residual guards are all cheap elementwise ops.  Containers (pjit,
# custom_*_call, scan) are not compute themselves; their bodies are
# inspected recursively.
_EXPENSIVE_PRIMS = {
    "dot_general", "conv_general_dilated", "sort", "top_k", "cumsum",
    "reduce_window", "gather", "scatter", "scatter-add", "fft",
}


def _has_real_compute(eqn) -> bool:
    if eqn.primitive.name in _EXPENSIVE_PRIMS:
        return True
    return any(
        _has_real_compute(inner)
        for sub in _subjaxprs(eqn)
        for inner in sub.eqns
    )

# reduce_scatter (jax.lax.psum_scatter's primitive) reduces like psum -
# its output is a slice of the sum - so a step whose gradients flow
# through it IS synchronized (PD201)
_REDUCING_COLLECTIVES = {"psum", "pmin", "pmax", "reduce_scatter"}
# primitive -> params key carrying the axis name(s)
_AXIS_PARAM = {
    "psum": "axes", "pmin": "axes", "pmax": "axes",
    "ppermute": "axis_name", "all_gather": "axis_name",
    "all_to_all": "axis_name", "reduce_scatter": "axis_name",
    "axis_index": "axis_name",
}


def _axes_of(eqn) -> tuple:
    value = eqn.params.get(_AXIS_PARAM[eqn.primitive.name])
    if value is None:
        return ()
    if isinstance(value, (tuple, list)):
        return tuple(value)
    return (value,)


def _as_jaxpr(obj):
    """Normalize Jaxpr/ClosedJaxpr to the open Jaxpr."""
    return getattr(obj, "jaxpr", obj)


def _subjaxprs(eqn) -> list:
    """Sub-jaxprs held by this equation's params (pjit/shard_map/scan/
    while/cond/remat/custom_* bodies)."""
    found = []
    for value in eqn.params.values():
        items = value if isinstance(value, (tuple, list)) else (value,)
        for item in items:
            inner = _as_jaxpr(item)
            if hasattr(inner, "eqns") and hasattr(inner, "outvars"):
                found.append(inner)
    return found


@dataclass
class TracedEntry:
    entry: TraceEntry
    closed: object  # jax ClosedJaxpr
    out_shape: object  # pytree of ShapeDtypeStruct (make_jaxpr return_shape)
    root: Path
    _sources: dict = field(default_factory=dict)

    # -- output bookkeeping --------------------------------------------------

    def flat_out_positions(self, element: int) -> list[int]:
        """Flat outvar positions belonging to top-level output
        ``element`` (the step contract returns a tuple; element 0 is the
        updated params pytree)."""
        import jax

        out = self.out_shape
        if not isinstance(out, (tuple, list)) or element >= len(out):
            return list(range(len(self.closed.jaxpr.outvars)))
        offset = 0
        for i, part in enumerate(out):
            n = len(jax.tree_util.tree_leaves(part))
            if i == element:
                return list(range(offset, offset + n))
            offset += n
        return []

    def flat_arg_slices(self) -> list[tuple[int, int]]:
        """(start, stop) flat invar range per top-level argument - the
        donation declaration is per-argument, the jaxpr is flat."""
        import jax

        slices = []
        offset = 0
        for spec in self.entry_args:
            n = len(jax.tree_util.tree_leaves(spec))
            slices.append((offset, offset + n))
            offset += n
        return slices

    entry_args: tuple = ()

    # -- source provenance ---------------------------------------------------

    def source_of(self, eqn) -> tuple[str, int]:
        """(repo-relative path, line) of the best user frame for this
        equation; falls back to the entry's declared file when the
        provenance API is unavailable or every frame is library code."""
        key = id(eqn)
        if key in self._sources:
            return self._sources[key]
        path, line = self.entry.path, 1
        try:  # private API: degrade to entry-anchored findings if moved
            from jax._src import source_info_util

            for frame in source_info_util.user_frames(eqn.source_info):
                frame_path = Path(frame.file_name)
                try:
                    rel = frame_path.resolve().relative_to(
                        self.root.resolve()).as_posix()
                except (ValueError, OSError):
                    continue
                path, line = rel, int(frame.start_line)
                break
        except Exception:
            pass
        self._sources[key] = (path, line)
        return path, line

    def finding(self, rule: str, message: str, *,
                eqn=None, path: str | None = None,
                line: int = 1) -> Finding:
        if eqn is not None:
            path, line = self.source_of(eqn)
        path = path or self.entry.path
        return Finding(
            rule=rule, path=path, line=line, col=0, message=message,
            symbol=self.entry.name, snippet=_line_text(self.root, path, line),
        )


def _line_text(root: Path, path: str, line: int) -> str:
    try:
        lines = (root / path).read_text().splitlines()
        if 1 <= line <= len(lines):
            return lines[line - 1].strip()
    except OSError:
        pass
    return ""


# ---------------------------------------------------------------------------
# jaxpr walking / slicing

def walk_eqns(jaxpr, bound_axes: frozenset = frozenset()):
    """Yield ``(eqn, bound_axes)`` over the whole program.  ``shard_map``
    equations bind their traced mesh's axis names for everything below -
    the ground truth PD202 compares collective axes against."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        sub_bound = bound_axes
        if name == "shard_map":
            mesh = eqn.params.get("mesh")
            if mesh is not None:
                sub_bound = bound_axes | frozenset(mesh.axis_names)
        yield eqn, bound_axes
        for sub in _subjaxprs(eqn):
            yield from walk_eqns(sub, sub_bound)


class _Slicer:
    """Backward slice over a (possibly nested) jaxpr.

    Precise 1:1 input/output mapping is used for call-like equations
    whose single sub-jaxpr mirrors the equation signature (pjit,
    shard_map, remat, custom_vjp/jvp bodies); anything else (scan,
    while, cond) is handled conservatively - the whole sub-program
    counts as on-slice once the equation is needed.  Conservative
    over-approximation is the safe direction for PD201: it can only
    make a reduction easier to find, never invent a missing one.
    """

    def slice(self, jaxpr, out_positions) -> tuple[list, list[int]]:
        """(eqns on the slice, needed input positions)."""
        var_cls = _var_class(jaxpr)
        needed = set()
        for pos in out_positions:
            if pos < len(jaxpr.outvars):
                var = jaxpr.outvars[pos]
                if isinstance(var, var_cls):
                    needed.add(var)
        on_slice: list = []
        for eqn in reversed(jaxpr.eqns):
            if not any(v in needed for v in eqn.outvars):
                continue
            on_slice.append(eqn)
            subs = _subjaxprs(eqn)
            if (len(subs) == 1
                    and len(subs[0].invars) == len(eqn.invars)
                    and len(subs[0].outvars) == len(eqn.outvars)):
                sub = subs[0]
                sub_out = [i for i, v in enumerate(eqn.outvars)
                           if v in needed]
                sub_eqns, sub_in = self.slice(sub, sub_out)
                on_slice.extend(sub_eqns)
                for i in sub_in:
                    var = eqn.invars[i]
                    if isinstance(var, var_cls):
                        needed.add(var)
            else:
                for sub in subs:
                    sub_eqns, _ = self.slice(
                        sub, list(range(len(sub.outvars))))
                    on_slice.extend(sub_eqns)
                for var in eqn.invars:
                    if isinstance(var, var_cls):
                        needed.add(var)
        in_positions = [i for i, v in enumerate(jaxpr.invars) if v in needed]
        return on_slice, in_positions


def _var_class(jaxpr):
    from jax.core import Var

    return Var


def backward_slice(jaxpr, out_positions) -> list:
    return _Slicer().slice(jaxpr, out_positions)[0]


def _dead_eqns(jaxpr) -> list:
    """Equations DCE would remove, per jaxpr, recursively (each nested
    body is judged against its own outputs; effectful eqns are live)."""
    var_cls = _var_class(jaxpr)
    live = {v for v in jaxpr.outvars if isinstance(v, var_cls)}
    dead, kept = [], []
    for eqn in reversed(jaxpr.eqns):
        if any(v in live for v in eqn.outvars) or eqn.effects:
            kept.append(eqn)
            for var in eqn.invars:
                if isinstance(var, var_cls):
                    live.add(var)
        else:
            dead.append(eqn)
    for eqn in kept:
        for sub in _subjaxprs(eqn):
            dead.extend(_dead_eqns(sub))
    return dead


# ---------------------------------------------------------------------------
# PD201 unreduced-gradient


@register_deep(
    "PD201", "unreduced-gradient",
    "train step whose params-update path carries no psum/pmean over the "
    "declared data axis (replicas silently diverge)",
)
def check_unreduced_gradient(traced: TracedEntry) -> Iterator[Finding]:
    entry = traced.entry
    if entry.kind != "train_step" or entry.data_axis is None:
        return
    if entry.gspmd:
        yield from _check_gspmd_reduction(traced)
        return
    on_slice = backward_slice(
        traced.closed.jaxpr, traced.flat_out_positions(0))
    for eqn in on_slice:
        if (eqn.primitive.name in _REDUCING_COLLECTIVES
                and entry.data_axis in _axes_of(eqn)):
            return
    yield traced.finding(
        "PD201",
        f"no psum/pmean over data axis \"{entry.data_axis}\" on the "
        f"updated-params path of `{entry.name}`: each shard applies its "
        "own local gradient",
    )


def _check_gspmd_reduction(traced: TracedEntry) -> Iterator[Finding]:
    """GSPMD-style steps (ZeRO/FSDP) carry no explicit collective - the
    partitioner derives the reduce-scatter from sharding annotations.
    The contract to verify is that those annotations exist and mention
    the data axis (strip them and the step silently trains on local
    gradients when run per-shard)."""
    entry = traced.entry
    axis = entry.data_axis
    for eqn, _ in walk_eqns(traced.closed.jaxpr):
        if eqn.primitive.name == "sharding_constraint":
            sharding = eqn.params.get("sharding")
            if _sharding_mentions(sharding, axis):
                return
        elif eqn.primitive.name == "pjit":
            shardings = tuple(eqn.params.get("in_shardings") or ()) + tuple(
                eqn.params.get("out_shardings") or ())
            if any(_sharding_mentions(s, axis) for s in shardings):
                return
    yield traced.finding(
        "PD201",
        f"gspmd step `{entry.name}` carries no sharding annotation "
        f"mentioning data axis \"{axis}\": the partitioner has nothing "
        "to derive the gradient reduction from",
    )


def _sharding_mentions(sharding, axis: str) -> bool:
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return False
    for part in spec:
        parts = part if isinstance(part, (tuple, list)) else (part,)
        if axis in parts:
            return True
    return False


# ---------------------------------------------------------------------------
# PD202 collective-axis-mismatch


@register_deep(
    "PD202", "collective-axis-mismatch",
    "collective over an axis name absent from the mesh the program was "
    "traced under (ground truth for AST-level PD101)",
)
def check_collective_axis(traced: TracedEntry) -> Iterator[Finding]:
    declared = frozenset(traced.entry.mesh_axes)
    for eqn, bound in walk_eqns(traced.closed.jaxpr, declared):
        if eqn.primitive.name not in _AXIS_PARAM:
            continue
        for axis in _axes_of(eqn):
            if isinstance(axis, str) and axis not in bound:
                shown = ", ".join(sorted(bound)) or "<none>"
                yield traced.finding(
                    "PD202",
                    f'{eqn.primitive.name} over axis "{axis}" not bound '
                    f"by the traced mesh (axes: {shown})",
                    eqn=eqn,
                )


_UNBOUND_AXIS_RE = re.compile(
    r"unbound axis name:?\s*([A-Za-z_][A-Za-z0-9_]*)")


def trace_error_finding(traced_stub: TracedEntry,
                        error: Exception) -> Finding:
    """Classify a build/trace failure: an unbound-axis NameError is the
    PD202 bug class caught at trace time (the collective names an axis
    the mesh does not carry); anything else is PD200."""
    message = f"{error.__class__.__name__}: {error}"
    m = _UNBOUND_AXIS_RE.search(str(error))
    if isinstance(error, NameError) and m:
        entry = traced_stub.entry
        shown = ", ".join(sorted(entry.mesh_axes)) or "<none>"
        return traced_stub.finding(
            "PD202",
            f'collective over axis "{m.group(1)}" absent from the traced '
            f"mesh (axes: {shown})",
        )
    return traced_stub.finding(
        "PD200", f"entry failed to build/trace: {message}")


# PD200 is registered for --list-rules/--select visibility; findings are
# emitted by the driver (a failed trace has no jaxpr to hand a rule)
@register_deep(
    "PD200", "trace-failure",
    "a registered entry point no longer builds or traces with its "
    "declared abstract specs",
)
def check_trace_failure(traced: TracedEntry) -> Iterator[Finding]:
    return iter(())


# ---------------------------------------------------------------------------
# PD203 dtype-promotion-leak


@register_deep(
    "PD203", "dtype-promotion-leak",
    "bf16/f16 values upcast to f32 via convert_element_type outside an "
    "allowlisted accumulation (# noqa: PD203 with the contract)",
)
def check_dtype_promotion(traced: TracedEntry) -> Iterator[Finding]:
    import jax.numpy as jnp
    import numpy as np

    low = (jnp.bfloat16, np.float16)
    seen: set[tuple[str, int]] = set()
    for eqn, _ in walk_eqns(traced.closed.jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        aval = getattr(eqn.invars[0], "aval", None)
        src = getattr(aval, "dtype", None)
        if src is None or not any(src == np.dtype(d) for d in low):
            continue
        if np.dtype(eqn.params.get("new_dtype")) != np.dtype(np.float32):
            continue
        where = traced.source_of(eqn)
        if where in seen:  # fwd + transposed bwd share the source line
            continue
        seen.add(where)
        yield traced.finding(
            "PD203",
            f"{np.dtype(src).name} value upcast to f32: accumulation "
            "dtype leak (allowlist intentional sites with # noqa: PD203 "
            "and the contract)",
            eqn=eqn,
        )


# ---------------------------------------------------------------------------
# PD204 dead-computation


@register_deep(
    "PD204", "dead-computation",
    "DCE-removable equation clusters with real compute (dot/scan/...) "
    f"producing >= {DEAD_ELEMS_THRESHOLD} dead output elements at one "
    "source site: traced-but-unused work, usually a forgotten output",
)
def check_dead_computation(traced: TracedEntry) -> Iterator[Finding]:
    import numpy as np

    by_site: dict[tuple[str, int], list] = {}
    for eqn in _dead_eqns(traced.closed.jaxpr):
        by_site.setdefault(traced.source_of(eqn), []).append(eqn)
    for (path, line), eqns in sorted(by_site.items()):
        if not any(_has_real_compute(e) for e in eqns):
            continue  # autodiff residual guards, free for XLA to drop
        elems = 0
        for eqn in eqns:
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                if aval is not None and hasattr(aval, "shape"):
                    elems += int(np.prod(aval.shape, dtype=np.int64))
        if elems < DEAD_ELEMS_THRESHOLD:
            continue
        yield traced.finding(
            "PD204",
            f"{len(eqns)} DCE-removable equations ({elems} dead output "
            f"elements) in `{traced.entry.name}`: computed but never "
            "used",
            path=path, line=line,
        )


# ---------------------------------------------------------------------------
# PD205 donation-mismatch


@register_deep(
    "PD205", "donation-mismatch",
    "donated input buffer with no alias-compatible output (XLA drops "
    "the donation; the caller still treats the buffer as consumed) or "
    "donated but never read",
)
def check_donation(traced: TracedEntry) -> Iterator[Finding]:
    entry = traced.entry
    if not entry.donate:
        return
    jaxpr = traced.closed.jaxpr
    slices = traced.flat_arg_slices()
    var_cls = _var_class(jaxpr)

    used: set = set()
    for eqn, _ in walk_eqns(jaxpr):
        for var in eqn.invars:
            if isinstance(var, var_cls):
                used.add(var)
    outvars = set(v for v in jaxpr.outvars if isinstance(v, var_cls))

    # alias feasibility is by (shape, dtype) multiset: each donated
    # buffer needs SOME output of identical layout to take it over
    supply: dict = {}
    for var in jaxpr.outvars:
        aval = getattr(var, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            key = (tuple(aval.shape), str(aval.dtype))
            supply[key] = supply.get(key, 0) + 1

    for arg_index in entry.donate:
        if arg_index >= len(slices):
            continue
        start, stop = slices[arg_index]
        unmatched = 0
        unread = 0
        for var in jaxpr.invars[start:stop]:
            aval = getattr(var, "aval", None)
            if aval is None or not hasattr(aval, "shape"):
                continue
            key = (tuple(aval.shape), str(aval.dtype))
            if supply.get(key, 0) > 0:
                supply[key] -= 1
            else:
                unmatched += 1
            if var not in used and var not in outvars:
                unread += 1
        if unmatched:
            yield traced.finding(
                "PD205",
                f"argument {arg_index} of `{entry.name}` is donated but "
                f"{unmatched} of its buffers match no output shape/dtype: "
                "XLA drops the donation while the caller's buffer is "
                "already forfeit",
            )
        elif unread:
            yield traced.finding(
                "PD205",
                f"argument {arg_index} of `{entry.name}` is donated but "
                f"{unread} of its buffers are never read by the program",
            )


# ---------------------------------------------------------------------------
# Driver


def _collective_traffic(traced: TracedEntry) -> dict:
    """Per-entry collective counts/bytes, reusing the evaluation
    report's jaxpr walker (``evaluation/collectives.py``) on the
    already-traced step."""
    from pytorch_distributed_rnn_tpu.evaluation.collectives import (
        closed_jaxpr_collective_stats,
    )

    return closed_jaxpr_collective_stats(traced.closed)


def trace_entry(entry: TraceEntry, root: Path) -> TracedEntry:
    """Build and trace one entry (abstract inputs, CPU, no compile)."""
    import jax

    fn, args = entry.build()
    closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*args)
    traced = TracedEntry(entry=entry, closed=closed, out_shape=out_shape,
                         root=root)
    traced.entry_args = tuple(args)
    return traced


def run_deep(
    *,
    select=None,
    ignore=None,
    root: str | Path | None = None,
    entries=None,
    noqa: Callable[[str, int], set] | None = None,
) -> tuple[list[Finding], dict]:
    """Trace every registered entry and run the active PD2xx rules.

    Returns ``(findings, stats)`` where ``stats`` records what was
    traced/skipped (the CI artifact makes regressions diffable).
    ``noqa(path, line) -> {codes}`` lets the caller suppress findings
    with the same inline-directive machinery the AST layer uses.

    CPU-only contract: if this pass is what first initializes jax, the
    process backend becomes (and stays) CPU - see
    :func:`~pytorch_distributed_rnn_tpu.lint.trace_registry.
    cpu_trace_session` for the library-caller implications.
    """
    root = Path(root) if root is not None else Path.cwd()
    rules = deep_rules()
    active = set(rules)
    if select:
        active &= set(select)
    if ignore:
        active -= set(ignore)
    if not active:
        # every deep rule filtered out: tracing would be pure cost
        return [], {"entries": [], "traced": 0, "skipped": [],
                    "families": [], "devices": 0}

    findings: list[Finding] = []
    seen: set[tuple] = set()

    def emit(finding: Finding):
        if finding.rule not in active:
            return
        if noqa is not None and finding.rule in noqa(
                finding.path, finding.line):
            return
        key = (finding.rule, finding.path, finding.line, finding.message)
        if key in seen:  # entries sharing a loss fn trace the same eqns
            return
        seen.add(key)
        findings.append(finding)

    with cpu_trace_session() as available:
        if entries is None:
            entries = load_entries()
        stats = {
            "entries": [],
            "traced": 0,
            "skipped": [],
            "families": sorted({e.family for e in entries}),
            "devices": available,
        }
        for entry in entries:
            if entry.devices_needed > available:
                stats["skipped"].append({
                    "entry": entry.name,
                    "reason": f"needs {entry.devices_needed} devices, "
                              f"have {available}",
                })
                continue
            stub = TracedEntry(entry=entry, closed=None, out_shape=None,
                               root=root)
            try:
                traced = trace_entry(entry, root)
            except Exception as e:  # noqa: BLE001 - failures are findings
                emit(trace_error_finding(stub, e))
                continue
            stats["traced"] += 1
            stats["entries"].append({
                "entry": entry.name,
                "family": entry.family,
                "eqns": sum(1 for _ in walk_eqns(traced.closed.jaxpr)),
                # per-step collective traffic (scan trip counts
                # multiplied in) - the communication side of the scaling
                # model, made diffable across PRs via the CI artifact
                "collectives": _collective_traffic(traced),
            })
            for code in sorted(active):
                for finding in rules[code].check(traced):
                    emit(finding)

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, stats
