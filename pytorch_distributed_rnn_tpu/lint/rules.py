"""The built-in rule plugins (PD101-PD105).

Each rule is a ``(module, index) -> Iterator[Finding]`` function added
via :func:`pytorch_distributed_rnn_tpu.lint.core.register`; new rules
only need this module (or any importer) to call ``register``.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from pytorch_distributed_rnn_tpu.lint.core import (
    Finding,
    ModuleInfo,
    PackageIndex,
    register,
)

# ---------------------------------------------------------------------------
# shared call-resolution helpers


def _tail(resolved: str) -> str:
    return resolved.rsplit(".", 1)[-1]


def _is_jit(resolved: str | None) -> bool:
    return resolved is not None and (
        resolved == "jax.jit" or
        (resolved.startswith("jax.") and resolved.endswith(".jit"))
    )


def _is_shard_map(resolved: str | None) -> bool:
    return resolved is not None and _tail(resolved) == "shard_map"


def _is_partial(resolved: str | None) -> bool:
    return resolved is not None and _tail(resolved) == "partial"


def _jit_construction(mod: ModuleInfo, call: ast.Call) -> ast.Call | None:
    """The jit call itself for ``jax.jit(...)`` or
    ``partial(jax.jit, ...)`` forms, else None."""
    resolved = mod.resolve(call.func)
    if _is_jit(resolved):
        return call
    if _is_partial(resolved) and call.args:
        if _is_jit(mod.resolve(call.args[0])):
            return call
    return None


def _first_wrapped_param(mod: ModuleInfo, node: ast.AST) -> str | None:
    """First parameter name of the function a jit/shard_map call wraps:
    an inline lambda, a local def referenced by name, or a bound method
    referenced as ``self.name`` (methods are indexed by name too).
    ``self``/``cls`` leaders are skipped."""
    if isinstance(node, ast.Lambda):
        params = [a.arg for a in node.args.args]
    else:
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        fn = mod.functions.get(name) if name else None
        if fn is None:
            return None
        params = [a.arg for a in fn.args.args]
    while params and params[0] in ("self", "cls"):
        params = params[1:]
    return params[0] if params else None


# ---------------------------------------------------------------------------
# PD101 axis-consistency

# axis-name argument position per collective (jax.lax primitives and the
# package's pytree wrappers in parallel/collectives.py)
_AXIS_ARG_POS = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1,
    "all_gather": 1, "ppermute": 1, "psum_scatter": 1, "all_to_all": 1,
    "axis_index": 0, "axis_size": 0,
    "psum_tree": 1, "pmean_tree": 1, "allgather_tree": 1,
    "broadcast_from": 1,
}
_JAX_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "ppermute",
    "psum_scatter", "all_to_all", "axis_index", "axis_size",
}
_AXIS_KWARGS = ("axis_name", "axis")
# pandas-style string axes that are not mesh axes
_NON_MESH_AXIS_STRINGS = {"index", "columns", "rows"}


def _literal_axis_names(node: ast.AST | None) -> Iterator[tuple[ast.AST, str]]:
    if node is None:
        return
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node, node.value
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            yield from _literal_axis_names(elt)


def _collective_axis_arg(mod: ModuleInfo, call: ast.Call) -> ast.AST | None:
    resolved = mod.resolve(call.func)
    if resolved is None:
        return None
    tail = _tail(resolved)
    if tail not in _AXIS_ARG_POS:
        return None
    if tail in _JAX_COLLECTIVES and not (
            resolved.startswith("jax.") or resolved == tail):
        return None  # someone else's psum
    pos = _AXIS_ARG_POS[tail]
    if len(call.args) > pos:
        return call.args[pos]
    for kw in call.keywords:
        if kw.arg in _AXIS_KWARGS:
            return kw.value
    return None


@register(
    "PD101", "axis-consistency",
    "string-literal mesh-axis names must be declared by a known mesh/"
    "shard_map axis set",
)
def check_axis_consistency(mod: ModuleInfo,
                           index: PackageIndex) -> Iterator[Finding]:
    known = index.known_axes

    def check(node: ast.AST, name: str, context: str) -> Iterator[Finding]:
        if name in known:
            return
        shown = ", ".join(sorted(known)) or "<none>"
        yield mod.finding(
            "PD101", node,
            f'unknown mesh axis "{name}" in {context} '
            f"(declared axes: {shown})",
        )

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            resolved = mod.resolve(node.func) or ""
            # collectives: the axis argument
            axis_arg = _collective_axis_arg(mod, node)
            for lit, name in _literal_axis_names(axis_arg):
                yield from check(lit, name, f"{_tail(resolved)}()")
            # PartitionSpec literals: every string entry is an axis
            if _tail(resolved) == "PartitionSpec":
                for arg in node.args:
                    for lit, name in _literal_axis_names(arg):
                        yield from check(lit, name, "PartitionSpec")
            # axis-ish keywords on any call (axis="dp", tp_axis="tp",
            # stat_axes=("dp", "ep"), ...)
            if axis_arg is None:
                for kw in node.keywords:
                    if kw.arg and (kw.arg in _AXIS_KWARGS
                                   or kw.arg.endswith("_axis")
                                   or kw.arg.endswith("_axes")):
                        for lit, name in _literal_axis_names(kw.value):
                            # pandas-style string axes only exist on
                            # this generic-kwarg path, never as a
                            # collective/PartitionSpec argument
                            if name in _NON_MESH_AXIS_STRINGS:
                                continue
                            yield from check(lit, name, f"{kw.arg}=")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # axis-ish parameter defaults: def f(..., axis="dp")
            args = node.args
            pos = args.posonlyargs + args.args
            for arg, default in zip(pos[len(pos) - len(args.defaults):],
                                    args.defaults):
                if (arg.arg in _AXIS_KWARGS or arg.arg.endswith("_axis")
                        or arg.arg.endswith("_axes")):
                    for lit, name in _literal_axis_names(default):
                        yield from check(lit, name,
                                         f"default {arg.arg}=")
            for arg, default in zip(args.kwonlyargs, args.kw_defaults):
                if default is not None and (
                        arg.arg in _AXIS_KWARGS
                        or arg.arg.endswith("_axis")
                        or arg.arg.endswith("_axes")):
                    for lit, name in _literal_axis_names(default):
                        yield from check(lit, name,
                                         f"default {arg.arg}=")


# ---------------------------------------------------------------------------
# PD102 host-sync-in-jit

# calls through these control-flow primitives trace their function
# arguments (so host syncs inside those functions fire per-trace or,
# worse, per-step via callbacks that silently block dispatch)
_TRACING_CALL_TAILS = {"scan", "fori_loop", "while_loop", "cond", "switch",
                       "shard_map", "jit", "remat", "checkpoint", "vmap",
                       "grad", "value_and_grad", "pmap"}
_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}


def _traced_functions(mod: ModuleInfo) -> dict[str, ast.AST]:
    """Local defs that run under tracing: jit/shard_map decorated, or
    passed (by name) into jit/shard_map/lax control-flow calls."""
    traced: dict[str, ast.AST] = {}

    def mark(name: str | None):
        if name and name in mod.functions:
            traced[name] = mod.functions[name]

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                target = deco.func if isinstance(deco, ast.Call) else deco
                resolved = mod.resolve(target)
                if _is_jit(resolved) or _is_shard_map(resolved):
                    traced[node.name] = node
                elif isinstance(deco, ast.Call) and _is_partial(resolved):
                    if deco.args and (_is_jit(mod.resolve(deco.args[0]))
                                      or _is_shard_map(
                                          mod.resolve(deco.args[0]))):
                        traced[node.name] = node
        elif isinstance(node, ast.Call):
            resolved = mod.resolve(node.func)
            if resolved is None:
                continue
            if _tail(resolved) in _TRACING_CALL_TAILS and (
                    resolved.startswith("jax.")
                    or _is_shard_map(resolved)):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        mark(arg.id)
            elif _jit_construction(mod, node) is not None:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        mark(arg.id)
    return traced


def _is_host_sync(mod: ModuleInfo, call: ast.Call,
                  param_names: set[str]) -> str | None:
    """Why this call blocks (or breaks) tracing, or None."""
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr == "item":
        return ".item() forces a device->host transfer"
    resolved = mod.resolve(func)
    if resolved is not None:
        if resolved in ("print",):
            return "print() runs per-trace, not per-step (use jax.debug.print)"
        if resolved.startswith("time."):
            return "host time.* call traces to a constant"
        if (resolved.startswith("random.")
                or resolved.startswith("numpy.random.")):
            return ("host RNG traces to a constant "
                    "(use jax.random with a threaded key)")
        if resolved in ("numpy.array", "numpy.asarray"):
            return ("np.asarray/np.array on a traced value forces a "
                    "host sync (use jnp.asarray)")
        if resolved in ("float", "int", "bool") and len(call.args) == 1:
            arg = call.args[0]
            names = {n.id for n in ast.walk(arg)
                     if isinstance(n, ast.Name)}
            attrs = {n.attr for n in ast.walk(arg)
                     if isinstance(n, ast.Attribute)}
            if names & param_names and not attrs & _SHAPE_ATTRS:
                return (f"{resolved}() on a traced value forces a "
                        "host sync")
    return None


@register(
    "PD102", "host-sync-in-jit",
    "host-blocking calls (.item(), float/int on traced values, "
    "np.asarray, print, time.*, random.*) inside traced functions",
)
def check_host_sync_in_jit(mod: ModuleInfo,
                           index: PackageIndex) -> Iterator[Finding]:
    for name, fn in _traced_functions(mod).items():
        params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                why = _is_host_sync(mod, node, params)
                if why is not None:
                    yield mod.finding(
                        "PD102", node,
                        f"inside traced function `{name}`: {why}",
                    )


# ---------------------------------------------------------------------------
# PD103 missing-donation

_DONATABLE_FIRST_PARAMS = {
    "params", "param", "state", "opt_state", "train_state", "weights",
}
_DONATE_KWARGS = {"donate_argnums", "donate_argnames"}


def _has_donation(call: ast.Call) -> bool:
    return any(kw.arg in _DONATE_KWARGS for kw in call.keywords)


@register(
    "PD103", "missing-donation",
    "jax.jit over a params/opt-state step without "
    "donate_argnums/donate_argnames doubles peak memory",
)
def check_missing_donation(mod: ModuleInfo,
                           index: PackageIndex) -> Iterator[Finding]:
    seen: set[int] = set()
    for node in ast.walk(mod.tree):
        # decorator form: @jax.jit / @partial(jax.jit, ...) def step(params, ...)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = [a.arg for a in node.args.args]
            while params and params[0] in ("self", "cls"):
                params = params[1:]
            first = params[0] if params else None
            if first not in _DONATABLE_FIRST_PARAMS:
                continue
            for deco in node.decorator_list:
                target = deco.func if isinstance(deco, ast.Call) else deco
                resolved = mod.resolve(target)
                donated = isinstance(deco, ast.Call) and _has_donation(deco)
                if _is_jit(resolved) and not donated:
                    seen.add(id(deco))
                    yield mod.finding(
                        "PD103", node,
                        f"`@jit` step `{node.name}({first}, ...)` "
                        "updates state in place but donates no buffers",
                    )
                elif (isinstance(deco, ast.Call) and _is_partial(resolved)
                        and deco.args
                        and _is_jit(mod.resolve(deco.args[0]))
                        and not donated):
                    seen.add(id(deco))
                    yield mod.finding(
                        "PD103", node,
                        f"`@partial(jax.jit, ...)` step "
                        f"`{node.name}({first}, ...)` donates no buffers",
                    )
        elif isinstance(node, ast.Call) and id(node) not in seen:
            jit_call = _jit_construction(mod, node)
            if jit_call is None or _has_donation(jit_call):
                continue
            wrapped_args = (node.args[1:] if _is_partial(
                mod.resolve(node.func)) else node.args)
            if not wrapped_args:
                continue
            first = _first_wrapped_param(mod, wrapped_args[0])
            if first in _DONATABLE_FIRST_PARAMS:
                yield mod.finding(
                    "PD103", node,
                    f"jit site wraps a step whose first argument "
                    f"`{first}` is an updated pytree but donates no "
                    "buffers",
                )


# ---------------------------------------------------------------------------
# PD104 retrace-hazard


@register(
    "PD104", "retrace-hazard",
    "jit/shard_map constructed inside a loop body retraces and "
    "recompiles every iteration",
)
def check_retrace_hazard(mod: ModuleInfo,
                         index: PackageIndex) -> Iterator[Finding]:
    flagged: set[int] = set()
    for loop in ast.walk(mod.tree):
        if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
            continue
        for node in ast.walk(loop):
            if node is loop or not isinstance(node, ast.Call):
                continue
            if id(node) in flagged:
                continue
            resolved = mod.resolve(node.func)
            what = None
            if _jit_construction(mod, node) is not None:
                what = "jax.jit"
            elif _is_shard_map(resolved):
                what = "shard_map"
            if what is not None:
                flagged.add(id(node))
                yield mod.finding(
                    "PD104", node,
                    f"{what}(...) constructed inside a loop: the "
                    "wrapped callable is rebuilt per iteration, so "
                    "every call retraces (hoist the construction out "
                    "of the loop)",
                )


# ---------------------------------------------------------------------------
# PD105 stub/dead-code

_ABSTRACT_DECOS = {
    "abstractmethod", "abstractproperty", "abstractclassmethod",
    "abstractstaticmethod", "overload",
}


def _is_stub_body(body: list[ast.stmt]) -> bool:
    stmts = list(body)
    if (stmts and isinstance(stmts[0], ast.Expr)
            and isinstance(stmts[0].value, ast.Constant)
            and isinstance(stmts[0].value.value, str)):
        stmts = stmts[1:]  # docstring
    if not stmts:
        return True  # docstring-only body
    if len(stmts) != 1:
        return False
    stmt = stmts[0]
    if isinstance(stmt, ast.Pass):
        return True
    if (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis):
        return True
    if isinstance(stmt, ast.Raise) and stmt.exc is not None:
        exc = stmt.exc
        name = exc.func if isinstance(exc, ast.Call) else exc
        if isinstance(name, ast.Name) and name.id == "NotImplementedError":
            return True
        if (isinstance(name, ast.Attribute)
                and name.attr == "NotImplementedError"):
            return True
    return False


@register(
    "PD105", "stub-dead-code",
    "function bodies that are only pass/.../raise NotImplementedError "
    "(abstract methods and overloads excluded)",
)
def check_stub_dead_code(mod: ModuleInfo,
                         index: PackageIndex) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _is_stub_body(node.body):
            continue
        deco_tails = set()
        for deco in node.decorator_list:
            target = deco.func if isinstance(deco, ast.Call) else deco
            resolved = mod.resolve(target)
            if resolved:
                deco_tails.add(_tail(resolved))
        if deco_tails & _ABSTRACT_DECOS:
            continue
        # Protocol members are interface declarations, not stubs
        parent = mod.parents.get(node)
        if isinstance(parent, ast.ClassDef) and any(
                isinstance(b, (ast.Name, ast.Attribute))
                and _tail(mod.resolve(b) or "") == "Protocol"
                for b in parent.bases):
            continue
        yield mod.finding(
            "PD105", node,
            f"`{node.name}` has a stub body "
            "(pass/.../raise NotImplementedError)",
        )
