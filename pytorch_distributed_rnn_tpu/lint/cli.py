"""``pdrnn-lint`` command line.

::

    python -m pytorch_distributed_rnn_tpu.lint [paths...]
        [--deep] [--no-concurrency] [--no-lifecycle]
        [--format text|json|sarif]
        [--select PD101,PD201] [--ignore PD103] [--stats]
        [--baseline lint_baseline.json | --no-baseline]
        [--write-baseline | --prune-baseline] [--known-axes dp,tp]
        [--list-rules]

Four layers share one reporting path: the AST rules (PD1xx), the
concurrency lock-discipline rules (PD3xx, ``lint/concurrency.py``,
skippable with ``--no-concurrency``), and the wire-contract/
resource-lifecycle rules (PD4xx, ``lint/lifecycle.py``, skippable with
``--no-lifecycle``) always run; ``--deep`` adds the jaxpr-level rules
(PD2xx) by tracing every registered trainer entry point on CPU
(abstract inputs, no compile, no TPU - see
``lint/trace_registry.py``).  Baseline, ``# noqa``, select/ignore and
the JSON/SARIF schemas apply identically to all layers.

Exit status: 0 = clean (all findings baselined or none), 1 = new
findings, 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from pytorch_distributed_rnn_tpu.lint.baseline import (
    load_baseline,
    prune_baseline,
    write_baseline,
)
from pytorch_distributed_rnn_tpu.lint.concurrency import concurrency_rules
from pytorch_distributed_rnn_tpu.lint.core import all_rules, run_lint
from pytorch_distributed_rnn_tpu.lint.jaxpr_pass import deep_rules
from pytorch_distributed_rnn_tpu.lint.lifecycle import lifecycle_rules

_DEFAULT_BASELINE = "lint_baseline.json"


def _csv(value: str) -> list[str]:
    return [v.strip() for v in value.split(",") if v.strip()]


def _scanned_paths(paths, baseline_path: Path) -> set[str]:
    """Repo-relative posix paths of the files a run actually lints -
    the same path convention findings carry (relative to the
    baseline's directory)."""
    from pytorch_distributed_rnn_tpu.lint.core import collect_files

    root = baseline_path.resolve().parent
    out = set()
    for f in collect_files(paths):
        try:
            out.add(f.resolve().relative_to(root).as_posix())
        except ValueError:
            out.add(f.as_posix())
    return out


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pdrnn-lint",
        description="JAX-aware static analysis for "
                    "pytorch_distributed_rnn_tpu (AST rules PD101-PD105; "
                    "jaxpr rules PD200-PD205 with --deep)",
    )
    parser.add_argument(
        "paths", nargs="*", default=["pytorch_distributed_rnn_tpu"],
        help="files or directories to lint "
             "(default: the package directory)",
    )
    parser.add_argument(
        "--deep", action="store_true",
        help="also trace every registered trainer entry point and run "
             "the jaxpr-level PD2xx rules (CPU-only, no compile)")
    parser.add_argument(
        "--no-concurrency", action="store_true",
        help="skip the PD3xx lock-discipline rules (baseline "
             "write/prune then preserves PD3xx entries, exactly as "
             "PD2xx entries are preserved without --deep)")
    parser.add_argument(
        "--no-lifecycle", action="store_true",
        help="skip the PD4xx wire-contract/resource-lifecycle rules "
             "(baseline write/prune then preserves PD4xx entries, "
             "same semantics as --no-concurrency)")
    parser.add_argument(
        "--stats", action="store_true",
        help="append a per-rule count summary (new + baselined) to the "
             "text output - CI log readability")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text", dest="fmt")
    parser.add_argument("--select", type=_csv, default=None, metavar="RULES",
                        help="comma-separated rule codes to run exclusively")
    parser.add_argument("--ignore", type=_csv, default=None, metavar="RULES",
                        help="comma-separated rule codes to skip")
    parser.add_argument("--known-axes", type=_csv, default=[],
                        metavar="AXES",
                        help="extra mesh-axis names to treat as declared")
    parser.add_argument("--baseline", default=None, metavar="PATH",
                        help=f"baseline file (default: ./{_DEFAULT_BASELINE} "
                             "when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, baseline ignored")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept all current findings into the "
                             "baseline file and exit 0")
    parser.add_argument("--prune-baseline", action="store_true",
                        help="drop baseline entries matching no current "
                             "finding and exit 0 (PD2xx entries are "
                             "only pruned when --deep runs)")
    parser.add_argument("--list-rules", action="store_true")
    return parser


def _sarif_report(result) -> dict:
    """SARIF 2.1.0 document covering all four layers - the shape GitHub
    code scanning ingests, so lint findings annotate PR diffs.  Only
    NEW findings become results (baselined ones are accepted debt)."""
    descriptors = []
    for code, rule in sorted({**all_rules(), **deep_rules()}.items()):
        descriptors.append({
            "id": code,
            "name": rule.name,
            "shortDescription": {"text": rule.name},
            "fullDescription": {"text": rule.description},
            "defaultConfiguration": {"level": "warning"},
        })
    results = []
    for f in result.findings:
        results.append({
            "ruleId": f.rule,
            "level": "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": f.line,
                        "startColumn": f.col + 1,
                    },
                },
            }],
            "partialFingerprints": {
                "pdrnnLintFingerprint": f.to_dict()["fingerprint"],
            },
        })
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "pdrnn-lint",
                "informationUri":
                    "https://github.com/jkhlr/pytorch-distributed-rnn",
                "rules": descriptors,
            }},
            "results": results,
        }],
    }


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for code, rule in sorted({**all_rules(), **deep_rules()}.items()):
            layer = ("jaxpr" if code.startswith("PD2")
                     else "concurrency" if code.startswith("PD3")
                     else "lifecycle" if code.startswith("PD4")
                     else "ast")
            print(f"{code} [{layer}] {rule.name}: {rule.description}")
        return 0

    # a typo'd rule code must not turn the gate vacuously green
    known_codes = set(all_rules()) | set(deep_rules())
    unknown = set(args.select or ()) | set(args.ignore or ())
    unknown -= known_codes
    if unknown:
        print(f"pdrnn-lint: unknown rule code(s): "
              f"{', '.join(sorted(unknown))} "
              f"(known: {', '.join(sorted(known_codes))})",
              file=sys.stderr)
        return 2

    # selecting a jaxpr rule without the jaxpr layer would report
    # nothing and exit 0 - the same vacuously-green hazard as a typo
    deep_selected = set(args.select or ()) & set(deep_rules())
    if deep_selected and not args.deep:
        print(f"pdrnn-lint: --select {', '.join(sorted(deep_selected))} "
              "needs --deep (jaxpr rules only run when the deep pass "
              "traces the registry)", file=sys.stderr)
        return 2

    # same vacuously-green hazard for the concurrency layer
    conc_selected = set(args.select or ()) & set(concurrency_rules())
    if conc_selected and args.no_concurrency:
        print(f"pdrnn-lint: --select {', '.join(sorted(conc_selected))} "
              "conflicts with --no-concurrency (the PD3xx layer would "
              "not run)", file=sys.stderr)
        return 2

    # ... and for the lifecycle layer
    life_selected = set(args.select or ()) & set(lifecycle_rules())
    if life_selected and args.no_lifecycle:
        print(f"pdrnn-lint: --select {', '.join(sorted(life_selected))} "
              "conflicts with --no-lifecycle (the PD4xx layer would "
              "not run)", file=sys.stderr)
        return 2

    # a filtered run sees only a subset of findings; rewriting the
    # baseline from it would silently drop every other rule's entries
    if (args.write_baseline or args.prune_baseline) and (
            args.select or args.ignore):
        print("pdrnn-lint: --write-baseline/--prune-baseline must run "
              "unfiltered (drop --select/--ignore)", file=sys.stderr)
        return 2
    if args.write_baseline and args.prune_baseline:
        print("pdrnn-lint: --write-baseline and --prune-baseline are "
              "mutually exclusive", file=sys.stderr)
        return 2

    baseline_path = Path(args.baseline or _DEFAULT_BASELINE)
    baseline: dict[str, int] = {}
    if not args.no_baseline and not (args.write_baseline
                                     or args.prune_baseline):
        try:
            baseline = load_baseline(baseline_path)
        except ValueError as e:
            print(f"pdrnn-lint: {e}", file=sys.stderr)
            return 2

    try:
        result = run_lint(
            args.paths,
            select=args.select,
            ignore=args.ignore,
            known_axes=args.known_axes,
            baseline=baseline,
            # report paths relative to the baseline's directory (the
            # repo root), so fingerprints match no matter the cwd
            root=baseline_path.resolve().parent,
            deep=args.deep,
            concurrency=not args.no_concurrency,
            lifecycle=not args.no_lifecycle,
        )
    except FileNotFoundError as e:
        print(f"pdrnn-lint: {e}", file=sys.stderr)
        return 2

    if result.deep:
        for skip in result.deep.get("skipped", ()):
            print(f"pdrnn-lint: deep: skipped {skip['entry']} "
                  f"({skip['reason']})", file=sys.stderr)

    if args.write_baseline or args.prune_baseline:
        # preservation guards keep a narrowed run from deleting accepted
        # entries it could not have re-observed: entries for files
        # outside the linted paths, PD2xx entries when the jaxpr layer
        # never ran (no --deep), PD3xx entries when the concurrency
        # layer was skipped (--no-concurrency), and PD4xx entries when
        # the lifecycle layer was skipped (--no-lifecycle)
        keep_rules = () if args.deep else tuple(deep_rules())
        if args.no_concurrency:
            keep_rules = tuple(keep_rules) + tuple(concurrency_rules())
        if args.no_lifecycle:
            keep_rules = tuple(keep_rules) + tuple(lifecycle_rules())
        scanned = _scanned_paths(args.paths, baseline_path)

    if args.write_baseline:
        data = write_baseline(baseline_path, result.findings,
                              keep_rules=keep_rules, scanned=scanned)
        print(f"pdrnn-lint: wrote {len(data['findings'])} baseline "
              f"entries ({len(result.findings)} findings) to "
              f"{baseline_path}")
        return 0

    if args.prune_baseline:
        try:
            data, dropped = prune_baseline(baseline_path, result.findings,
                                           keep_rules=keep_rules,
                                           scanned=scanned)
        except ValueError as e:
            print(f"pdrnn-lint: {e}", file=sys.stderr)
            return 2
        print(f"pdrnn-lint: pruned {dropped} stale baseline "
              f"occurrence(s); {len(data['findings'])} entries remain "
              f"in {baseline_path}")
        return 0

    if args.fmt == "sarif":
        print(json.dumps(_sarif_report(result), indent=2))
    elif args.fmt == "json":
        report = {
            "version": 1,
            "files": result.files,
            "known_axes": sorted(result.known_axes),
            "counts": result.counts(),
            "baseline_suppressed": result.suppressed,
            "baseline_suppressed_counts": result.suppressed_counts,
            "findings": [f.to_dict() for f in result.findings],
        }
        if result.deep is not None:
            report["deep"] = result.deep
        print(json.dumps(report, indent=2))
    else:
        for f in result.findings:
            print(f.render())
        if args.stats:
            # one row per rule that produced anything this run, new and
            # baselined both - the per-rule view a CI log can grep
            rows = sorted(set(result.counts())
                          | set(result.suppressed_counts))
            print("rule    new  baselined")
            for code in rows:
                print(f"{code}  {result.counts().get(code, 0):>5}  "
                      f"{result.suppressed_counts.get(code, 0):>9}")
            if not rows:
                print("(no findings in any rule)")
        summary = (
            f"pdrnn-lint: {len(result.findings)} finding(s) in "
            f"{result.files} file(s)"
        )
        if result.deep is not None:
            summary += (
                f" (+{result.deep['traced']} entry points traced)"
            )
        if result.suppressed:
            summary += f" ({result.suppressed} baselined)"
        print(summary)

    return 1 if result.findings else 0


if __name__ == "__main__":  # pragma: no cover - exercised via __main__
    sys.exit(main())
