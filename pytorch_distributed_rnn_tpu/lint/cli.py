"""``pdrnn-lint`` command line.

::

    python -m pytorch_distributed_rnn_tpu.lint [paths...]
        [--format text|json] [--select PD101,PD105] [--ignore PD103]
        [--baseline lint_baseline.json | --no-baseline]
        [--write-baseline] [--known-axes dp,tp] [--list-rules]

Exit status: 0 = clean (all findings baselined or none), 1 = new
findings, 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from pytorch_distributed_rnn_tpu.lint.baseline import (
    load_baseline,
    write_baseline,
)
from pytorch_distributed_rnn_tpu.lint.core import all_rules, run_lint

_DEFAULT_BASELINE = "lint_baseline.json"


def _csv(value: str) -> list[str]:
    return [v.strip() for v in value.split(",") if v.strip()]


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pdrnn-lint",
        description="JAX-aware static analysis for "
                    "pytorch_distributed_rnn_tpu (rules PD101-PD105)",
    )
    parser.add_argument(
        "paths", nargs="*", default=["pytorch_distributed_rnn_tpu"],
        help="files or directories to lint "
             "(default: the package directory)",
    )
    parser.add_argument("--format", choices=("text", "json"),
                        default="text", dest="fmt")
    parser.add_argument("--select", type=_csv, default=None, metavar="RULES",
                        help="comma-separated rule codes to run exclusively")
    parser.add_argument("--ignore", type=_csv, default=None, metavar="RULES",
                        help="comma-separated rule codes to skip")
    parser.add_argument("--known-axes", type=_csv, default=[],
                        metavar="AXES",
                        help="extra mesh-axis names to treat as declared")
    parser.add_argument("--baseline", default=None, metavar="PATH",
                        help=f"baseline file (default: ./{_DEFAULT_BASELINE} "
                             "when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, baseline ignored")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept all current findings into the "
                             "baseline file and exit 0")
    parser.add_argument("--list-rules", action="store_true")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for code, rule in sorted(all_rules().items()):
            print(f"{code} {rule.name}: {rule.description}")
        return 0

    # a typo'd rule code must not turn the gate vacuously green
    known_codes = set(all_rules())
    unknown = set(args.select or ()) | set(args.ignore or ())
    unknown -= known_codes
    if unknown:
        print(f"pdrnn-lint: unknown rule code(s): "
              f"{', '.join(sorted(unknown))} "
              f"(known: {', '.join(sorted(known_codes))})",
              file=sys.stderr)
        return 2

    # a filtered run sees only a subset of findings; writing it out
    # would silently drop every other rule's accepted entries
    if args.write_baseline and (args.select or args.ignore):
        print("pdrnn-lint: --write-baseline must run unfiltered "
              "(drop --select/--ignore)", file=sys.stderr)
        return 2

    baseline_path = Path(args.baseline or _DEFAULT_BASELINE)
    baseline: dict[str, int] = {}
    if not args.no_baseline and not args.write_baseline:
        try:
            baseline = load_baseline(baseline_path)
        except ValueError as e:
            print(f"pdrnn-lint: {e}", file=sys.stderr)
            return 2

    try:
        result = run_lint(
            args.paths,
            select=args.select,
            ignore=args.ignore,
            known_axes=args.known_axes,
            baseline=baseline,
            # report paths relative to the baseline's directory (the
            # repo root), so fingerprints match no matter the cwd
            root=baseline_path.resolve().parent,
        )
    except FileNotFoundError as e:
        print(f"pdrnn-lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        data = write_baseline(baseline_path, result.findings)
        print(f"pdrnn-lint: wrote {len(data['findings'])} baseline "
              f"entries ({len(result.findings)} findings) to "
              f"{baseline_path}")
        return 0

    if args.fmt == "json":
        print(json.dumps({
            "version": 1,
            "files": result.files,
            "known_axes": sorted(result.known_axes),
            "counts": result.counts(),
            "baseline_suppressed": result.suppressed,
            "findings": [f.to_dict() for f in result.findings],
        }, indent=2))
    else:
        for f in result.findings:
            print(f.render())
        summary = (
            f"pdrnn-lint: {len(result.findings)} finding(s) in "
            f"{result.files} file(s)"
        )
        if result.suppressed:
            summary += f" ({result.suppressed} baselined)"
        print(summary)

    return 1 if result.findings else 0


if __name__ == "__main__":  # pragma: no cover - exercised via __main__
    sys.exit(main())
