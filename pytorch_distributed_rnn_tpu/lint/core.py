"""Linter core: module model, import-alias resolution, rule registry,
and the ``run_lint`` driver.

Everything is pure ``ast`` - the linter never imports the code it
checks, so it runs identically with or without jax/TPU runtimes
installed (and in CI before any heavyweight import).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterable, Iterator

# ---------------------------------------------------------------------------
# Findings


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str  # "PD101"
    path: str  # repo-relative posix path
    line: int  # 1-based
    col: int  # 0-based
    message: str
    symbol: str = ""  # enclosing function qualname, "" at module scope
    snippet: str = ""  # stripped source line (line-number-stable key)
    # extra lines a `# noqa:` directive may sit on for this finding: the
    # line a multi-line call/statement STARTS on, and the first decorator
    # line of a decorated def.  Not serialized; not part of the
    # fingerprint.
    anchors: tuple = ()

    def to_dict(self) -> dict:
        from pytorch_distributed_rnn_tpu.lint.baseline import fingerprint

        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "symbol": self.symbol,
            "message": self.message,
            "snippet": self.snippet,
            "fingerprint": fingerprint(self),
        }

    def render(self) -> str:
        where = f"{self.path}:{self.line}:{self.col + 1}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{where}: {self.rule}{sym} {self.message}"


# ---------------------------------------------------------------------------
# Per-module model

_NOQA_RE = re.compile(
    r"#\s*(?:noqa:|pdrnn-lint:\s*ignore\[)\s*([A-Z]{2}\d{3}(?:[,\s]+[A-Z]{2}\d{3})*)"
)


def noqa_codes(line_text: str) -> set[str]:
    """Rule codes suppressed by an inline directive on this source line."""
    m = _NOQA_RE.search(line_text)
    if not m:
        return set()
    return set(re.findall(r"[A-Z]{2}\d{3}", m.group(1)))


@dataclass
class ModuleInfo:
    """A parsed module plus the lookup tables every rule needs."""

    path: str  # repo-relative posix path
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)
    aliases: dict[str, str] = field(default_factory=dict)
    functions: dict[str, ast.FunctionDef] = field(default_factory=dict)
    parents: dict[ast.AST, ast.AST] = field(default_factory=dict)

    @classmethod
    def parse(cls, path: str, source: str) -> "ModuleInfo":
        tree = ast.parse(source, filename=path)
        info = cls(path=path, source=source, tree=tree,
                   lines=source.splitlines())
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                info.parents[child] = node
            if isinstance(node, ast.Import):
                for a in node.names:
                    info.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    info.aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # name -> def; later (nested) defs shadow earlier ones,
                # which is the right lookup for jit(local_fn) sites
                info.functions[node.name] = node  # type: ignore[assignment]
        return info

    def resolve(self, node: ast.AST) -> str | None:
        """Dotted path of a Name/Attribute chain with import aliases
        expanded: ``lax.psum`` -> ``jax.lax.psum`` when the module did
        ``from jax import lax``.  None for anything unresolvable."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def noqa_rules(self, lineno: int) -> set[str]:
        return noqa_codes(self.line_text(lineno))

    def enclosing_function(self, node: ast.AST) -> str:
        names: list[str] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(names))

    def noqa_anchors(self, node: ast.AST) -> tuple:
        """Lines (besides the node's own) where a suppressing ``noqa``
        directive is honored: the start line of the enclosing statement
        (a finding inside a parenthesized multi-line call anchors to a
        continuation line the directive cannot legally live on) and the
        first decorator line of a decorated def (PD103's decorator-form
        findings anchor to the ``def`` line, the directive belongs on
        the ``@jit`` span)."""
        lineno = getattr(node, "lineno", 1)
        anchors = []
        cur: ast.AST | None = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.parents.get(cur)
        if cur is not None and getattr(cur, "lineno", lineno) != lineno:
            anchors.append(cur.lineno)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.decorator_list:
            anchors.append(node.decorator_list[0].lineno)
        return tuple(anchors)

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        lineno = getattr(node, "lineno", 1)
        return Finding(
            rule=rule,
            path=self.path,
            line=lineno,
            col=getattr(node, "col_offset", 0),
            message=message,
            symbol=self.enclosing_function(node),
            snippet=self.line_text(lineno),
            anchors=self.noqa_anchors(node),
        )


# ---------------------------------------------------------------------------
# Package-wide context shared by the rules


@dataclass
class PackageIndex:
    modules: list[ModuleInfo]
    known_axes: set[str]


# ---------------------------------------------------------------------------
# Rule registry

RuleFn = Callable[[ModuleInfo, PackageIndex], Iterator[Finding]]

_REGISTRY: dict[str, "Rule"] = {}


@dataclass(frozen=True)
class Rule:
    code: str
    name: str
    description: str
    check: RuleFn


def register(code: str, name: str, description: str):
    """Decorator adding a rule function to the registry (the plugin
    surface: a rule is just a ``(module, index) -> findings`` callable)."""

    def deco(fn: RuleFn) -> RuleFn:
        if code in _REGISTRY:
            raise ValueError(f"duplicate lint rule {code}")
        _REGISTRY[code] = Rule(code=code, name=name,
                               description=description, check=fn)
        return fn

    return deco


def all_rules() -> dict[str, Rule]:
    # import for side effect: rule registration (PD1xx AST rules, the
    # PD3xx concurrency layer, and the PD4xx lifecycle layer; the PD2xx
    # jaxpr layer keeps its own registry in lint/jaxpr_pass.py because
    # its check signature differs)
    from pytorch_distributed_rnn_tpu.lint import concurrency  # noqa: F401
    from pytorch_distributed_rnn_tpu.lint import lifecycle  # noqa: F401
    from pytorch_distributed_rnn_tpu.lint import rules  # noqa: F401

    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Driver


def collect_files(paths: Iterable[str | Path]) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            # skip hidden/__pycache__ components BELOW the requested
            # root only - the root itself may live under a dotted
            # checkout path (~/.cache CI workspaces etc.)
            files.extend(
                f for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.relative_to(p).parts
                and not any(part.startswith(".")
                            for part in f.relative_to(p).parts)
            )
        elif p.suffix == ".py":
            files.append(p)
        else:
            raise FileNotFoundError(f"not a Python file or directory: {p}")
    return files


@dataclass
class LintResult:
    findings: list[Finding]  # new (non-baselined, non-noqa) findings
    suppressed: int  # baselined findings matched this run
    known_axes: set[str]
    files: int
    deep: dict | None = None  # jaxpr-pass stats when run with deep=True
    # per-rule count of baseline-suppressed findings (--stats)
    suppressed_counts: dict = field(default_factory=dict)

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))


def _rel(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def run_lint(
    paths: Iterable[str | Path],
    *,
    select: Iterable[str] | None = None,
    ignore: Iterable[str] | None = None,
    known_axes: Iterable[str] = (),
    baseline: dict[str, int] | None = None,
    root: str | Path | None = None,
    deep: bool = False,
    concurrency: bool = True,
    lifecycle: bool = True,
) -> LintResult:
    """Lint ``paths`` (files or directories) and return the result.

    ``baseline`` maps finding fingerprints to accepted occurrence
    counts (see :mod:`.baseline`); matched findings are suppressed.
    ``known_axes`` extends the mesh-axis registry scanned from the
    files themselves.  ``deep=True`` additionally traces every
    registered trainer entry point and runs the jaxpr-level PD2xx rules
    (:mod:`.jaxpr_pass`); deep findings ride the same noqa/baseline/
    select machinery.  ``concurrency=False`` skips the PD3xx
    lock-discipline layer (:mod:`.concurrency`), mirroring how the
    PD2xx layer is absent without ``deep`` - the CLI's baseline
    write/prune then preserves PD3xx entries instead of dropping them.
    ``lifecycle=False`` does the same for the PD4xx wire-contract/
    resource-lifecycle layer (:mod:`.lifecycle`).
    """
    from pytorch_distributed_rnn_tpu.lint.axes import collect_known_axes
    from pytorch_distributed_rnn_tpu.lint.baseline import apply_baseline

    root = Path(root) if root is not None else Path.cwd()
    files = collect_files(paths)
    modules: list[ModuleInfo] = []
    findings: list[Finding] = []
    for f in files:
        try:
            source = f.read_text()
            modules.append(ModuleInfo.parse(_rel(f, root), source))
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                rule="PD000", path=_rel(f, root),
                line=getattr(e, "lineno", 1) or 1, col=0,
                message=f"unparseable module: {e.__class__.__name__}: {e}",
            ))

    index = PackageIndex(
        modules=modules,
        known_axes=collect_known_axes(modules) | set(known_axes),
    )

    rules = all_rules()
    active = set(rules)
    if not concurrency:
        from pytorch_distributed_rnn_tpu.lint.concurrency import (
            concurrency_rules,
        )

        active -= set(concurrency_rules())
    if not lifecycle:
        from pytorch_distributed_rnn_tpu.lint.lifecycle import (
            lifecycle_rules,
        )

        active -= set(lifecycle_rules())
    if select:
        active &= set(select)
    if ignore:
        active -= set(ignore)

    for mod in modules:
        for code in sorted(active):
            for finding in rules[code].check(mod, index):
                lines = (finding.line,) + finding.anchors
                if any(finding.rule in mod.noqa_rules(ln)
                       for ln in lines):
                    continue
                findings.append(finding)

    deep_stats = None
    if deep:
        from pytorch_distributed_rnn_tpu.lint.jaxpr_pass import run_deep

        # the deep pass traces the WHOLE registry regardless of which
        # paths were linted, so its noqa lookup must resolve from the
        # finding's file - not from the happened-to-be-linted set
        by_path = {m.path: m for m in modules}
        line_cache: dict[str, list[str]] = {}

        def noqa(path: str, line: int) -> set[str]:
            mod = by_path.get(path)
            if mod is not None:
                return mod.noqa_rules(line)
            lines = line_cache.get(path)
            if lines is None:
                try:
                    lines = (Path(root) / path).read_text().splitlines()
                except OSError:
                    lines = []
                line_cache[path] = lines
            if 1 <= line <= len(lines):
                return noqa_codes(lines[line - 1])
            return set()

        deep_findings, deep_stats = run_deep(
            select=select, ignore=ignore, root=root, noqa=noqa,
        )
        findings.extend(deep_findings)

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    new, suppressed = apply_baseline(findings, baseline or {})
    # per-rule suppressed counts: the multiset difference between all
    # findings and the surviving ones (--stats renders this)
    suppressed_counts: dict[str, int] = {}
    survivor_ids = {id(f) for f in new}
    for f in findings:
        if id(f) not in survivor_ids:
            suppressed_counts[f.rule] = suppressed_counts.get(f.rule, 0) + 1
    return LintResult(findings=new, suppressed=suppressed,
                      known_axes=index.known_axes, files=len(files),
                      deep=deep_stats,
                      suppressed_counts=dict(sorted(
                          suppressed_counts.items())))
