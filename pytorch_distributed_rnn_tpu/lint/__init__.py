"""pdrnn-lint: JAX-aware static analysis for this framework.

The failure classes that cost the most at scale are the silent ones:
an axis-name typo in a ``lax.psum`` that XLA happily reduces over the
wrong (or no) mesh axis, a host sync buried in a jitted step that
serializes every dispatch, a weight-update ``jit`` that forgets buffer
donation and doubles peak memory, a closure rebuilt per step that
retraces every call, and stub functions that look implemented.  Each
round's external review re-derived these by ad-hoc AST scans; this
package makes the scans first-class, plugin-based, and CI-gated.

Rules
-----
- **PD101 axis-consistency** - every axis name passed as a string
  literal to a collective (``lax.psum``/``pmean``/``all_gather``/
  ``ppermute``/``axis_index``/... and the package's ``*_tree``
  wrappers), every ``PartitionSpec`` literal entry, and every
  ``axis=...`` default/keyword must be declared by a known mesh
  constructor (``Mesh(...)``, ``make_mesh({...})``, ``*_AXES``
  constants, axes-dict literals) somewhere in the scanned files.
- **PD102 host-sync-in-jit** - ``.item()``, ``float()/int()`` on
  traced values, ``np.asarray``/``np.array``, ``print``, ``time.*``
  and stdlib ``random.*`` calls reachable inside ``@jit``/
  ``shard_map``-wrapped or ``lax.scan``-carried functions.
- **PD103 missing-donation** - ``jax.jit`` sites whose wrapped
  function's first parameter is a params/opt-state pytree but that
  pass no ``donate_argnums``/``donate_argnames``.
- **PD104 retrace-hazard** - ``jax.jit``/``shard_map`` *construction*
  inside a loop body: the wrapped callable is rebuilt per iteration,
  so every call retraces and recompiles.
- **PD105 stub/dead-code** - function bodies that are only ``pass``/
  ``...``/``raise NotImplementedError`` (abstract methods, overloads
  and Protocol members excluded).

Deep (jaxpr) layer - ``--deep``
-------------------------------
The AST rules stop where tracing starts: unreduced gradients, silent
dtype promotion, and mesh/collective mismatches only exist in the
traced program.  ``--deep`` traces every trainer entry point declared
in the trace registry (``lint/trace_registry.py`` - each family in
``training/`` and ``parallel/`` registers its step with abstract
shape/dtype specs, no real data, CPU-only) and runs the jaxpr rules
(``lint/jaxpr_pass.py``):

- **PD200 trace-failure** - a registered entry no longer traces.
- **PD201 unreduced-gradient** - no psum/pmean over the declared data
  axis on the updated-params path (GSPMD entries: no sharding
  annotation mentioning the axis).
- **PD202 collective-axis-mismatch** - collective over an axis absent
  from the traced mesh (ground truth for PD101).
- **PD203 dtype-promotion-leak** - bf16/f16 upcast to f32 outside an
  allowlisted accumulation (``# noqa: PD203`` + contract comment).
- **PD204 dead-computation** - large DCE-removable clusters.
- **PD205 donation-mismatch** - donated buffers XLA cannot alias to
  any output (the donation silently drops).

Both layers share the CLI, ``# noqa`` directives, the baseline file and
the JSON report.  Run ``python -m pytorch_distributed_rnn_tpu.lint
--help`` for the CLI; ``lint_baseline.json`` at the repo root carries
the accepted pre-existing findings so CI gates on *new* ones only
(``--prune-baseline`` drops entries that stopped matching).
"""

from pytorch_distributed_rnn_tpu.lint.core import (
    Finding,
    LintResult,
    ModuleInfo,
    all_rules,
    run_lint,
)
from pytorch_distributed_rnn_tpu.lint.baseline import (
    load_baseline,
    prune_baseline,
    write_baseline,
)

__all__ = [
    "Finding",
    "LintResult",
    "ModuleInfo",
    "all_rules",
    "run_lint",
    "load_baseline",
    "prune_baseline",
    "write_baseline",
]
