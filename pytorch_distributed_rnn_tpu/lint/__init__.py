"""pdrnn-lint: JAX-aware static analysis for this framework.

The failure classes that cost the most at scale are the silent ones:
an axis-name typo in a ``lax.psum`` that XLA happily reduces over the
wrong (or no) mesh axis, a host sync buried in a jitted step that
serializes every dispatch, a weight-update ``jit`` that forgets buffer
donation and doubles peak memory, a closure rebuilt per step that
retraces every call, and stub functions that look implemented.  Each
round's external review re-derived these by ad-hoc AST scans; this
package makes the scans first-class, plugin-based, and CI-gated.

Rules
-----
- **PD101 axis-consistency** - every axis name passed as a string
  literal to a collective (``lax.psum``/``pmean``/``all_gather``/
  ``ppermute``/``axis_index``/... and the package's ``*_tree``
  wrappers), every ``PartitionSpec`` literal entry, and every
  ``axis=...`` default/keyword must be declared by a known mesh
  constructor (``Mesh(...)``, ``make_mesh({...})``, ``*_AXES``
  constants, axes-dict literals) somewhere in the scanned files.
- **PD102 host-sync-in-jit** - ``.item()``, ``float()/int()`` on
  traced values, ``np.asarray``/``np.array``, ``print``, ``time.*``
  and stdlib ``random.*`` calls reachable inside ``@jit``/
  ``shard_map``-wrapped or ``lax.scan``-carried functions.
- **PD103 missing-donation** - ``jax.jit`` sites whose wrapped
  function's first parameter is a params/opt-state pytree but that
  pass no ``donate_argnums``/``donate_argnames``.
- **PD104 retrace-hazard** - ``jax.jit``/``shard_map`` *construction*
  inside a loop body: the wrapped callable is rebuilt per iteration,
  so every call retraces and recompiles.
- **PD105 stub/dead-code** - function bodies that are only ``pass``/
  ``...``/``raise NotImplementedError`` (abstract methods, overloads
  and Protocol members excluded).

Run ``python -m pytorch_distributed_rnn_tpu.lint --help`` for the CLI;
``lint_baseline.json`` at the repo root carries the accepted
pre-existing findings so CI gates on *new* ones only.
"""

from pytorch_distributed_rnn_tpu.lint.core import (
    Finding,
    LintResult,
    ModuleInfo,
    all_rules,
    run_lint,
)
from pytorch_distributed_rnn_tpu.lint.baseline import (
    load_baseline,
    write_baseline,
)

__all__ = [
    "Finding",
    "LintResult",
    "ModuleInfo",
    "all_rules",
    "run_lint",
    "load_baseline",
    "write_baseline",
]
