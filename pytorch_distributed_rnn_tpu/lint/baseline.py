"""Baseline handling: accepted pre-existing findings, keyed by a
line-number-independent fingerprint so unrelated edits (or pure line
drift) never invalidate the file.

``lint_baseline.json``::

    {
      "version": 1,
      "findings": [
        {"fingerprint": "...", "count": 2,
         "rule": "PD105", "path": "...", "symbol": "...", "snippet": "..."}
      ]
    }

A current finding is suppressed while the baseline still has budget
for its fingerprint (identical findings in one file share one entry
with a count).  Regenerate with ``--write-baseline`` after reviewing
that every remaining finding is genuinely accepted.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import TYPE_CHECKING, Iterable

if TYPE_CHECKING:  # import cycle: core imports fingerprint lazily
    from pytorch_distributed_rnn_tpu.lint.core import Finding

_VERSION = 1


def fingerprint(finding: "Finding") -> str:
    key = "|".join((finding.rule, finding.path, finding.symbol,
                    finding.snippet))
    return hashlib.sha256(key.encode()).hexdigest()[:16]


def load_baseline(path: str | Path) -> dict[str, int]:
    """fingerprint -> accepted occurrence count.  Missing file = empty
    baseline (everything is a new finding)."""
    path = Path(path)
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    if data.get("version") != _VERSION:
        raise ValueError(
            f"unsupported baseline version {data.get('version')!r} "
            f"in {path} (expected {_VERSION})"
        )
    out: dict[str, int] = {}
    for entry in data.get("findings", []):
        out[entry["fingerprint"]] = (
            out.get(entry["fingerprint"], 0) + int(entry.get("count", 1))
        )
    return out


def write_baseline(path: str | Path,
                   findings: Iterable["Finding"]) -> dict:
    """Serialize ``findings`` as the new accepted baseline."""
    by_fp: dict[str, dict] = {}
    for f in findings:
        fp = fingerprint(f)
        if fp in by_fp:
            by_fp[fp]["count"] += 1
        else:
            by_fp[fp] = {
                "fingerprint": fp,
                "count": 1,
                "rule": f.rule,
                "path": f.path,
                "symbol": f.symbol,
                "snippet": f.snippet,
            }
    data = {
        "version": _VERSION,
        "tool": "pdrnn-lint",
        "findings": sorted(
            by_fp.values(),
            key=lambda e: (e["path"], e["rule"], e["symbol"], e["snippet"]),
        ),
    }
    Path(path).write_text(json.dumps(data, indent=2) + "\n")
    return data


def apply_baseline(findings: list["Finding"],
                   baseline: dict[str, int]) -> tuple[list["Finding"], int]:
    """Split ``findings`` into (new, suppressed_count)."""
    budget = dict(baseline)
    new: list["Finding"] = []
    suppressed = 0
    for f in findings:
        fp = fingerprint(f)
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            suppressed += 1
        else:
            new.append(f)
    return new, suppressed
