"""Baseline handling: accepted pre-existing findings, keyed by a
line-number-independent fingerprint so unrelated edits (or pure line
drift) never invalidate the file.

``lint_baseline.json``::

    {
      "version": 1,
      "findings": [
        {"fingerprint": "...", "count": 2,
         "rule": "PD105", "path": "...", "symbol": "...", "snippet": "..."}
      ]
    }

A current finding is suppressed while the baseline still has budget
for its fingerprint (identical findings in one file share one entry
with a count).  Regenerate with ``--write-baseline`` after reviewing
that every remaining finding is genuinely accepted.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import TYPE_CHECKING, Iterable

if TYPE_CHECKING:  # import cycle: core imports fingerprint lazily
    from pytorch_distributed_rnn_tpu.lint.core import Finding

_VERSION = 1


def fingerprint(finding: "Finding") -> str:
    key = "|".join((finding.rule, finding.path, finding.symbol,
                    finding.snippet))
    return hashlib.sha256(key.encode()).hexdigest()[:16]


def load_baseline(path: str | Path) -> dict[str, int]:
    """fingerprint -> accepted occurrence count.  Missing file = empty
    baseline (everything is a new finding)."""
    path = Path(path)
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    if data.get("version") != _VERSION:
        raise ValueError(
            f"unsupported baseline version {data.get('version')!r} "
            f"in {path} (expected {_VERSION})"
        )
    out: dict[str, int] = {}
    for entry in data.get("findings", []):
        out[entry["fingerprint"]] = (
            out.get(entry["fingerprint"], 0) + int(entry.get("count", 1))
        )
    return out


def write_baseline(path: str | Path,
                   findings: Iterable["Finding"],
                   keep_rules: Iterable[str] = (),
                   scanned: Iterable[str] | None = None) -> dict:
    """Serialize ``findings`` as the new accepted baseline.

    Existing entries matching a preservation guard are carried over
    instead of dropped: ``keep_rules`` (the CLI passes the PD2xx codes
    when writing WITHOUT ``--deep`` - the deep layer produced no
    findings, so a plain rewrite would silently delete every accepted
    deep entry) and ``scanned`` (repo-relative files this run actually
    linted; a narrowed path list must not wipe the rest of the repo's
    accepted entries).  Current findings win on fingerprint collision.
    """
    by_fp: dict[str, dict] = {}
    for f in findings:
        fp = fingerprint(f)
        if fp in by_fp:
            by_fp[fp]["count"] += 1
        else:
            by_fp[fp] = {
                "fingerprint": fp,
                "count": 1,
                "rule": f.rule,
                "path": f.path,
                "symbol": f.symbol,
                "snippet": f.snippet,
            }
    keep_rules = set(keep_rules)
    scanned = set(scanned) if scanned is not None else None
    path = Path(path)
    if path.exists() and (keep_rules or scanned is not None):
        for entry in json.loads(path.read_text()).get("findings", []):
            preserved = entry.get("rule") in keep_rules or (
                scanned is not None and entry.get("path") not in scanned)
            if preserved and entry["fingerprint"] not in by_fp:
                by_fp[entry["fingerprint"]] = entry
    data = {
        "version": _VERSION,
        "tool": "pdrnn-lint",
        "findings": sorted(
            by_fp.values(),
            key=lambda e: (e["path"], e["rule"], e["symbol"], e["snippet"]),
        ),
    }
    path.write_text(json.dumps(data, indent=2) + "\n")
    return data


def prune_baseline(path: str | Path,
                   findings: Iterable["Finding"],
                   keep_rules: Iterable[str] = (),
                   scanned: Iterable[str] | None = None) -> tuple[dict, int]:
    """Drop (or shrink) baseline entries whose fingerprint no longer
    matches any current finding - stale entries otherwise accumulate
    silently and could mask a future regression at the same location.

    ``findings`` must be the non-baselined current findings (run with
    ``baseline=None`` and no select/ignore).  Each entry's count is
    clamped to the current occurrence count; zero-match entries are
    removed.  Two preservation guards keep an entry untouched instead:
    ``keep_rules`` (the CLI passes the PD2xx codes when pruning WITHOUT
    ``--deep``, where deep entries would all look stale simply because
    their layer never ran) and ``scanned`` (the repo-relative files the
    run actually linted - entries for files OUTSIDE a narrowed path
    list would otherwise all look stale too).  Returns ``(new_data,
    dropped_count)`` and rewrites the file.
    """
    path = Path(path)
    data = json.loads(path.read_text()) if path.exists() else {
        "version": _VERSION, "tool": "pdrnn-lint", "findings": [],
    }
    if data.get("version") != _VERSION:
        raise ValueError(
            f"unsupported baseline version {data.get('version')!r} "
            f"in {path} (expected {_VERSION})"
        )
    current: dict[str, int] = {}
    for f in findings:
        fp = fingerprint(f)
        current[fp] = current.get(fp, 0) + 1

    keep_rules = set(keep_rules)
    scanned = set(scanned) if scanned is not None else None
    kept: list[dict] = []
    dropped = 0
    for entry in data.get("findings", []):
        if entry.get("rule") in keep_rules or (
                scanned is not None and entry.get("path") not in scanned):
            kept.append(entry)
            continue
        count = int(entry.get("count", 1))
        have = current.get(entry["fingerprint"], 0)
        keep = min(count, have)
        current[entry["fingerprint"]] = have - keep
        dropped += count - keep
        if keep:
            kept.append({**entry, "count": keep})
    data["findings"] = kept
    path.write_text(json.dumps(data, indent=2) + "\n")
    return data, dropped


def apply_baseline(findings: list["Finding"],
                   baseline: dict[str, int]) -> tuple[list["Finding"], int]:
    """Split ``findings`` into (new, suppressed_count)."""
    budget = dict(baseline)
    new: list["Finding"] = []
    suppressed = 0
    for f in findings:
        fp = fingerprint(f)
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            suppressed += 1
        else:
            new.append(f)
    return new, suppressed
