"""Trace registry: the entry points the jaxpr-level lint pass analyses.

The AST rules (PD1xx) see source text; the deep rules (PD2xx,
:mod:`.jaxpr_pass`) see the *traced program* - which only exists once a
concrete step function is bound to concrete input specs and a mesh.
This module is where each trainer family declares that binding: every
provider module (``training/native_ddp.py``, ``training/zero.py``,
``training/moe.py``, ``parallel/{dp,tp,sp,pp,ep}.py``) exposes a
``declare_trace_entries(register)`` hook that registers its step/forward
entry points with ABSTRACT input specs - shapes and dtypes only, via
``jax.ShapeDtypeStruct`` / ``jax.eval_shape``, no real data and no
compile.  Tracing runs on CPU under a small virtual device mesh
(``--xla_force_host_platform_device_count``), so the pass needs no TPU
and is cheap enough for a pre-merge gate.

A new trainer family plugs in by adding its module to
:data:`PROVIDER_MODULES` and defining ``declare_trace_entries``; see the
README "Static analysis" section for the contract.

Telemetry note: the observability subsystem (``obs/``) instruments the
step LOOPS, never the step PROGRAMS - timing and fencing happen around
the jitted call, and the traced-collectives event re-traces the live
step with ``jax.make_jaxpr`` without wrapping it.  The registered
entries here therefore keep covering instrumented trainers as-is;
``tests/test_obs.py::test_recorder_is_trace_transparent`` pins that a
recorder-enabled trainer builds a byte-identical step jaxpr.

This module imports jax only inside functions, so listing rule codes
and building the CLI stays jax-free.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from typing import Callable

# every trainer family that owns a step entry point; each module defines
# declare_trace_entries(register)
PROVIDER_MODULES = (
    "pytorch_distributed_rnn_tpu.parallel.dp",
    "pytorch_distributed_rnn_tpu.parallel.tp",
    "pytorch_distributed_rnn_tpu.parallel.sp",
    "pytorch_distributed_rnn_tpu.parallel.pp",
    "pytorch_distributed_rnn_tpu.parallel.ep",
    "pytorch_distributed_rnn_tpu.training.native_ddp",
    "pytorch_distributed_rnn_tpu.training.zero",
    "pytorch_distributed_rnn_tpu.training.moe",
    "pytorch_distributed_rnn_tpu.serving.engine",
    "pytorch_distributed_rnn_tpu.parallel.mpmd",
    "pytorch_distributed_rnn_tpu.streaming.runner",
)

# virtual CPU devices the deep pass guarantees when it owns the jax
# import (tests/conftest.py forces the same count for the suite)
LINT_DEVICE_COUNT = 8


@dataclass(frozen=True)
class TraceEntry:
    """One traceable step/forward program.

    ``build()`` is lazy (imports jax, constructs the mesh and abstract
    args) and returns ``(fn, args)`` where ``fn(*args)`` is traceable by
    ``jax.make_jaxpr`` - args are ``ShapeDtypeStruct`` pytrees, never
    real data.  ``data_axis`` is the mesh axis gradient reductions must
    cross (PD201); ``gspmd=True`` marks programs whose reduction is
    inserted by the SPMD partitioner from sharding annotations instead
    of explicit collectives (the ZeRO/FSDP style).  ``donate`` lists the
    argument indices the production builder donates (PD205).
    """

    name: str  # "dp.spmd_train_step"
    family: str  # "ddp"
    path: str  # repo-relative source file findings anchor to
    build: Callable[[], tuple]
    mesh_axes: dict = field(default_factory=dict)  # {"dp": 2}
    data_axis: str | None = None
    gspmd: bool = False
    donate: tuple = ()
    kind: str = "train_step"  # or "forward" / "update"

    @property
    def devices_needed(self) -> int:
        n = 1
        for size in self.mesh_axes.values():
            n *= size
        return n


class TraceRegistry:
    def __init__(self):
        self._entries: dict[str, TraceEntry] = {}

    def register(self, **kwargs) -> TraceEntry:
        entry = TraceEntry(**kwargs)
        if entry.name in self._entries:
            raise ValueError(f"duplicate trace entry {entry.name!r}")
        self._entries[entry.name] = entry
        return entry

    def entries(self) -> list[TraceEntry]:
        return [self._entries[k] for k in sorted(self._entries)]


@contextlib.contextmanager
def cpu_trace_session(n: int = LINT_DEVICE_COUNT):
    """Context for tracing: >= ``n`` virtual CPU devices when this
    process still controls backend initialization (the ``pdrnn-lint
    --deep`` CLI path: the package import pulls jax in, but XLA backend
    init is lazy, so the platform/device-count knobs still apply until
    something calls ``jax.devices()``).  Yields the visible device
    count; callers skip entries whose mesh needs more (backend already
    initialized smaller, e.g. under a test harness).

    The env/config mutations are restored on exit so child processes
    spawned later inherit the caller's platform choice.  ONE side
    effect is irreversible by design: if the deep pass is what first
    initializes jax, the process backend IS the CPU for its remaining
    lifetime (jax backends are global and the pass must never dial an
    attached accelerator just to make a jaxpr).  Library callers that
    want accelerator compute in the same process must touch
    ``jax.devices()`` before running the deep pass - at the cost of the
    pass then tracing on however few devices that backend exposes.
    """
    import os

    initialized = False
    try:  # private probe; on API drift assume uninitialized and set env
        from jax._src import xla_bridge

        initialized = bool(xla_bridge._backends)
    except Exception:
        pass
    saved = {key: os.environ.get(key)
             for key in ("JAX_PLATFORMS", "XLA_FLAGS")}
    config_touched = False
    prior_platforms = None
    if not initialized:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={n}"
            ).strip()
        try:
            import jax

            prior_platforms = jax.config.jax_platforms
            jax.config.update("jax_platforms", "cpu")
            config_touched = True
        except Exception:
            pass
    import jax

    try:
        yield len(jax.devices())
    finally:
        for key, value in saved.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value
        if config_touched:
            try:
                jax.config.update("jax_platforms", prior_platforms)
            except Exception:
                pass


def lint_mesh(axes: dict):
    """A concrete CPU mesh for tracing (``jax.make_jaxpr`` needs real
    devices bound to ``shard_map`` even though no data ever touches
    them).  Raises ``RuntimeError`` when the process has too few
    devices - ``run_deep`` converts that into a skipped entry."""
    import jax

    from pytorch_distributed_rnn_tpu.parallel.mesh import make_mesh

    needed = 1
    for size in axes.values():
        needed *= size
    have = len(jax.devices())
    if needed > have:
        raise RuntimeError(
            f"trace mesh {axes} needs {needed} devices, process has {have}"
        )
    return make_mesh(dict(axes))


def sds(shape, dtype):
    """Abstract array spec (the registry's only "data")."""
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def abstract_init(init_fn, *args):
    """Shape-level evaluation of an init function: the params/opt-state
    pytree as ``ShapeDtypeStruct`` leaves, no numbers materialized."""
    import jax

    return jax.eval_shape(init_fn, *args)


def prng_spec():
    """Abstract stand-in for a ``jax.random.PRNGKey(0)``-style key."""
    import jax.numpy as jnp

    return sds((2,), jnp.uint32)


def load_entries(provider_modules=PROVIDER_MODULES) -> list[TraceEntry]:
    """Import every provider module and collect its declared entries."""
    import importlib

    registry = TraceRegistry()
    for module_name in provider_modules:
        module = importlib.import_module(module_name)
        declare = getattr(module, "declare_trace_entries", None)
        if declare is None:
            raise RuntimeError(
                f"{module_name} is listed in PROVIDER_MODULES but defines "
                "no declare_trace_entries(register) hook"
            )
        declare(registry.register)
    return registry.entries()
