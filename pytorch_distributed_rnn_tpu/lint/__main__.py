"""``python -m pytorch_distributed_rnn_tpu.lint`` entry point."""

import sys

from pytorch_distributed_rnn_tpu.lint.cli import main

if __name__ == "__main__":
    sys.exit(main())
