"""Known-mesh-axis registry for PD101.

An axis name is "declared" when some scanned module constructs a mesh
(or an axes spec that feeds one) carrying it:

- ``Mesh(devices, ("dp", "tp"))`` / ``Mesh(..., axis_names=(...))``
- ``make_mesh({"dp": 4, "tp": -1})`` / ``make_mesh(axes={...})`` /
  ``global_device_mesh({...})`` / ``jax.make_mesh(..., ("dp",))``
- dict literals assigned to an axes-ish name (``axes = {"dp": dp}``,
  ``mesh_axes=...``, ``self.mesh_axes = ...``) - the package's
  strategy-resolution idiom builds the dict first, then calls
  ``make_mesh(axes)``
- tuple/list constants assigned to ``*_AXES`` module constants
  (``MODEL_AXES = ("sp", "tp", "pp")``)

The registry is the union over every scanned file, matching how one
process's mesh axes are visible to every shard_mapped function in the
package.  ``--known-axes`` extends it for out-of-tree callers.
"""

from __future__ import annotations

import ast
from typing import Iterable

from pytorch_distributed_rnn_tpu.lint.core import ModuleInfo

_MESH_CALL_TAILS = {"Mesh", "make_mesh", "global_device_mesh"}
_AXES_VAR_NAMES = {"axes", "mesh_axes", "axis_sizes"}
_AXIS_NAME_RE = r"[A-Za-z_][A-Za-z0-9_]*"


def _is_axis_str(node: ast.AST) -> bool:
    import re

    return (isinstance(node, ast.Constant) and isinstance(node.value, str)
            and re.fullmatch(_AXIS_NAME_RE, node.value) is not None)


def _strings_in(node: ast.AST | None) -> Iterable[str]:
    if node is None:
        return
    if _is_axis_str(node):
        yield node.value  # type: ignore[union-attr]
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if _is_axis_str(elt):
                yield elt.value  # type: ignore[union-attr]


def _dict_keys(node: ast.AST | None) -> Iterable[str]:
    if isinstance(node, ast.Dict):
        for k in node.keys:
            if k is not None and _is_axis_str(k):
                yield k.value  # type: ignore[union-attr]


def collect_known_axes(modules: Iterable[ModuleInfo]) -> set[str]:
    axes: set[str] = set()
    for mod in modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                resolved = mod.resolve(node.func) or ""
                tail = resolved.rsplit(".", 1)[-1]
                if tail in _MESH_CALL_TAILS:
                    for arg in node.args:
                        axes.update(_strings_in(arg))
                        axes.update(_dict_keys(arg))
                    for kw in node.keywords:
                        if kw.arg in ("axis_names", "axes", None):
                            axes.update(_strings_in(kw.value))
                            axes.update(_dict_keys(kw.value))
            elif isinstance(node, ast.Assign):
                targets = node.targets
                names = set()
                for t in targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        names.add(t.attr)
                if names & _AXES_VAR_NAMES or any(
                        n.endswith("_AXES") for n in names):
                    axes.update(_dict_keys(node.value))
                    axes.update(_strings_in(node.value))
                    # the resolution idiom merges defaults into the
                    # literal: axes = {"dp": 1, **axes}
                    if isinstance(node.value, ast.Dict):
                        for k, v in zip(node.value.keys, node.value.values):
                            if k is None:
                                axes.update(_dict_keys(v))
    return axes
