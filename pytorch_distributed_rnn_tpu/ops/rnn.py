"""RNN layers as ``lax.scan`` with MXU-batched input projections.

TPU-first design, deliberately NOT a translation of the reference's
``nn.LSTM`` call (``/root/reference/src/motion/model.py:9-16``):

- The input projection for *all* timesteps is computed up front as one large
  ``(B*T, in) x (in, 4H)`` matmul that XLA tiles onto the MXU.  The
  sequential part of the scan then only carries the ``(B, H) x (H, 4H)``
  recurrent matmul plus fused elementwise gate math - the minimum serial work
  an LSTM admits.
- ``lax.scan`` keeps the loop inside one XLA computation: traced once,
  unrolled/tiled by the compiler, no per-step Python dispatch.
- Weight layout and gate ordering follow torch (``w_ih: (4H, in)`` with gate
  order i,f,g,o; GRU r,z,n) so numerics are directly comparable with the
  reference models; tests check parity against torch CPU.

"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from pytorch_distributed_rnn_tpu.ops.initializers import lstm_uniform


# ---------------------------------------------------------------------------
# Parameter construction
# ---------------------------------------------------------------------------

def init_lstm_layer(key, input_size: int, hidden_size: int, dtype=jnp.float32):
    """One LSTM layer's params, torch layout: w_ih (4H, in), w_hh (4H, H),
    b_ih (4H,), b_hh (4H,). All U(-1/sqrt(H), 1/sqrt(H)) like torch."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    h = hidden_size
    return {
        "w_ih": lstm_uniform(k1, (4 * h, input_size), h, dtype),
        "w_hh": lstm_uniform(k2, (4 * h, h), h, dtype),
        "b_ih": lstm_uniform(k3, (4 * h,), h, dtype),
        "b_hh": lstm_uniform(k4, (4 * h,), h, dtype),
    }


def init_gru_layer(key, input_size: int, hidden_size: int, dtype=jnp.float32):
    """One GRU layer's params, torch layout with gate order r,z,n."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    h = hidden_size
    return {
        "w_ih": lstm_uniform(k1, (3 * h, input_size), h, dtype),
        "w_hh": lstm_uniform(k2, (3 * h, h), h, dtype),
        "b_ih": lstm_uniform(k3, (3 * h,), h, dtype),
        "b_hh": lstm_uniform(k4, (3 * h,), h, dtype),
    }


# ---------------------------------------------------------------------------
# Single layers
# ---------------------------------------------------------------------------

def lstm_input_proj(params, x):
    """Every timestep's LSTM pre-activation as one MXU matmul:
    ``x (B, T, in) -> (B, T, 4H)`` with BOTH bias vectors folded in (they
    add into the same pre-activation).  The one definition shared by the
    scan path, the Pallas fused path, and the sequence-parallel paths."""
    return (
        jnp.einsum("bti,gi->btg", x, params["w_ih"])
        + params["b_ih"]
        + params["b_hh"]
    )


def gru_input_proj(params, x):
    """Every timestep's GRU input-side pre-activation as one MXU matmul:
    ``x (B, T, in) -> (B, T, 3H)`` with ``b_ih`` folded in.  ``b_hh`` stays
    OUT: torch GRU semantics put the hidden-side n-bias inside the ``r *``
    product, so it joins in the recurrent step.  Shared by the scan and
    Pallas fused paths."""
    return jnp.einsum("bti,gi->btg", x, params["w_ih"]) + params["b_ih"]


def lstm_step(w_hh_t, carry, xp_t):
    """One LSTM gate step: ``xp_t`` is the (B, 4H) pre-activation with input
    projection and both biases folded in, ``carry`` is ``(h, c)``.  The one
    definition of the gate math (order i, f, g, o, torch semantics) shared by
    every scan-based path (``lstm_layer``, ``parallel/sp.py``); the Pallas
    kernel mirrors it and is parity-tested against it.

    Mixed-precision contract (matches the fused kernel's f32 VMEM scratch):
    the carry stays f32 so cell-state rounding never compounds across T;
    only the matmul runs in the compute dtype; the emitted per-step output
    follows ``xp_t``'s dtype.  All casts are no-ops in pure f32.
    """
    h, c = carry
    gates = (xp_t + h.astype(xp_t.dtype) @ w_hh_t).astype(jnp.float32)
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return (h, c), h.astype(xp_t.dtype)


def lstm_layer(params, x, h0=None, c0=None, *, unroll: int = 1):
    """Run one LSTM layer over ``x`` of shape (B, T, in).

    Returns ``(outputs (B, T, H), (h_T, c_T))``.  The initial carry defaults
    to zeros, matching torch's ``nn.LSTM`` when no hidden state is passed.
    """
    batch, _, _ = x.shape
    hidden = params["w_hh"].shape[1]
    dtype = x.dtype

    x_proj = lstm_input_proj(params, x)
    w_hh_t = params["w_hh"].T  # (H, 4H)

    # carry lives in f32 regardless of compute dtype (lstm_step contract)
    if h0 is None:
        h0 = jnp.zeros((batch, hidden), jnp.float32)
    if c0 is None:
        c0 = jnp.zeros((batch, hidden), jnp.float32)

    # scan over time: move T to the leading axis.
    (h_t, c_t), outputs = lax.scan(
        lambda carry, xp_t: lstm_step(w_hh_t, carry, xp_t),
        (h0.astype(jnp.float32), c0.astype(jnp.float32)),
        jnp.swapaxes(x_proj, 0, 1),
        unroll=unroll,
    )
    return jnp.swapaxes(outputs, 0, 1), (h_t.astype(dtype), c_t.astype(dtype))


def gru_step(w_hh_t, b_hh, h, xp_t):
    """One GRU gate step (torch semantics, gate order r, z, n): ``xp_t``
    is the (B, 3H) input-side pre-activation with ``b_ih`` folded in;
    ``b_hh`` joins the hidden-side projection here because the n-gate's
    hidden bias sits INSIDE the ``r *`` product.  The one definition of
    the GRU gate math shared by the scan path and the sequence-parallel
    relay; the Pallas kernel mirrors it and is parity-tested against it.

    Mixed-precision contract as :func:`lstm_step`: the carry stays f32,
    matmuls run in the compute dtype, the emitted output follows
    ``xp_t``'s dtype.
    """
    h_proj = (h.astype(xp_t.dtype) @ w_hh_t + b_hh).astype(jnp.float32)
    xr, xz, xn = jnp.split(xp_t.astype(jnp.float32), 3, axis=-1)
    hr, hz, hn = jnp.split(h_proj, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    h = (1.0 - z) * n + z * h
    return h, h.astype(xp_t.dtype)


def gru_layer(params, x, h0=None, *, unroll: int = 1):
    """Run one GRU layer over ``x`` of shape (B, T, in).

    torch GRU semantics: ``n = tanh(x_n + b_in + r * (h @ w_hn.T + b_hn))``,
    ``h' = (1 - z) * n + z * h`` - note the hidden-side bias sits *inside*
    the ``r`` product, so it cannot be folded into the input projection.
    """
    batch, _, _ = x.shape
    hidden = params["w_hh"].shape[1]
    dtype = x.dtype

    x_proj = gru_input_proj(params, x)
    w_hh_t = params["w_hh"].T  # (H, 3H)
    b_hh = params["b_hh"]

    # carry in f32 (mixed-precision contract: matmuls in compute dtype,
    # state accumulation in f32 - all casts no-ops in pure f32)
    if h0 is None:
        h0 = jnp.zeros((batch, hidden), jnp.float32)

    h_t, outputs = lax.scan(
        lambda h, xp_t: gru_step(w_hh_t, b_hh, h, xp_t),
        h0.astype(jnp.float32),
        jnp.swapaxes(x_proj, 0, 1), unroll=unroll)
    return jnp.swapaxes(outputs, 0, 1), h_t.astype(dtype)


# ---------------------------------------------------------------------------
# Stacks
# ---------------------------------------------------------------------------

def init_stacked_rnn(
    key,
    input_size: int,
    hidden_size: int,
    num_layers: int,
    cell: str = "lstm",
    dtype=jnp.float32,
):
    """Params for a stacked RNN: list of per-layer dicts (layer 0 consumes
    ``input_size``, the rest consume ``hidden_size``)."""
    init_fn = {"lstm": init_lstm_layer, "gru": init_gru_layer}[cell]
    keys = jax.random.split(key, num_layers)
    return [
        init_fn(keys[i], input_size if i == 0 else hidden_size, hidden_size, dtype)
        for i in range(num_layers)
    ]


def dtype_of(precision: str):
    """The ONE precision-string -> compute-dtype mapping (None = f32),
    shared by every model's apply path and every mesh loss builder - a
    new precision value added here takes effect everywhere at once."""
    return jnp.bfloat16 if precision == "bf16" else None


def resolve_rnn_impl(impl: str, cell: str, hidden: int | None = None) -> str:
    """Resolve the recurrent-step implementation.

    ``"scan"`` = portable ``lax.scan`` path; ``"fused"`` = Pallas fused
    time-loop kernel (``ops/pallas_rnn.py``); ``"auto"`` picks the fused
    kernel on TPU *for small hidden sizes* - the regime where per-step
    loop overhead dominates (the motion model's H=32) and the kernel's
    VMEM working set fits comfortably.  At large H (the 50M LM's H=1280)
    each scan step is already a substantial MXU matmul and the fused
    region's (T, B, 4H) buffers press the scoped-VMEM budget, so auto
    takes the scan path there.  Explicit ``"fused"`` is always honored.
    """
    if impl not in ("auto", "scan", "fused"):
        raise ValueError(f"unknown rnn impl {impl!r}")
    if impl == "auto":
        if (
            cell in ("lstm", "gru")
            and jax.default_backend() == "tpu"
            and (hidden is None or hidden <= 512)
        ):
            return "fused"
        return "scan"
    if impl == "fused" and cell not in ("lstm", "gru"):
        raise ValueError(f"fused impl supports lstm/gru only, got {cell!r}")
    return impl


def stacked_rnn(
    layers,
    x,
    cell: str = "lstm",
    *,
    dropout: float = 0.0,
    dropout_key=None,
    unroll: int = 1,
    impl: str = "auto",
    compute_dtype=None,
    remat: bool = False,
):
    """Apply a stack of RNN layers; dropout between layers (not after the
    last), matching torch's stacked ``nn.LSTM(dropout=...)`` placement.

    ``dropout_key=None`` selects eval/deterministic mode (the analogue of
    torch's ``model.eval()``): dropout is skipped even when ``dropout > 0``.
    Pass a PRNG key to enable train-mode dropout.

    TPU levers (both default off, numerics unchanged):

    - ``compute_dtype`` (e.g. ``jnp.bfloat16``): params and activations are
      cast for the layer compute - bf16 matmuls run at full MXU rate and
      halve HBM traffic; params stay stored in their own dtype, so the
      optimizer update remains full precision (standard mixed precision).
      Outputs come back in ``compute_dtype``; cast at the loss if needed.
    - ``remat``: wrap each layer in ``jax.checkpoint`` - activations are
      recomputed during backward instead of saved, trading FLOPs for HBM
      (the lever for deep stacks / long sequences like the 50M LM preset).

    Returns (outputs (B, T, H), list of per-layer final carries).
    """
    impl = resolve_rnn_impl(
        impl, cell, hidden=layers[0]["w_hh"].shape[1] if layers else None
    )
    if impl == "fused":
        from pytorch_distributed_rnn_tpu.ops.pallas_rnn import (
            gru_layer_fused,
            lstm_layer_fused,
        )

        lstm_fn = lstm_layer_fused
        gru_fn = gru_layer_fused
    else:
        lstm_fn = partial(lstm_layer, unroll=unroll)
        gru_fn = partial(gru_layer, unroll=unroll)
    if cell == "lstm":
        layer_fn = lstm_fn
    elif cell == "gru":
        layer_fn = gru_fn
    else:
        raise ValueError(f"unknown cell {cell!r}")
    if remat:
        layer_fn = jax.checkpoint(layer_fn)

    finals = []
    out = x
    if compute_dtype is not None:
        out = out.astype(compute_dtype)
    for idx, layer in enumerate(layers):
        if compute_dtype is not None:
            layer = jax.tree.map(
                lambda p: p.astype(compute_dtype), layer
            )
        out, final = layer_fn(layer, out)
        finals.append(final)
        if dropout > 0.0 and dropout_key is not None and idx < len(layers) - 1:
            out, dropout_key = interlayer_dropout(out, dropout_key, dropout)
    return out, finals


def stacked_rnn_decode_step(layers, carries, x, cell: str = "lstm"):
    """One autoregressive token step through a stacked RNN.

    ``x``: (B, in) - the current token's embedding; ``carries``: per-layer
    final states as returned by :func:`stacked_rnn` (LSTM ``(h, c)`` pairs
    or GRU ``h``).  Returns ``(new_carries, h_top (B, H))``.

    This is the ONE definition of single-token decode shared by
    ``CharRNN.generate``, ``MoELM.generate`` and the serving adapters
    (``serving/adapters.py``) - batched continuous-decode steps reuse the
    exact math of the per-request reference decode, so a request served
    inside a batch reproduces its single-request decode bit for bit.
    Decode runs in f32 (the generation contract: latency-bound, not
    MXU-bound, and sampling is sensitive to logit rounding); carries are
    cast on entry so callers may hand over the ``stacked_rnn`` finals of
    a reduced-precision prefill unchanged.
    """
    h_in = x
    new_carries = []
    for layer, state in zip(layers, carries):
        # single-timestep slice through the shared projection helpers
        # (the one definition of the bias-folding rules)
        if cell == "lstm":
            xp = lstm_input_proj(layer, h_in[:, None, :])[:, 0]
            state = jax.tree.map(lambda s: s.astype(jnp.float32), state)
            (h, c), h_in = lstm_step(layer["w_hh"].T, state, xp)
            new_carries.append((h, c))
        elif cell == "gru":
            xp = gru_input_proj(layer, h_in[:, None, :])[:, 0]
            h, h_in = gru_step(
                layer["w_hh"].T, layer["b_hh"],
                state.astype(jnp.float32), xp)
            new_carries.append(h)
        else:
            raise ValueError(f"unknown cell {cell!r}")
    return new_carries, h_in


def head_logits(head, h):
    """The ONE LM vocab-head projection (f32 compute regardless of the
    backbone's dtype - sampling is sensitive to logit rounding), shared
    by the char/MoE model families and the serving adapters so batched
    serving can never drift from single-request ``generate`` numerics.
    ``head``: ``{"weight", "bias"}``; ``h``: (..., H) -> (..., vocab)."""
    return h.astype(jnp.float32) @ head["weight"].T + head["bias"]


def interlayer_dropout(out, dropout_key, dropout: float):
    """The ONE between-layer dropout block (split/bernoulli/scale) shared
    by the unsharded stack above and the sp relay stacks
    (``parallel/sp.py``) - its placement/scaling being identical across
    paths is a tested contract.  Returns ``(masked_out, next_key)``."""
    dropout_key, sub = jax.random.split(dropout_key)
    keep = 1.0 - dropout
    mask = jax.random.bernoulli(sub, keep, out.shape)
    return jnp.where(mask, out / keep, 0.0).astype(out.dtype), dropout_key
