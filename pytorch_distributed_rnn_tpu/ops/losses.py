"""Losses with torch-parity semantics.

The reference trains with ``torch.nn.CrossEntropyLoss()`` (mean reduction,
logits input - ``/root/reference/src/motion/trainer/base.py:15``) and the toy
examples use ``nn.MSELoss()``
(``/root/reference/src/example/example_ddp.py:53``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_loss(logits, labels, reduction: str = "mean"):
    """Softmax cross entropy on integer labels.

    ``logits``: (N, C) float; ``labels``: (N,) int.  ``mean`` averages over
    the batch like torch's default ``CrossEntropyLoss``.
    """
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(log_probs, labels[:, None].astype(jnp.int32), axis=-1)[
        :, 0
    ]
    if reduction == "mean":
        return jnp.mean(nll)
    if reduction == "sum":
        return jnp.sum(nll)
    return nll


def mse_loss(pred, target, reduction: str = "mean"):
    """Mean squared error, torch ``MSELoss`` semantics (mean over all
    elements)."""
    sq = jnp.square(pred - target)
    if reduction == "mean":
        return jnp.mean(sq)
    if reduction == "sum":
        return jnp.sum(sq)
    return sq
