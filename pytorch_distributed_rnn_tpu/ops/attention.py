"""Attention primitives: full, ring (sequence-parallel), and Ulysses.

The reference framework is RNN-only - its sole sequence model is the motion
LSTM (``/root/reference/src/motion/model.py:4-17``) with a fixed 128-step
window.  Long-context support is a first-class capability of this framework,
so attention ships with two sequence/context-parallel execution strategies,
both pure XLA-collective designs (no NCCL/MPI analogue needed):

- **Ring attention** (`ring_attention`): Q stays put, K/V blocks rotate
  around the ``sp`` ring via ``lax.ppermute`` (CollectivePermute over ICI).
  Each of the S rounds combines one K/V block into a running flash-style
  (online-softmax) accumulator, so the full (T x T) score matrix never
  materializes and per-chip memory is O(T^2/S^2) per round.  Compute and
  the next block's transfer overlap naturally on TPU.
- **Ulysses / all-to-all** (`ulysses_attention`): one ``lax.all_to_all``
  re-shards from sequence-sharded to head-sharded, full attention runs
  locally per head group, and a second all-to-all restores sequence
  sharding.  Cheaper collectives for moderate T; requires heads % S == 0.

Both match :func:`mha_attention` on the gathered sequence exactly (same
softmax, fp32 accumulation) and are parity-tested against it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def mha_attention(q, k, v, *, causal: bool = False, q_offset=0, k_offset=0):
    """Reference multi-head attention.

    ``q``: (B, H, Tq, D), ``k``/``v``: (B, H, Tk, D) -> (B, H, Tq, D).
    ``q_offset``/``k_offset`` are the global positions of the first query /
    key, so causal masking works on sequence chunks.
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[2])
        k_pos = k_offset + jnp.arange(k.shape[2])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _online_update(q, k, v, m, l, acc, *, scale, mask=None):
    """Fold one K/V block into a flash-style running softmax.

    ``m``: (B, H, Tq) running max, ``l``: (B, H, Tq) running denominator,
    ``acc``: (B, H, Tq, D) running numerator, all fp32.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # exp(-inf - -inf) guard: rows with no valid key yet keep m = -inf
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(m - m_new)
    corr = jnp.where(jnp.isfinite(corr), corr, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis: str, *, causal: bool = False):
    """Sequence-parallel attention over a time-sharded sequence, for use
    inside ``shard_map``.

    ``q``/``k``/``v``: this shard's (B, H, T/S, D) chunk, sharded on global
    time along mesh axis ``axis``.  K/V blocks rotate S times around the
    ring; each round updates the online-softmax accumulator for the local
    queries.  Returns the local (B, H, T/S, D) output chunk.
    """
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    # blocks travel to the *next* shard each round, so after r rounds this
    # shard holds the block that started (idx - r) mod n
    perm = [(i, (i + 1) % n) for i in range(n)]
    scale = q.shape[-1] ** -0.5
    b, h, t_local, d = q.shape
    qf = q.astype(jnp.float32)
    q_pos = idx * t_local + jnp.arange(t_local)

    def block_mask(src):
        if not causal:
            return None
        k_pos = src * t_local + jnp.arange(t_local)
        return q_pos[:, None] >= k_pos[None, :]

    # round 0 is the local block - no transfer needed; the scan then does
    # permute-first so exactly n-1 CollectivePermutes run in total.
    m0 = jnp.full((b, h, t_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    acc0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    m, l, acc = _online_update(
        qf, k.astype(jnp.float32), v, m0, l0, acc0,
        scale=scale, mask=block_mask(idx),
    )

    def round_(carry, r):
        k_blk, v_blk, m, l, acc = carry
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        m, l, acc = _online_update(
            qf, k_blk.astype(jnp.float32), v_blk, m, l, acc,
            scale=scale, mask=block_mask((idx - r) % n),
        )
        return (k_blk, v_blk, m, l, acc), None

    if n > 1:
        (_, _, _, l, acc), _ = lax.scan(
            round_, (k, v, m, l, acc), jnp.arange(1, n)
        )
    return (acc / l[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, axis: str, *, causal: bool = False,
                      attn=None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style), for use
    inside ``shard_map``.

    Input is sequence-sharded (B, H, T/S, D); one all-to-all re-shards to
    head-sharded (B, H/S, T, D), attention runs locally over the full
    sequence for this shard's heads, and a second all-to-all restores
    sequence sharding.  Requires ``H %% S == 0``.  ``attn(q, k, v, *,
    causal)`` overrides the local full attention (default dense
    :func:`mha_attention`; pass the fused Pallas ``flash_attention`` for
    the kernelized inner).
    """
    n = lax.axis_size(axis)
    if q.shape[1] % n != 0:
        raise ValueError(
            f"ulysses needs heads ({q.shape[1]}) divisible by the axis size"
            f" ({n})"
        )
    attn = attn if attn is not None else mha_attention
    # split heads (axis 1) across shards, gather time (axis 2)
    to_heads = lambda x: lax.all_to_all(   # noqa: E731
        x, axis, split_axis=1, concat_axis=2, tiled=True)
    to_seq = lambda x: lax.all_to_all(     # noqa: E731
        x, axis, split_axis=2, concat_axis=1, tiled=True)
    out = attn(to_heads(q), to_heads(k), to_heads(v), causal=causal)
    return to_seq(out)
