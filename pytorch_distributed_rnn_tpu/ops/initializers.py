"""Torch-parity initializers, expressed with JAX PRNG.

The reference relies on PyTorch's default initializers (it never overrides
them): ``nn.LSTM`` draws every weight and bias from U(-k, k) with
k = 1/sqrt(hidden_size); ``nn.Linear`` uses kaiming-uniform(a=sqrt(5)) for the
weight -- which reduces to U(-1/sqrt(fan_in), 1/sqrt(fan_in)) -- and
U(-1/sqrt(fan_in), 1/sqrt(fan_in)) for the bias.  Matching the *distribution*
(not the bitstream) keeps loss curves comparable with the reference models
(``/root/reference/src/motion/model.py:9-16``,
``/root/reference/src/example/example_ddp.py:11-19``).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def uniform_bound(key: jax.Array, shape, bound: float, dtype=jnp.float32):
    """Sample U(-bound, bound)."""
    return jax.random.uniform(key, shape, dtype=dtype, minval=-bound, maxval=bound)


def lstm_uniform(key: jax.Array, shape, hidden_size: int, dtype=jnp.float32):
    """torch.nn.LSTM / nn.GRU default: U(-1/sqrt(H), 1/sqrt(H)) for all tensors."""
    return uniform_bound(key, shape, 1.0 / math.sqrt(hidden_size), dtype=dtype)


def linear_init(key: jax.Array, in_features: int, out_features: int, dtype=jnp.float32):
    """torch.nn.Linear default init.

    Returns ``{"weight": (out, in), "bias": (out,)}`` -- torch layout, so a
    forward pass is ``x @ weight.T + bias``.
    """
    wkey, bkey = jax.random.split(key)
    bound = 1.0 / math.sqrt(in_features)
    return {
        "weight": uniform_bound(wkey, (out_features, in_features), bound, dtype),
        "bias": uniform_bound(bkey, (out_features,), bound, dtype),
    }
