"""Mixture-of-experts FFN: token-choice (top-1, Switch-style) routing.

The reference has no MoE (SURVEY.md checklist: expert parallelism absent).
This is the capability layer for the ``ep`` mesh axis: a router picks one
expert per token, tokens are dispatched into per-expert capacity slots via
one-hot matmuls (the TPU-friendly formulation - dense einsums instead of
scatter/gather, so everything tiles onto the MXU), experts run their FFN,
and outputs combine back weighted by the gate probability.

``moe_ffn_dense`` computes every expert on every token (exact, O(E) flops)
- the numerics reference.  ``moe_ffn`` dispatches through capacity slots;
with ``capacity >= tokens routed to the busiest expert`` it matches the
dense path exactly, otherwise overflow tokens drop (standard Switch
behavior - the combine weight for dropped tokens is zero, so they pass
through the residual unchanged).  ``parallel/ep.py`` shards the expert
dimension of the same formulation over the mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pytorch_distributed_rnn_tpu.ops.initializers import linear_init


def init_moe_ffn(key, dim: int, num_experts: int, hidden: int):
    """Router + stacked expert FFN params."""
    kr, k1, k2 = jax.random.split(key, 3)
    e = num_experts

    def stacked(k, shape, fan_in):
        bound = fan_in ** -0.5
        return jax.random.uniform(k, shape, minval=-bound, maxval=bound)

    return {
        "router": linear_init(kr, dim, num_experts),
        "w1": stacked(k1, (e, dim, hidden), dim),
        "b1": jnp.zeros((e, hidden)),
        "w2": stacked(k2, (e, hidden, dim), hidden),
        "b2": jnp.zeros((e, dim)),
    }


def cast_expert_params(params, compute_dtype):
    """The MoE mixed-precision contract, in ONE place (shared by the
    dense ``MoEClassifier.features`` path and the ep-mesh loss): expert
    weights move to the compute dtype, the ROUTER stays f32 - routing
    decisions and the aux loss are the numerics that must not quantize.
    ``compute_dtype=None`` returns the tree unchanged."""
    if compute_dtype is None:
        return params
    return {
        k: (v if k == "router"
            else jax.tree.map(lambda p: p.astype(compute_dtype), v))
        for k, v in params.items()
    }


def _route(params, x):
    """Top-1 routing: returns (expert_idx (N,), prob (N,), gates (N, E))."""
    logits = x @ params["router"]["weight"].T + params["router"]["bias"]
    gates = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(gates, axis=-1)
    prob = jnp.max(gates, axis=-1)
    return expert, prob, gates


def load_balancing_loss(gates, expert, num_experts: int):
    """Switch aux loss: E * sum_e (fraction of tokens to e) * (mean gate
    prob of e); minimized at uniform routing."""
    one_hot = jax.nn.one_hot(expert, num_experts, dtype=gates.dtype)
    frac_tokens = jnp.mean(one_hot, axis=0)
    frac_prob = jnp.mean(gates, axis=0)
    return num_experts * jnp.sum(frac_tokens * frac_prob)


def _expert_ffn(params, tokens):
    """tokens: (E, C, D) - slot c of expert e -> same shape."""
    h = jax.nn.gelu(
        jnp.einsum("ecd,edh->ech", tokens, params["w1"])
        + params["b1"][:, None, :]
    )
    return (
        jnp.einsum("ech,ehd->ecd", h, params["w2"])
        + params["b2"][:, None, :]
    )


def make_dispatch(expert, prob, num_experts: int, capacity: int, dtype):
    """Build the (N, E, C) one-hot dispatch tensor and the prob-weighted
    combine tensor from top-1 assignments.

    Position within an expert's capacity = how many earlier tokens chose the
    same expert; tokens whose position >= capacity are dropped (combine
    weight 0).
    """
    one_hot = jax.nn.one_hot(expert, num_experts, dtype=jnp.int32)
    # slot = how many earlier tokens chose the same expert
    pos = jnp.sum((jnp.cumsum(one_hot, axis=0) - 1) * one_hot, axis=1)
    in_cap = pos < capacity
    dispatch = (
        jax.nn.one_hot(expert, num_experts, dtype=dtype)[:, :, None]
        * jax.nn.one_hot(jnp.where(in_cap, pos, -1), capacity, dtype=dtype)[
            :, None, :
        ]
    )
    combine = dispatch * prob[:, None, None]
    return dispatch, combine


def moe_ffn(params, x, *, capacity_factor: float = 2.0):
    """Top-1 MoE FFN over tokens ``x`` (..., D) via one-hot dispatch.

    Capacity per expert = ceil(tokens / E * capacity_factor).
    """
    shape = x.shape
    d = shape[-1]
    xt = x.reshape(-1, d)
    n = xt.shape[0]
    e = params["w1"].shape[0]
    capacity = int(-(-n * capacity_factor // e))

    expert, prob, gates = _route(params, xt)
    dispatch, combine = make_dispatch(expert, prob, e, capacity, xt.dtype)
    tokens = jnp.einsum("nec,nd->ecd", dispatch, xt)
    out = jnp.einsum("nec,ecd->nd", combine, _expert_ffn(params, tokens))
    aux = load_balancing_loss(gates, expert, e)
    return out.reshape(shape), aux


def moe_ffn_dense(params, x):
    """Exact top-1 MoE: every expert computes every token, the gate picks.
    O(E) compute - the parity reference for the dispatched paths."""
    shape = x.shape
    d = shape[-1]
    xt = x.reshape(-1, d)
    e = params["w1"].shape[0]

    expert, prob, gates = _route(params, xt)
    h = jax.nn.gelu(
        jnp.einsum("nd,edh->neh", xt, params["w1"]) + params["b1"][None]
    )
    all_out = (
        jnp.einsum("neh,ehd->ned", h, params["w2"]) + params["b2"][None]
    )
    sel = jax.nn.one_hot(expert, e, dtype=xt.dtype)
    out = jnp.einsum("ne,ned->nd", sel, all_out) * prob[:, None]
    aux = load_balancing_loss(gates, expert, e)
    return out.reshape(shape), aux
