"""Mixture-of-experts FFN: token-choice top-1 (Switch) and top-2 (GShard)
routing.

The reference has no MoE (SURVEY.md checklist: expert parallelism absent).
This is the capability layer for the ``ep`` mesh axis: a router picks
``num_selected`` experts per token, tokens are dispatched into per-expert
capacity slots via one-hot matmuls (the TPU-friendly formulation - dense
einsums instead of scatter/gather, so everything tiles onto the MXU),
experts run their FFN, and outputs combine back weighted by the gate
probabilities.

Routing conventions follow the papers: ``num_selected=1`` is Switch - the
combine weight is the RAW max gate probability; ``num_selected>=2`` is
GShard - the selected gates are renormalized to sum to 1, and capacity
slots are assigned choice-major (every token's first choice outranks any
second choice), so under pressure second choices drop first.

``moe_ffn_dense`` computes every expert on every token (exact, O(E) flops)
- the numerics reference.  ``moe_ffn`` dispatches through capacity slots;
with ``capacity >= tokens routed to the busiest expert`` it matches the
dense path exactly, otherwise overflow tokens drop (the combine weight
for dropped tokens is zero, so they pass through the residual unchanged).
``parallel/ep.py`` shards the expert dimension of the same formulation
over the mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pytorch_distributed_rnn_tpu.ops.initializers import linear_init


def init_moe_ffn(key, dim: int, num_experts: int, hidden: int):
    """Router + stacked expert FFN params."""
    kr, k1, k2 = jax.random.split(key, 3)
    e = num_experts

    def stacked(k, shape, fan_in):
        bound = fan_in ** -0.5
        return jax.random.uniform(k, shape, minval=-bound, maxval=bound)

    return {
        "router": linear_init(kr, dim, num_experts),
        "w1": stacked(k1, (e, dim, hidden), dim),
        "b1": jnp.zeros((e, hidden)),
        "w2": stacked(k2, (e, hidden, dim), hidden),
        "b2": jnp.zeros((e, dim)),
    }


def cast_expert_params(params, compute_dtype):
    """The MoE mixed-precision contract, in ONE place (shared by the
    dense ``MoEClassifier.features`` path and the ep-mesh loss): expert
    weights move to the compute dtype, the ROUTER stays f32 - routing
    decisions and the aux loss are the numerics that must not quantize.
    ``compute_dtype=None`` returns the tree unchanged."""
    if compute_dtype is None:
        return params
    return {
        k: (v if k == "router"
            else jax.tree.map(lambda p: p.astype(compute_dtype), v))
        for k, v in params.items()
    }


def _route(params, x):
    """Top-1 routing: returns (expert_idx (N,), prob (N,), gates (N, E))."""
    logits = x @ params["router"]["weight"].T + params["router"]["bias"]
    gates = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(gates, axis=-1)
    prob = jnp.max(gates, axis=-1)
    return expert, prob, gates


def _route_topk(params, x, k: int):
    """Top-k routing: returns (experts (N, k), probs (N, k), gates (N, E)).

    ``k=1`` reproduces :func:`_route` exactly (raw max-gate combine
    weight, Switch).  ``k>=2`` renormalizes the selected gates to sum to
    1 per token (GShard eq. 1)."""
    logits = x @ params["router"]["weight"].T + params["router"]["bias"]
    gates = jax.nn.softmax(logits, axis=-1)
    probs, experts = jax.lax.top_k(gates, k)
    if k > 1:
        probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    return experts, probs, gates


def load_balancing_loss(gates, expert, num_experts: int):
    """Switch aux loss: E * sum_e (fraction of tokens to e) * (mean gate
    prob of e); minimized at uniform routing."""
    one_hot = jax.nn.one_hot(expert, num_experts, dtype=gates.dtype)
    frac_tokens = jnp.mean(one_hot, axis=0)
    frac_prob = jnp.mean(gates, axis=0)
    return num_experts * jnp.sum(frac_tokens * frac_prob)


def _expert_ffn(params, tokens):
    """tokens: (E, C, D) - slot c of expert e -> same shape."""
    h = jax.nn.gelu(
        jnp.einsum("ecd,edh->ech", tokens, params["w1"])
        + params["b1"][:, None, :]
    )
    return (
        jnp.einsum("ech,ehd->ecd", h, params["w2"])
        + params["b2"][:, None, :]
    )


def _slot_positions(expert, num_experts: int):
    """Capacity-slot position of each assignment: how many earlier
    entries of ``expert`` chose the same expert.  The ONE slotting
    formula - :func:`make_dispatch` builds its one-hots from it, and a
    drop-fraction counter summing ``pos < capacity`` matches the real
    dispatch exactly without materializing the (N, E, C) tensor."""
    one_hot = jax.nn.one_hot(expert, num_experts, dtype=jnp.int32)
    return jnp.sum((jnp.cumsum(one_hot, axis=0) - 1) * one_hot, axis=1)


def make_dispatch(expert, prob, num_experts: int, capacity: int, dtype):
    """Build the (N, E, C) one-hot dispatch tensor and the prob-weighted
    combine tensor from top-1 assignments.

    Position within an expert's capacity = how many earlier tokens chose the
    same expert; tokens whose position >= capacity are dropped (combine
    weight 0).
    """
    pos = _slot_positions(expert, num_experts)
    in_cap = pos < capacity
    dispatch = (
        jax.nn.one_hot(expert, num_experts, dtype=dtype)[:, :, None]
        * jax.nn.one_hot(jnp.where(in_cap, pos, -1), capacity, dtype=dtype)[
            :, None, :
        ]
    )
    combine = dispatch * prob[:, None, None]
    return dispatch, combine


def make_dispatch_topk(experts, probs, num_experts: int, capacity: int,
                       dtype):
    """(N, E, C) dispatch/combine tensors from top-k assignments.

    Slots are assigned CHOICE-MAJOR (GShard): all tokens' choice-0
    assignments take positions before any choice-1 assignment, so when an
    expert overflows its capacity, second choices are dropped first.
    ``k=1`` degenerates to :func:`make_dispatch` exactly.
    """
    n, k = experts.shape
    # flatten choice-major: rows [choice0 tokens..., choice1 tokens...]
    flat_experts = experts.T.reshape(-1)  # (k*N,)
    flat_probs = probs.T.reshape(-1)
    dispatch_flat, combine_flat = make_dispatch(
        flat_experts, flat_probs, num_experts, capacity, dtype
    )
    # fold the k choice rows of each token back together: a token's
    # dispatch is the SUM of its per-choice one-hots (disjoint slots, so
    # the sum stays one-hot per (expert, slot))
    dispatch = dispatch_flat.reshape(k, n, num_experts, capacity).sum(0)
    combine = combine_flat.reshape(k, n, num_experts, capacity).sum(0)
    return dispatch, combine


def moe_capacity(n_tokens: int, num_experts: int, capacity_factor: float,
                 num_selected: int = 1) -> int:
    """Capacity per expert = ceil(assignments / E * capacity_factor),
    where assignments = tokens x num_selected (GShard scales capacity
    with k; k=1 reduces to the Switch formula).  ONE definition shared by
    the dense dispatch and the ep-sharded path, so the two can never
    disagree on drop behavior."""
    return int(-(-n_tokens * num_selected * capacity_factor // num_experts))


def grouped_pack_topk(xt, experts_k, probs_k, num_experts: int,
                      group_size: int, capacity_factor: float,
                      num_selected: int):
    """Grouped (GShard) slot packing from top-k assignments: returns
    ``(tokens (E, G*C, D), combine (G, group_size, E, C), G, C)``.  ONE
    definition shared by the single-device dispatched path and the
    ep-sharded path (the :func:`moe_capacity` convention), so the two
    can never disagree on grouped slotting, capacity, or validation."""
    n, d = xt.shape
    if group_size <= 0 or n % group_size:
        raise ValueError(
            f"{n} tokens do not split into groups of {group_size} "
            "(moe group_size must be positive and divide the token count)"
        )
    g = n // group_size
    capacity = moe_capacity(group_size, num_experts, capacity_factor,
                            num_selected)
    disp_g, comb_g = jax.vmap(
        lambda ex, pr: make_dispatch_topk(ex, pr, num_experts, capacity,
                                          xt.dtype)
    )(experts_k.reshape(g, group_size, -1),
      probs_k.reshape(g, group_size, -1))
    # per-group pack -> (E, G*C, D) slots so the expert FFN (and the ep
    # path's all_to_all) see ONE stacked slot dim over all groups
    tokens = jnp.einsum(
        "gnec,gnd->egcd", disp_g, xt.reshape(g, group_size, d)
    ).reshape(num_experts, g * capacity, d)
    return tokens, comb_g, g, capacity


def grouped_combine_topk(out_tokens, combine, g: int, capacity: int):
    """Inverse of :func:`grouped_pack_topk`'s packing: gate-weighted
    per-group combine of processed ``(E, G*C, D)`` slots back to
    ``(N, D)`` tokens."""
    e, _, d = out_tokens.shape
    return jnp.einsum(
        "gnec,egcd->gnd", combine, out_tokens.reshape(e, g, capacity, d)
    ).reshape(g * combine.shape[1], d)


def moe_ffn(params, x, *, capacity_factor: float = 2.0,
            num_selected: int = 1, group_size: int | None = None):
    """Top-k MoE FFN over tokens ``x`` (..., D) via one-hot dispatch.

    ``group_size`` routes tokens in independent groups of that size
    (GShard sec. 3.2: capacity and slot assignment are per group, so
    the one-hot dispatch/combine einsums cost 2*N*E*C_g*D with
    C_g ~ group_size*cf/E - LINEAR in N, where ungrouped dispatch's
    C ~ N*cf/E makes them quadratic).  ``None`` = one global group
    (exact-union drop semantics, the small-N default).  Gating and the
    load-balancing aux stay global either way - grouping only changes
    which assignments compete for capacity slots.
    """
    shape = x.shape
    d = shape[-1]
    xt = x.reshape(-1, d)
    n = xt.shape[0]
    e = params["w1"].shape[0]

    experts, probs, gates = _route_topk(params, xt, num_selected)
    aux = load_balancing_loss(gates, experts[:, 0], e)

    if group_size is None or group_size >= n:
        capacity = moe_capacity(n, e, capacity_factor, num_selected)
        dispatch, combine = make_dispatch_topk(experts, probs, e,
                                               capacity, xt.dtype)
        tokens = jnp.einsum("nec,nd->ecd", dispatch, xt)
        out = jnp.einsum("nec,ecd->nd", combine,
                         _expert_ffn(params, tokens))
        return out.reshape(shape), aux

    tokens, comb_g, g, capacity = grouped_pack_topk(
        xt, experts, probs, e, group_size, capacity_factor, num_selected)
    out = grouped_combine_topk(_expert_ffn(params, tokens), comb_g, g,
                               capacity)
    return out.reshape(shape), aux


def _route_expert_choice(params, xt, capacity: int):
    """Expert-choice selection AND combine weighting: returns
    ``(sel, combine)``, both (E, C, N) - each expert's top-``capacity``
    tokens as a one-hot and the same one-hot scaled by the gate
    affinity.  ONE definition shared by the dense path and the
    ep-sharded path (the :func:`moe_capacity` convention), so the two
    can never disagree on selection or weighting semantics."""
    n = xt.shape[0]
    logits = xt @ params["router"]["weight"].T + params["router"]["bias"]
    gates = jax.nn.softmax(logits, axis=-1)  # (N, E)
    vals, idx = jax.lax.top_k(gates.T, min(capacity, n))  # (E, C)
    sel = jax.nn.one_hot(idx, n, dtype=xt.dtype)  # (E, C, N)
    return sel, sel * vals[..., None].astype(xt.dtype)


def moe_ffn_expert_choice(params, x, *, capacity_factor: float = 2.0):
    """Expert-choice MoE FFN (Zhou et al. 2022): EXPERTS pick tokens.

    Token-choice (Switch/GShard above) lets each token pick its experts
    and drops overflow; expert-choice inverts it - each expert selects
    its top-C tokens by gate affinity, so every expert processes EXACTLY
    C tokens: perfect load balance by construction, no auxiliary loss
    (returned aux is 0.0 to keep the family's loss surface uniform).
    A token may be chosen by several experts (outputs sum, gate-weighted)
    or by none (passes through the caller's residual unchanged).

    C = ceil(tokens * capacity_factor / E).  All-dense formulation: the
    per-expert top-C becomes a (E, C, N) one-hot gather einsum, so
    dispatch/combine tile onto the MXU like the token-choice paths.
    """
    shape = x.shape
    d = shape[-1]
    xt = x.reshape(-1, d)
    n = xt.shape[0]
    e = params["w1"].shape[0]
    sel, combine = _route_expert_choice(
        params, xt, moe_capacity(n, e, capacity_factor))

    tokens = jnp.einsum("ecn,nd->ecd", sel, xt)
    out_tokens = _expert_ffn(params, tokens)
    out = jnp.einsum("ecn,ecd->nd", combine, out_tokens)
    return out.reshape(shape), jnp.float32(0.0)


def moe_ffn_dense(params, x, *, num_selected: int = 1):
    """Exact top-k MoE: every expert computes every token, the gates
    pick.  O(E) compute - the parity reference for the dispatched
    paths."""
    shape = x.shape
    d = shape[-1]
    xt = x.reshape(-1, d)
    e = params["w1"].shape[0]

    experts, probs, gates = _route_topk(params, xt, num_selected)
    h = jax.nn.gelu(
        jnp.einsum("nd,edh->neh", xt, params["w1"]) + params["b1"][None]
    )
    all_out = (
        jnp.einsum("neh,ehd->ned", h, params["w2"]) + params["b2"][None]
    )
    # (N, E) selection weights: sum of prob-weighted one-hots over the k
    # choices (distinct experts, so no double counting)
    sel = jnp.einsum(
        "nk,nke->ne", probs,
        jax.nn.one_hot(experts, e, dtype=xt.dtype),
    )
    out = jnp.einsum("ne,ned->nd", sel, all_out)
    # aux on the FIRST choice (Switch/GShard convention: the primary
    # assignment is what load balancing shapes)
    aux = load_balancing_loss(gates, experts[:, 0], e)
    return out.reshape(shape), aux
