from pytorch_distributed_rnn_tpu.ops.initializers import (
    lstm_uniform,
    linear_init,
    uniform_bound,
)
from pytorch_distributed_rnn_tpu.ops.losses import cross_entropy_loss, mse_loss
from pytorch_distributed_rnn_tpu.ops.attention import (
    mha_attention,
    ring_attention,
    ulysses_attention,
)

# NOTE: the fused kernels (ops.pallas_attention.flash_attention /
# ring_flash_attention, ops.pallas_rnn) are deliberately NOT re-exported
# here - importing them pulls jax.experimental.pallas, which the CPU/RNN
# startup path avoids; import from their modules directly.
from pytorch_distributed_rnn_tpu.ops.rnn import (
    init_gru_layer,
    init_lstm_layer,
    init_stacked_rnn,
    gru_layer,
    lstm_layer,
    stacked_rnn,
)

__all__ = [
    "lstm_uniform",
    "linear_init",
    "uniform_bound",
    "cross_entropy_loss",
    "mse_loss",
    "mha_attention",
    "ring_attention",
    "ulysses_attention",
    "init_gru_layer",
    "init_lstm_layer",
    "init_stacked_rnn",
    "gru_layer",
    "lstm_layer",
    "stacked_rnn",
]
