"""Flash attention as Pallas TPU kernels (the attention performance path).

The dense :func:`~pytorch_distributed_rnn_tpu.ops.attention.mha_attention`
materializes the full (Tq x Tk) score matrix in HBM - O(T^2) memory and an
HBM round-trip between the two matmuls.  This module fuses
QK^T -> online softmax -> (.)V into one kernel, the same treatment
``ops/pallas_rnn.py`` gives the RNN families' hot loop (SURVEY §2.8:
"custom Pallas kernels for the hot loop"; the reference itself has no
attention at all - long-context is a first-class new capability here).

Kernel layout (all three kernels share it):

- Arrays are flattened to ``(B*H, T, D)``; the grid is
  ``(B*H, outer blocks, inner blocks)``.  The TPU grid is sequential over
  the trailing dimension, so VMEM scratch carries the running
  online-softmax state (forward) or gradient accumulators (backward)
  across the inner block sweep, and Pallas double-buffers the next
  block's fetch automatically.
- Forward: for each Q block, sweep K/V blocks maintaining
  ``(m, l, acc)`` - running max, denominator, numerator - in f32 VMEM
  scratch.  Outputs the normalized block and its logsumexp row stats
  (saved for the backward).
- Backward splits into a dQ kernel (sweep K for fixed Q block) and a
  dK/dV kernel (sweep Q for fixed K block), both recomputing
  ``p = exp(s - lse)`` from the saved row stats instead of storing the
  (Tq x Tk) probability matrix - the standard flash backward.
- ``m``/``l``/``lse``/``delta`` row stats live lane-replicated as
  ``(block, 128)`` tiles (the (8, 128) f32 register tile has no cheap
  1-lane form on TPU).
- The global positions of the first query/key ride in as a (2,) int32
  SMEM scalar, so causal masking works on *traced* offsets - a ring
  shard's offset is ``lax.axis_index``, unknown at trace time.  Blocks
  entirely above the causal diagonal skip their compute via ``pl.when``.

:func:`ring_flash_attention` composes the same kernels into the
sequence-parallel ring (K/V blocks rotating via ``lax.ppermute``): the
forward merges each round's normalized block result through its
logsumexp, and a ring-level ``custom_vjp`` runs the flash backward as a
second ring pass in which dK/dV accumulators travel with their blocks.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_rnn_tpu.utils.compat import (
    pallas_tpu_compiler_params as _compiler_params,
)
from pytorch_distributed_rnn_tpu.ops.pallas_rnn import (
    _interpret,
    _round_up,
)

_LANES = 128
_NEG_INF = -jnp.inf


def resolve_attention_impl(impl: str) -> str:
    """``auto`` -> ``flash`` on TPU, ``dense`` elsewhere (interpret-mode
    flash on CPU is correct but far slower than XLA's fused dense path)."""
    if impl not in ("auto", "dense", "flash"):
        raise ValueError(f"unknown attention impl {impl!r}")
    if impl == "auto":
        return "flash" if jax.default_backend() == "tpu" else "dense"
    return impl


def _block_mask(qi, ki, q_off, k_off, *, block_q, block_k, t_q, t_k,
                causal):
    """(block_q, block_k) validity mask for one score block, or None when
    every entry is statically known valid (full block, no causal edge)."""
    need_kpad = t_k % block_k != 0
    need_qpad = t_q % block_q != 0
    if not (causal or need_kpad or need_qpad):
        return None
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = (q_pos < t_q) & (k_pos < t_k)
    if causal:
        mask &= (q_pos + q_off) >= (k_pos + k_off)
    return mask


def _causal_skip(qi, ki, q_off, k_off, *, block_q, block_k):
    """True when the whole block lies above the causal diagonal (no valid
    score) - its compute can be skipped entirely."""
    q_max = (qi + 1) * block_q - 1 + q_off
    k_min = ki * block_k + k_off
    return q_max < k_min


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr,
                *, scale, causal, t_q, t_k, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    q_off = offs_ref[0]
    k_off = offs_ref[1]

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    skip = (_causal_skip(qi, ki, q_off, k_off, block_q=block_q,
                         block_k=block_k) if causal else False)

    @pl.when(jnp.logical_not(skip))
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        mask = _block_mask(qi, ki, q_off, k_off, block_q=block_q,
                           block_k=block_k, t_q=t_q, t_k=t_k, causal=causal)
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if mask is not None:
            # fully-masked rows have s = m_new = -inf -> exp(nan); the
            # where() both zeroes masked entries and scrubs those nans
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        corr = jnp.where(jnp.isfinite(corr), corr, 0.0)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc_scr[:] * corr + jax.lax.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[:] = acc

    @pl.when(ki == nk - 1)
    def _():
        l = l_scr[:, :1]
        m = m_scr[:, :1]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l > 0, m + jnp.log(l_safe), _NEG_INF)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _scalar_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _fwd_impl(q, k, v, offsets, causal, block_q, block_k, t_q, t_k):
    """q: (BH, Tq, D) padded to block multiples; ``t_q``/``t_k`` are the
    actual (pre-padding) lengths the masks validate against; ``offsets``
    is a (2,) int32 [q_offset, k_offset] (may be traced).  Returns
    (o, lse) with lse lane-replicated (BH, Tq, 128) f32."""
    bh, t_q_pad, d = q.shape
    t_k_pad = k.shape[1]
    grid = (bh, t_q_pad // block_q, t_k_pad // block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=d ** -0.5, causal=causal,
        t_q=t_q, t_k=t_k, block_q=block_q, block_k=block_k,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _scalar_spec(),
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_q_pad, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t_q_pad, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(offsets, q, k, v)


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _recompute_p(q, k, lse, mask, scale):
    """p = exp(s - lse) with masked entries (and their inf/nan fallout
    from padded rows' lse = -inf) scrubbed to zero."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    p = jnp.exp(s - lse)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    return jnp.where(jnp.isfinite(p), p, 0.0)


def _dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr,
               *, scale, causal, t_q, t_k, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    q_off = offs_ref[0]
    k_off = offs_ref[1]

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    skip = (_causal_skip(qi, ki, q_off, k_off, block_q=block_q,
                         block_k=block_k) if causal else False)

    @pl.when(jnp.logical_not(skip))
    def _():
        mask = _block_mask(qi, ki, q_off, k_off, block_q=block_q,
                           block_k=block_k, t_q=t_q, t_k=t_k, causal=causal)
        p = _recompute_p(q_ref[0], k_ref[0], lse_ref[0][:, :1], mask, scale)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dq_scr[:] += jax.lax.dot(
            ds.astype(k_ref.dtype), k_ref[0],
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, t_q, t_k, block_q, block_k):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    q_off = offs_ref[0]
    k_off = offs_ref[1]

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    skip = (_causal_skip(qi, ki, q_off, k_off, block_q=block_q,
                         block_k=block_k) if causal else False)

    @pl.when(jnp.logical_not(skip))
    def _():
        mask = _block_mask(qi, ki, q_off, k_off, block_q=block_q,
                           block_k=block_k, t_q=t_q, t_k=t_k, causal=causal)
        p = _recompute_p(q_ref[0], k_ref[0], lse_ref[0][:, :1], mask, scale)
        do = do_ref[0]
        # dv += p^T @ do; dk += ds^T @ q - contract the block_q dim (0)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_impl(q, k, v, do, lse, delta, offsets, causal, block_q, block_k,
              t_q, t_k):
    bh, t_q_pad, d = q.shape
    t_k_pad = k.shape[1]
    common = dict(scale=d ** -0.5, causal=causal, t_q=t_q, t_k=t_k,
                  block_q=block_q, block_k=block_k)
    q_spec = pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0))
    k_spec = pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0))
    row_spec = pl.BlockSpec((1, block_q, _LANES),
                            lambda b, qi, ki: (b, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        grid=(bh, t_q_pad // block_q, t_k_pad // block_k),
        in_specs=[_scalar_spec(), q_spec, k_spec, k_spec, q_spec, row_spec,
                  row_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(offsets, q, k, v, do, lse, delta)[0]

    # swapped grid: outer = K blocks, inner sweep = Q blocks
    q_spec_t = pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0))
    k_spec_t = pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0))
    row_spec_t = pl.BlockSpec((1, block_q, _LANES),
                              lambda b, ki, qi: (b, qi, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **common),
        grid=(bh, t_k_pad // block_k, t_q_pad // block_q),
        in_specs=[_scalar_spec(), q_spec_t, k_spec_t, k_spec_t, q_spec_t,
                  row_spec_t, row_spec_t],
        out_specs=[k_spec_t, k_spec_t],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(offsets, q, k, v, do, lse, delta)
    return dq, dk, dv


def _delta_of(do, o):
    """delta = rowsum(do * o): cheap elementwise, fused by XLA; stored
    lane-replicated to match the kernels' row-stat layout."""
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    return jnp.broadcast_to(delta, (*delta.shape[:-1], _LANES))


# ---------------------------------------------------------------------------
# custom-VJP wrapper (single device / per shard)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, causal, q_offset, k_offset, block_q, block_k, t_q, t_k):
    offs = jnp.array([q_offset, k_offset], jnp.int32)
    o, _ = _fwd_impl(q, k, v, offs, causal, block_q, block_k, t_q, t_k)
    return o


def _flash_fwd(q, k, v, causal, q_offset, k_offset, block_q, block_k,
               t_q, t_k):
    offs = jnp.array([q_offset, k_offset], jnp.int32)
    o, lse = _fwd_impl(q, k, v, offs, causal, block_q, block_k, t_q, t_k)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, q_offset, k_offset, block_q, block_k, t_q, t_k,
               res, do):
    q, k, v, o, lse = res
    offs = jnp.array([q_offset, k_offset], jnp.int32)
    dq, dk, dv = _bwd_impl(q, k, v, do, lse, _delta_of(do, o), offs,
                           causal, block_q, block_k, t_q, t_k)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def _resolve_blocks(t_q, t_k, block_q, block_k):
    for name, blk in (("block_q", block_q), ("block_k", block_k)):
        if blk is not None and blk % _LANES:
            raise ValueError(f"{name} ({blk}) must be a multiple of "
                             f"{_LANES} (the TPU lane width)")
    block_q = min(block_q or 256, _round_up(t_q, _LANES))
    block_k = min(block_k or 256, _round_up(t_k, _LANES))
    return block_q, block_k


def _flatten_pad(x, t_pad):
    b, h, t, d = x.shape
    x = x.reshape(b * h, t, d)
    if t != t_pad:
        x = jnp.pad(x, ((0, 0), (0, t_pad - t), (0, 0)))
    return x


def flash_attention(q, k, v, *, causal: bool = False, q_offset: int = 0,
                    k_offset: int = 0, block_q: int | None = None,
                    block_k: int | None = None):
    """Fused flash attention, drop-in for
    :func:`~pytorch_distributed_rnn_tpu.ops.attention.mha_attention`.

    ``q``: (B, H, Tq, D), ``k``/``v``: (B, H, Tk, D) -> (B, H, Tq, D).
    ``q_offset``/``k_offset`` are static global positions of the first
    query/key so causal masking works on sequence chunks.  Differentiable
    via the flash backward (dQ + dK/dV kernels); O(T) memory - the score
    matrix never leaves VMEM.
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError("flash_attention wants (B, H, T, D) inputs, got "
                         f"{q.shape}/{k.shape}/{v.shape}")
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    block_q, block_k = _resolve_blocks(t_q, t_k, block_q, block_k)
    t_q_pad = _round_up(t_q, block_q)
    t_k_pad = _round_up(t_k, block_k)
    o = _flash(_flatten_pad(q, t_q_pad), _flatten_pad(k, t_k_pad),
               _flatten_pad(v, t_k_pad),
               causal, q_offset, k_offset, block_q, block_k, t_q, t_k)
    return o[:, :t_q].reshape(b, h, t_q, d)


# ---------------------------------------------------------------------------
# Ring composition (sequence parallelism, inside shard_map)
# ---------------------------------------------------------------------------


def _merge_partials(o_a, lse_a, o_b, lse_b):
    """Merge two normalized flash results through their logsumexps:
    o = (o_a e^{lse_a} + o_b e^{lse_b}) / (e^{lse_a} + e^{lse_b}).
    Operates in f32 - the ring keeps the running output in f32 across all
    rounds (matching ``ring_attention``'s f32 accumulator) and casts once
    at the end, so bf16 inputs do not compound per-round rounding."""
    m = jnp.maximum(lse_a, lse_b)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    w_a = jnp.where(jnp.isfinite(lse_a), jnp.exp(lse_a - m_safe), 0.0)
    w_b = jnp.where(jnp.isfinite(lse_b), jnp.exp(lse_b - m_safe), 0.0)
    denom = w_a + w_b
    lse = jnp.where(denom > 0, m_safe + jnp.log(jnp.where(denom > 0, denom,
                                                          1.0)), _NEG_INF)
    safe = jnp.where(denom > 0, denom, 1.0)
    o = (o_a * (w_a[:, :, :1] / safe[:, :, :1])
         + o_b * (w_b[:, :, :1] / safe[:, :, :1]))
    return o, lse


def _ring_fwd_impl(q, k, v, axis, causal, block_q, block_k, t_local):
    """q/k/v: (BH, t_pad, D) local chunks (already padded); returns the
    merged (o, lse) for the local queries."""
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def round_offs(r):
        src = (idx - r) % n
        return jnp.stack([idx * t_local, src * t_local]).astype(jnp.int32)

    o, lse = _fwd_impl(q, k, v, round_offs(0), causal, block_q, block_k,
                       t_local, t_local)
    o = o.astype(jnp.float32)

    def round_(carry, r):
        k_blk, v_blk, o, lse = carry
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        o_r, lse_r = _fwd_impl(q, k_blk, v_blk, round_offs(r), causal,
                               block_q, block_k, t_local, t_local)
        o, lse = _merge_partials(o, lse, o_r.astype(jnp.float32), lse_r)
        return (k_blk, v_blk, o, lse), None

    if n > 1:
        (_, _, o, lse), _ = lax.scan(round_, (k, v, o, lse),
                                     jnp.arange(1, n))
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis, causal, block_q, block_k, t_local):
    o, _ = _ring_fwd_impl(q, k, v, axis, causal, block_q, block_k, t_local)
    return o


def _ring_flash_fwd(q, k, v, axis, causal, block_q, block_k, t_local):
    o, lse = _ring_fwd_impl(q, k, v, axis, causal, block_q, block_k,
                            t_local)
    return o, (q, k, v, o, lse)


def _ring_flash_bwd(axis, causal, block_q, block_k, t_local, res, do):
    """Second ring pass: dK/dV accumulators travel with their K/V blocks
    (n ppermutes total per array), dQ accumulates locally; every round
    recomputes p against the *global* lse, which is exactly the global
    flash backward split blockwise."""
    q, k, v, o, lse = res
    delta = _delta_of(do, o)
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def round_offs(r):
        src = (idx - r) % n
        return jnp.stack([idx * t_local, src * t_local]).astype(jnp.int32)

    dq, dk, dv = _bwd_impl(q, k, v, do, lse, delta, round_offs(0), causal,
                           block_q, block_k, t_local, t_local)
    # accumulate in f32 across rounds (the same policy as the forward's
    # f32 merge): bf16 adds repeated n-1 times would compound rounding
    f32 = jnp.float32
    dq, dk, dv = dq.astype(f32), dk.astype(f32), dv.astype(f32)

    def round_(carry, r):
        k_blk, v_blk, dk_blk, dv_blk, dq = carry
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        dk_blk = lax.ppermute(dk_blk, axis, perm)
        dv_blk = lax.ppermute(dv_blk, axis, perm)
        dq_r, dk_r, dv_r = _bwd_impl(q, k_blk, v_blk, do, lse, delta,
                                     round_offs(r), causal,
                                     block_q, block_k, t_local, t_local)
        return (k_blk, v_blk, dk_blk + dk_r.astype(f32),
                dv_blk + dv_r.astype(f32), dq + dq_r.astype(f32)), None

    if n > 1:
        (_, _, dk, dv, dq), _ = lax.scan(round_, (k, v, dk, dv, dq),
                                         jnp.arange(1, n))
        # blocks sit one shard short of home after n-1 rotations
        dk = lax.ppermute(dk, axis, perm)
        dv = lax.ppermute(dv, axis, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(q, k, v, axis: str, *, causal: bool = False,
                         block_q: int | None = None,
                         block_k: int | None = None):
    """Ring attention with the flash kernel as the per-shard inner step,
    for use inside ``shard_map`` - fused drop-in for
    :func:`~pytorch_distributed_rnn_tpu.ops.attention.ring_attention`.

    ``q``/``k``/``v``: this shard's (B, H, T/S, D) chunk, sharded on
    global time along mesh axis ``axis``.  K/V blocks rotate around the
    ring via ``lax.ppermute``; each round runs the fused kernel against
    the visiting block and folds the result in through its logsumexp.
    """
    b, h, t_local, d = q.shape
    block_q, block_k = _resolve_blocks(t_local, t_local, block_q, block_k)
    # Q and K share t_local in the ring, so one padded length must tile
    # by BOTH block sizes - max() would silently drop tail K blocks for
    # mismatched explicit blocks (e.g. 384/256 at t=300)
    t_pad = _round_up(t_local, math.lcm(block_q, block_k))
    o = _ring_flash(_flatten_pad(q, t_pad), _flatten_pad(k, t_pad),
                    _flatten_pad(v, t_pad),
                    axis, causal, block_q, block_k, t_local)
    return o[:, :t_local].reshape(b, h, t_local, d)
