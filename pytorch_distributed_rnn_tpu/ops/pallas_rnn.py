"""Fused LSTM scan as Pallas TPU kernels (the performance path).

The ``lax.scan`` LSTM in ``ops/rnn.py`` is correct and portable, but each
timestep is its own XLA loop iteration: the tiny recurrent matmul
``(B, H) @ (H, 4H)`` plus gate math pays per-step loop/fusion overhead 128
times per layer.  For the reference workload (H=32 - the motion model,
``/root/reference/src/motion/model.py:9-16``) that overhead dominates the
actual FLOPs.

This module fuses the *entire* time loop into one Pallas kernel:

- Grid ``(batch_tiles, T)``.  The TPU grid is sequential, so VMEM scratch
  persists across grid steps: ``h``/``c`` live in scratch for all T steps of
  a batch tile, and Pallas double-buffers the per-step ``x_proj`` block
  fetch automatically.
- The input projection for all timesteps is still one big MXU matmul
  *outside* the kernel (same trick as the scan path); the kernel only does
  the serial part: ``gates = x_proj[t] + h @ w_hh^T`` and the gate math.
- Backward is a second kernel running the grid in reverse time order,
  carrying ``dh``/``dc`` in scratch and accumulating ``dw_hh`` in a VMEM
  accumulator across the whole grid, wired up via ``jax.custom_vjp``
  (Pallas kernels are not auto-differentiable).

Layouts are time-major ``(T, B, ...)`` inside the fused region so each
block's trailing two dims ``(block_b, 4H)`` align with the (8, 128) f32
tile.  Weight layout and gate order (i, f, g, o) follow torch exactly like
the scan path, so both implementations are interchangeable and parity-tested
against each other and against torch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    """Pallas interpret mode off-TPU so the CPU test mesh runs the kernels."""
    return jax.default_backend() != "tpu"


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# Mosaic's default per-kernel scoped-VMEM budget is 16MB.  The backward
# kernel is the fat one, and its footprint is dominated NOT by the block
# windows but by the f32 stack temporaries the kernel body materializes -
# gates, the four split views, the d_gates concat - each (block_b, 4H)
# regardless of the input dtype.  Model calibrated against real-v5e
# compiler measurements at H=512 (run-chip char row, r3):
#   f32  block 256 -> 17.26MB measured (overflow);  block 128 runs
#   bf16 block 512 -> 25.25MB measured (overflow);  block 256 runs
# The terms below bracket all four points under a 13MB budget.
_VMEM_BUDGET = 13 * 1024 * 1024


def _bwd_vmem_bytes(block_b: int, hidden: int, itemsize: int) -> int:
    weights = 4 * hidden * hidden * itemsize   # the (H, 4H) block
    stack = 64 * hidden * block_b              # f32 (block_b, 4H) temporaries
    streamed = 6 * hidden * block_b * itemsize  # time-indexed windows
    return weights + stack + streamed


def _pick_block_b(batch: int, hidden: int = 32, itemsize: int = 4) -> int:
    """Batch tile: large enough to keep the MXU/VPU busy, small enough that
    the backward kernel's working set fits the scoped-VMEM budget.  When
    the VMEM cap does not bind, tiles waste at most 7 padded rows (e.g.
    1440 -> 3 tiles of 480, not 3 tiles of 512); when it does, the tile
    count rises and padding can exceed that (1440 at H=512 f32 -> 7 tiles
    of 208 = 16 padded rows)."""
    cap = 512
    while cap > 8 and _bwd_vmem_bytes(cap, hidden, itemsize) > _VMEM_BUDGET:
        cap -= 8
    if _bwd_vmem_bytes(cap, hidden, itemsize) > _VMEM_BUDGET and not _interpret():
        # No tile fits (the resident weight block alone can exceed the
        # budget, e.g. H=1024 f32 = 16.78MB): the kernel would die in the
        # Mosaic compiler with a scoped-VMEM overflow, so fail with a
        # actionable message instead.  Interpret mode (CPU tests) has no
        # such limit and keeps working at any H.
        raise ValueError(
            f"fused RNN backward cannot fit scoped VMEM at hidden={hidden} "
            f"itemsize={itemsize} (weights block alone "
            f"{4 * hidden * hidden * itemsize / 2**20:.1f}MB); "
            "use impl='scan' for this size"
        )
    num_tiles = -(-batch // cap)
    return min(cap, _round_up(-(-batch // num_tiles), 8))


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _lstm_fwd_kernel(x_proj_ref, h0_ref, c0_ref, w_hh_t_ref,
                     h_all_ref, c_all_ref, h_scr, c_scr):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h = h_scr[:]
    c = c_scr[:]
    gates = x_proj_ref[0] + jnp.dot(
        h, w_hh_t_ref[:], preferred_element_type=jnp.float32
    )
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c = f * c + i * g
    h = o * jnp.tanh(c)
    h_scr[:] = h
    c_scr[:] = c
    h_all_ref[0] = h.astype(h_all_ref.dtype)
    c_all_ref[0] = c.astype(c_all_ref.dtype)


def _lstm_fwd_pallas(x_proj, h0, c0, w_hh_t, *, block_b):
    """x_proj: (T, Bp, 4H) time-major; returns h_all, c_all (T, Bp, H)."""
    seq_len, batch_p, gate_dim = x_proj.shape
    hidden = gate_dim // 4
    nb = batch_p // block_b
    grid = (nb, seq_len)
    dtype = x_proj.dtype

    h_all, c_all = pl.pallas_call(
        _lstm_fwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_b, gate_dim), lambda b, t: (t, b, 0)),
            pl.BlockSpec((block_b, hidden), lambda b, t: (b, 0)),
            pl.BlockSpec((block_b, hidden), lambda b, t: (b, 0)),
            pl.BlockSpec((hidden, gate_dim), lambda b, t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_b, hidden), lambda b, t: (t, b, 0)),
            pl.BlockSpec((1, block_b, hidden), lambda b, t: (t, b, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((seq_len, batch_p, hidden), dtype),
            jax.ShapeDtypeStruct((seq_len, batch_p, hidden), dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_b, hidden), jnp.float32),
            pltpu.VMEM((block_b, hidden), jnp.float32),
        ],
        interpret=_interpret(),
    )(x_proj, h0, c0, w_hh_t)
    return h_all, c_all


# ---------------------------------------------------------------------------
# Backward kernel (reverse time order)
# ---------------------------------------------------------------------------


def _lstm_bwd_kernel(x_proj_ref, h_prev_ref, c_prev_ref, c_t_ref,
                     dh_all_ref, dh_T_ref, dc_T_ref, w_hh_t_ref,
                     h0_ref, c0_ref,
                     dx_proj_ref, dh0_ref, dc0_ref,
                     dh_scr, dc_scr):
    """Reverse-time sweep; the weight grad is NOT accumulated here - a
    (4H, H) f32 VMEM accumulator is 26MB at H=1280, over the scoped-vmem
    limit.  Like the GRU backward, the kernel emits per-step gate
    cotangents (``dx_proj`` doubles as them) and the wrapper forms
    ``dw_hh`` with one big MXU matmul outside - better tiling anyway."""
    t = pl.program_id(1)
    seq_len = pl.num_programs(1)
    tt_is_first = t == 0          # tt == T-1: start of backward sweep
    tt_is_last = t == seq_len - 1  # tt == 0: end of backward sweep

    @pl.when(tt_is_first)
    def _():
        dh_scr[:] = dh_T_ref[:].astype(jnp.float32)
        dc_scr[:] = dc_T_ref[:].astype(jnp.float32)

    # At tt == 0 the "previous" state is the initial carry, not a saved step.
    h_prev = jnp.where(tt_is_last, h0_ref[:], h_prev_ref[0])
    c_prev = jnp.where(tt_is_last, c0_ref[:], c_prev_ref[0])

    # Recompute the gates for this step (cheaper than saving 4H activations).
    gates = x_proj_ref[0] + jnp.dot(
        h_prev, w_hh_t_ref[:], preferred_element_type=jnp.float32
    )
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)

    dh = dh_scr[:] + dh_all_ref[0]
    dc = dc_scr[:]

    tanh_c = jnp.tanh(c_t_ref[0])
    do = dh * tanh_c
    dc = dc + dh * o * (1.0 - tanh_c * tanh_c)
    di = dc * g
    df = dc * c_prev
    dg = dc * i

    d_gates = jnp.concatenate(
        [
            di * i * (1.0 - i),
            df * f * (1.0 - f),
            dg * (1.0 - g * g),
            do * o * (1.0 - o),
        ],
        axis=-1,
    )

    dx_proj_ref[0] = d_gates.astype(dx_proj_ref.dtype)

    # d_gates @ w_hh_t^T via transposed contraction dims: reusing the SAME
    # (H, 4H) block the gate recompute reads keeps ONE weight array in
    # VMEM.  Shipping a second pre-transposed (4H, H) copy doubled the
    # resident weight footprint (both blocks double-buffered: 16MB at
    # H=512 f32) and overflowed the 16MB scoped-VMEM limit on real v5e.
    dh_prev = jax.lax.dot_general(
        d_gates, w_hh_t_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dc_prev = dc * f
    dh_scr[:] = dh_prev
    dc_scr[:] = dc_prev

    @pl.when(tt_is_last)
    def _():
        dh0_ref[:] = dh_prev.astype(dh0_ref.dtype)
        dc0_ref[:] = dc_prev.astype(dc0_ref.dtype)


def _lstm_bwd_pallas(x_proj, h_all, c_all, h0, c0, w_hh_t,
                     dh_all, dh_T, dc_T, *, block_b):
    seq_len, batch_p, gate_dim = x_proj.shape
    hidden = gate_dim // 4
    nb = batch_p // block_b
    grid = (nb, seq_len)
    dtype = x_proj.dtype

    rev = lambda b, t: (seq_len - 1 - t, b, 0)        # noqa: E731
    rev_prev = lambda b, t: (                          # noqa: E731
        jnp.maximum(seq_len - 2 - t, 0), b, 0)

    dx_proj, dh0, dc0 = pl.pallas_call(
        _lstm_bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_b, gate_dim), rev),       # x_proj[tt]
            pl.BlockSpec((1, block_b, hidden), rev_prev),    # h_all[tt-1]
            pl.BlockSpec((1, block_b, hidden), rev_prev),    # c_all[tt-1]
            pl.BlockSpec((1, block_b, hidden), rev),         # c_all[tt]
            pl.BlockSpec((1, block_b, hidden), rev),         # dh_all[tt]
            pl.BlockSpec((block_b, hidden), lambda b, t: (b, 0)),  # dh_T
            pl.BlockSpec((block_b, hidden), lambda b, t: (b, 0)),  # dc_T
            pl.BlockSpec((hidden, gate_dim), lambda b, t: (0, 0)),
            pl.BlockSpec((block_b, hidden), lambda b, t: (b, 0)),  # h0
            pl.BlockSpec((block_b, hidden), lambda b, t: (b, 0)),  # c0
        ],
        out_specs=[
            pl.BlockSpec((1, block_b, gate_dim), rev),
            pl.BlockSpec((block_b, hidden), lambda b, t: (b, 0)),
            pl.BlockSpec((block_b, hidden), lambda b, t: (b, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((seq_len, batch_p, gate_dim), dtype),
            jax.ShapeDtypeStruct((batch_p, hidden), dtype),
            jax.ShapeDtypeStruct((batch_p, hidden), dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_b, hidden), jnp.float32),
            pltpu.VMEM((block_b, hidden), jnp.float32),
        ],
        interpret=_interpret(),
    )(x_proj, h_all, c_all, c_all, dh_all, dh_T, dc_T, w_hh_t, h0, c0)
    return dx_proj, dh0, dc0


# ---------------------------------------------------------------------------
# custom_vjp wrapper: differentiable fused scan
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_lstm_scan(x_proj, w_hh_t, h0, c0, block_b):
    """Fused LSTM time loop.

    Args: ``x_proj`` (T, Bp, 4H) with both biases folded in, ``w_hh_t``
    (H, 4H), ``h0``/``c0`` (Bp, H); ``Bp`` must be a multiple of
    ``block_b``.  Returns ``(h_all (T, Bp, H), (h_T, c_T))``.
    """
    h_all, c_all = _lstm_fwd_pallas(x_proj, h0, c0, w_hh_t, block_b=block_b)
    return h_all, (h_all[-1], c_all[-1])


def _fused_fwd(x_proj, w_hh_t, h0, c0, block_b):
    h_all, c_all = _lstm_fwd_pallas(x_proj, h0, c0, w_hh_t, block_b=block_b)
    out = (h_all, (h_all[-1], c_all[-1]))
    return out, (x_proj, h_all, c_all, h0, c0, w_hh_t)


def _fused_bwd(block_b, residuals, cotangents):
    x_proj, h_all, c_all, h0, c0, w_hh_t = residuals
    dh_all, (dh_T, dc_T) = cotangents
    dx_proj, dh0, dc0 = _lstm_bwd_pallas(
        x_proj, h_all, c_all, h0, c0, w_hh_t,
        dh_all, dh_T, dc_T, block_b=block_b,
    )
    # weight grad as one big MXU matmul over all (t, b) at once: for the
    # LSTM the emitted gate cotangents ARE dx_proj, so
    # dw_hh = sum_t d_gates[t]^T h_prev[t]  ->  (4H, H), f32 accumulate
    h_prev_all = jnp.concatenate([h0[None], h_all[:-1]], axis=0)
    dw_hh = jnp.einsum(
        "tbg,tbh->gh", dx_proj, h_prev_all,
        preferred_element_type=jnp.float32,
    ).astype(x_proj.dtype)
    return dx_proj, dw_hh.T, dh0, dc0


fused_lstm_scan.defvjp(_fused_fwd, _fused_bwd)


# ---------------------------------------------------------------------------
# Layer API (drop-in for ops.rnn.lstm_layer)
# ---------------------------------------------------------------------------


def lstm_layer_fused(params, x, h0=None, c0=None, *, block_b=None):
    """Drop-in replacement for ``ops.rnn.lstm_layer`` running the time loop
    as a fused Pallas kernel.  Same params (torch layout), same results.
    """
    batch, _, _ = x.shape
    hidden = params["w_hh"].shape[1]
    dtype = x.dtype

    if block_b is None:
        block_b = _pick_block_b(batch, hidden, jnp.dtype(dtype).itemsize)
    batch_p = _round_up(max(batch, block_b), block_b)

    from pytorch_distributed_rnn_tpu.ops.rnn import lstm_input_proj

    # to time-major after the shared one-big-matmul input projection
    x_proj = jnp.swapaxes(lstm_input_proj(params, x), 0, 1)  # (T, B, 4H)
    if batch_p != batch:
        x_proj = jnp.pad(x_proj, ((0, 0), (0, batch_p - batch), (0, 0)))

    if h0 is None:
        h0 = jnp.zeros((batch, hidden), dtype)
    if c0 is None:
        c0 = jnp.zeros((batch, hidden), dtype)
    if batch_p != batch:
        h0 = jnp.pad(h0, ((0, batch_p - batch), (0, 0)))
        c0 = jnp.pad(c0, ((0, batch_p - batch), (0, 0)))

    h_all, (h_T, c_T) = fused_lstm_scan(
        x_proj, params["w_hh"].T, h0, c0, block_b
    )
    outputs = jnp.swapaxes(h_all, 0, 1)[:batch]
    return outputs, (h_T[:batch], c_T[:batch])


# ---------------------------------------------------------------------------
# GRU: fused forward + backward kernels
# ---------------------------------------------------------------------------


def _gru_fwd_kernel(x_proj_ref, h0_ref, w_hh_t_ref, b_hh_ref, h_all_ref,
                    h_scr):
    """One grid step = one timestep of one batch tile.  Unlike the LSTM,
    the hidden-side bias CANNOT fold into ``x_proj``: torch GRU semantics
    put ``b_hn`` inside the ``r *`` product, so ``h_proj`` carries it."""
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:].astype(jnp.float32)

    h = h_scr[:]
    h_proj = jnp.dot(
        h, w_hh_t_ref[:], preferred_element_type=jnp.float32
    ) + b_hh_ref[:]
    xr, xz, xn = jnp.split(x_proj_ref[0], 3, axis=-1)
    hr, hz, hn = jnp.split(h_proj, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    h = (1.0 - z) * n + z * h
    h_scr[:] = h
    h_all_ref[0] = h.astype(h_all_ref.dtype)


def _gru_fwd_pallas(x_proj, h0, w_hh_t, b_hh, *, block_b):
    seq_len, batch_p, gate_dim = x_proj.shape
    hidden = gate_dim // 3
    grid = (batch_p // block_b, seq_len)
    dtype = x_proj.dtype

    return pl.pallas_call(
        _gru_fwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_b, gate_dim), lambda b, t: (t, b, 0)),
            pl.BlockSpec((block_b, hidden), lambda b, t: (b, 0)),
            pl.BlockSpec((hidden, gate_dim), lambda b, t: (0, 0)),
            pl.BlockSpec((1, gate_dim), lambda b, t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_b, hidden), lambda b, t: (t, b, 0)),
        out_shape=jax.ShapeDtypeStruct((seq_len, batch_p, hidden), dtype),
        scratch_shapes=[pltpu.VMEM((block_b, hidden), jnp.float32)],
        interpret=_interpret(),
    )(x_proj, h0, w_hh_t, b_hh)


def _gru_bwd_kernel(x_proj_ref, h_prev_ref, dh_all_ref, dh_T_ref,
                    w_hh_t_ref, b_hh_ref, h0_ref,
                    dx_proj_ref, dhgates_ref, dh0_ref, dh_scr):
    """Reverse-time sweep; weight/bias grads are NOT accumulated here -
    the kernel emits per-step hidden-side gate cotangents (``dhgates``)
    and the wrapper turns them into ``dw_hh``/``db_hh`` with one big MXU
    matmul outside (better tiling than a VMEM accumulator)."""
    t = pl.program_id(1)
    seq_len = pl.num_programs(1)
    tt_is_first = t == 0           # tt == T-1
    tt_is_last = t == seq_len - 1  # tt == 0

    @pl.when(tt_is_first)
    def _():
        dh_scr[:] = dh_T_ref[:].astype(jnp.float32)

    h_prev = jnp.where(tt_is_last, h0_ref[:], h_prev_ref[0]).astype(
        jnp.float32
    )
    # recompute this step's gates (cheaper than saving 3H activations)
    h_proj = jnp.dot(
        h_prev, w_hh_t_ref[:], preferred_element_type=jnp.float32
    ) + b_hh_ref[:]
    xr, xz, xn = jnp.split(x_proj_ref[0], 3, axis=-1)
    hr, hz, hn = jnp.split(h_proj, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)

    dh = dh_scr[:] + dh_all_ref[0]
    dz = dh * (h_prev - n)
    dn = dh * (1.0 - z)
    dn_pre = dn * (1.0 - n * n)
    dr = dn_pre * hn
    dz_pre = dz * z * (1.0 - z)
    dr_pre = dr * r * (1.0 - r)

    d_xgates = jnp.concatenate([dr_pre, dz_pre, dn_pre], axis=-1)
    d_hgates = jnp.concatenate([dr_pre, dz_pre, dn_pre * r], axis=-1)
    dx_proj_ref[0] = d_xgates.astype(dx_proj_ref.dtype)
    dhgates_ref[0] = d_hgates.astype(dhgates_ref.dtype)

    # d_hgates @ w_hh_t^T via transposed contraction dims - one resident
    # weight array instead of two (see the LSTM backward note)
    dh_prev = dh * z + jax.lax.dot_general(
        d_hgates, w_hh_t_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dh_scr[:] = dh_prev

    @pl.when(tt_is_last)
    def _():
        dh0_ref[:] = dh_prev.astype(dh0_ref.dtype)


def _gru_bwd_pallas(x_proj, h_all, h0, w_hh_t, b_hh, dh_all, dh_T, *,
                    block_b):
    seq_len, batch_p, gate_dim = x_proj.shape
    hidden = gate_dim // 3
    grid = (batch_p // block_b, seq_len)
    dtype = x_proj.dtype

    rev = lambda b, t: (seq_len - 1 - t, b, 0)        # noqa: E731
    rev_prev = lambda b, t: (                          # noqa: E731
        jnp.maximum(seq_len - 2 - t, 0), b, 0)

    return pl.pallas_call(
        _gru_bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_b, gate_dim), rev),       # x_proj[tt]
            pl.BlockSpec((1, block_b, hidden), rev_prev),    # h_all[tt-1]
            pl.BlockSpec((1, block_b, hidden), rev),         # dh_all[tt]
            pl.BlockSpec((block_b, hidden), lambda b, t: (b, 0)),
            pl.BlockSpec((hidden, gate_dim), lambda b, t: (0, 0)),
            pl.BlockSpec((1, gate_dim), lambda b, t: (0, 0)),
            pl.BlockSpec((block_b, hidden), lambda b, t: (b, 0)),  # h0
        ],
        out_specs=[
            pl.BlockSpec((1, block_b, gate_dim), rev),
            pl.BlockSpec((1, block_b, gate_dim), rev),
            pl.BlockSpec((block_b, hidden), lambda b, t: (b, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((seq_len, batch_p, gate_dim), dtype),
            jax.ShapeDtypeStruct((seq_len, batch_p, gate_dim), dtype),
            jax.ShapeDtypeStruct((batch_p, hidden), dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_b, hidden), jnp.float32)],
        interpret=_interpret(),
    )(x_proj, h_all, dh_all, dh_T, w_hh_t, b_hh, h0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_gru_scan(x_proj, w_hh_t, b_hh, h0, block_b):
    """Fused GRU time loop.  ``x_proj`` (T, Bp, 3H) carries the input
    projection + b_ih only (b_hh stays separate - GRU semantics);
    ``b_hh`` is (1, 3H).  Returns ``(h_all (T, Bp, H), h_T)``."""
    h_all = _gru_fwd_pallas(x_proj, h0, w_hh_t, b_hh, block_b=block_b)
    return h_all, h_all[-1]


def _gru_fwd(x_proj, w_hh_t, b_hh, h0, block_b):
    h_all = _gru_fwd_pallas(x_proj, h0, w_hh_t, b_hh, block_b=block_b)
    return (h_all, h_all[-1]), (x_proj, h_all, h0, w_hh_t, b_hh)


def _gru_bwd(block_b, residuals, cotangents):
    x_proj, h_all, h0, w_hh_t, b_hh = residuals
    dh_all, dh_T = cotangents
    dx_proj, dhgates, dh0 = _gru_bwd_pallas(
        x_proj, h_all, h0, w_hh_t, b_hh, dh_all, dh_T, block_b=block_b
    )
    # weight/bias grads as big MXU matmuls over all (t, b) at once
    h_prev_all = jnp.concatenate([h0[None], h_all[:-1]], axis=0)
    dw_hh = jnp.einsum("tbg,tbh->gh", dhgates, h_prev_all)  # (3H, H)
    db_hh = jnp.sum(dhgates, axis=(0, 1))[None]             # (1, 3H)
    return dx_proj, dw_hh.T, db_hh, dh0


fused_gru_scan.defvjp(_gru_fwd, _gru_bwd)


def gru_layer_fused(params, x, h0=None, *, block_b=None):
    """Drop-in replacement for ``ops.rnn.gru_layer`` running the time loop
    as a fused Pallas kernel.  Same params (torch layout, gate order
    r, z, n), same results."""
    batch, _, _ = x.shape
    hidden = params["w_hh"].shape[1]
    dtype = x.dtype

    if block_b is None:
        # the LSTM (4H-wide, fatter) VMEM model bounds the GRU's 3H one
        block_b = _pick_block_b(batch, hidden, jnp.dtype(dtype).itemsize)
    batch_p = _round_up(max(batch, block_b), block_b)

    from pytorch_distributed_rnn_tpu.ops.rnn import gru_input_proj

    # shared input projection (b_ih only; b_hh joins inside the kernel)
    x_proj = jnp.swapaxes(gru_input_proj(params, x), 0, 1)  # (T, B, 3H)
    if batch_p != batch:
        x_proj = jnp.pad(x_proj, ((0, 0), (0, batch_p - batch), (0, 0)))

    if h0 is None:
        h0 = jnp.zeros((batch, hidden), dtype)
    if batch_p != batch:
        h0 = jnp.pad(h0, ((0, batch_p - batch), (0, 0)))

    h_all, h_T = fused_gru_scan(
        x_proj, params["w_hh"].T, params["b_hh"][None], h0, block_b
    )
    return jnp.swapaxes(h_all, 0, 1)[:batch], h_T[:batch]
