"""Toy MLP used by the distributed smoke-test examples.

Capability parity with the reference ``ToyModel``
(``/root/reference/src/example/example_ddp.py:11-19``): Linear(10,10) ->
ReLU -> Linear(10,5), trained with MSE + SGD in the examples, used to check
that every rank ends with identical parameters.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax

from pytorch_distributed_rnn_tpu.ops.initializers import linear_init


@dataclass(frozen=True)
class ToyModel:
    in_dim: int = 10
    hidden_dim: int = 10
    out_dim: int = 5

    def init(self, key: jax.Array):
        k1, k2 = jax.random.split(key)
        return {
            "net1": linear_init(k1, self.in_dim, self.hidden_dim),
            "net2": linear_init(k2, self.hidden_dim, self.out_dim),
        }

    def apply(self, params, x: jax.Array) -> jax.Array:
        h = jax.nn.relu(x @ params["net1"]["weight"].T + params["net1"]["bias"])
        return h @ params["net2"]["weight"].T + params["net2"]["bias"]
