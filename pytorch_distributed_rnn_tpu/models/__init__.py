from pytorch_distributed_rnn_tpu.models.attention import AttentionClassifier
from pytorch_distributed_rnn_tpu.models.attention_lm import AttentionLM
from pytorch_distributed_rnn_tpu.models.char_rnn import (
    CharRNN,
    char_rnn_50m,
    num_params,
)
from pytorch_distributed_rnn_tpu.models.moe import MoEClassifier
from pytorch_distributed_rnn_tpu.models.moe_lm import MoELM
from pytorch_distributed_rnn_tpu.models.motion import MotionModel
from pytorch_distributed_rnn_tpu.models.toy import ToyModel

__all__ = [
    "AttentionClassifier",
    "AttentionLM",
    "CharRNN",
    "char_rnn_50m",
    "num_params",
    "MoEClassifier",
    "MoELM",
    "MotionModel",
    "ToyModel",
]
