from pytorch_distributed_rnn_tpu.models.attention import AttentionClassifier
from pytorch_distributed_rnn_tpu.models.motion import MotionModel
from pytorch_distributed_rnn_tpu.models.toy import ToyModel

__all__ = ["AttentionClassifier", "MotionModel", "ToyModel"]
