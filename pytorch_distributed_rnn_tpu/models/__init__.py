from pytorch_distributed_rnn_tpu.models.attention import AttentionClassifier
from pytorch_distributed_rnn_tpu.models.char_rnn import (
    CharRNN,
    char_rnn_50m,
    num_params,
)
from pytorch_distributed_rnn_tpu.models.moe import MoEClassifier
from pytorch_distributed_rnn_tpu.models.motion import MotionModel
from pytorch_distributed_rnn_tpu.models.toy import ToyModel

__all__ = [
    "AttentionClassifier",
    "CharRNN",
    "char_rnn_50m",
    "num_params",
    "MoEClassifier",
    "MotionModel",
    "ToyModel",
]
