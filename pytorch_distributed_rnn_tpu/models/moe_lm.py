"""MoE language model: the MoE family's LM adapter.

The MoE family's existing model is a sequence CLASSIFIER
(``models/moe.py``) with no token head, so - like
``models/attention_lm.py`` for the attention family - this module is
the family's thin generation adapter: the char-RNN shape (embedding ->
stacked LSTM/GRU -> per-timestep head) with the classifier's residual
Switch-style MoE FFN (``ops/moe.py::moe_ffn_dense``, the dense-exact
numerics reference) applied to EVERY timestep's hidden state before the
vocab projection.

Only token-choice routing is exposed: dense token-choice routes each
token independently of every other token in the batch, which is the
property continuous batching rests on - a request decoded inside a
mixed batch routes exactly as it would alone.  Expert-choice selection
is global over the token set the router sees (``models/moe.py``
docstring), so an EC decode would change with its batch neighbours;
the constructor rejects it loudly.

Decode is bounded-buffer: RNN carries only, one
``stacked_rnn_decode_step`` + MoE FFN + head per token (shared with
``serving/adapters.py`` via :func:`moe_lm_decode_tail`).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from pytorch_distributed_rnn_tpu.ops.initializers import linear_init
from pytorch_distributed_rnn_tpu.ops.moe import init_moe_ffn, moe_ffn_dense
from pytorch_distributed_rnn_tpu.ops.rnn import (
    head_logits,
    init_stacked_rnn,
    stacked_rnn,
    stacked_rnn_decode_step,
)


def moe_lm_decode_tail(params, h_top, num_selected: int):
    """Residual MoE + vocab head for ONE decode step's hidden state:
    ``h_top (B, H) -> logits (B, vocab)``.  The single definition shared
    by :meth:`MoELM.generate` and the serving adapter - dense
    token-choice routing is per-token, so the (B, 1, H) call routes each
    slot exactly as the full-sequence pass routes that position."""
    moe_out, _ = moe_ffn_dense(
        params["moe"], h_top[:, None, :], num_selected=num_selected
    )
    return head_logits(params["head"], h_top + moe_out[:, 0])


@dataclass(frozen=True)
class MoELM:
    """``params = model.init(key)``; ``logits = model.apply(params,
    tokens)`` maps (B, T) int tokens -> (B, T, vocab) next-token logits
    through an RNN backbone + residual dense-MoE FFN."""

    vocab_size: int = 256
    embed_dim: int = 64
    hidden_dim: int = 128
    layer_dim: int = 2
    num_experts: int = 4
    num_selected: int = 1
    expert_hidden: int | None = None  # default 2 * hidden_dim
    aux_weight: float = 0.01
    cell: str = "lstm"

    def __post_init__(self):
        if not 1 <= self.num_selected <= self.num_experts:
            raise ValueError(
                f"num_selected {self.num_selected} needs at least that "
                f"many experts (num_experts {self.num_experts})"
            )

    @property
    def _expert_hidden(self) -> int:
        return self.expert_hidden or 2 * self.hidden_dim

    def init(self, key: jax.Array):
        k_embed, k_rnn, k_moe, k_head = jax.random.split(key, 4)
        scale = self.embed_dim ** -0.5
        return {
            "embed": jax.random.normal(
                k_embed, (self.vocab_size, self.embed_dim)) * scale,
            "rnn": init_stacked_rnn(
                k_rnn, self.embed_dim, self.hidden_dim, self.layer_dim,
                self.cell,
            ),
            "moe": init_moe_ffn(
                k_moe, self.hidden_dim, self.num_experts,
                self._expert_hidden,
            ),
            "head": linear_init(k_head, self.hidden_dim, self.vocab_size),
        }

    def apply_with_aux(self, params, tokens: jax.Array, dropout_key=None):
        """(logits (B, T, vocab), aux scalar load-balancing loss)."""
        x = params["embed"][tokens]
        out, _ = stacked_rnn(params["rnn"], x, self.cell, impl="scan")
        moe_out, aux = moe_ffn_dense(
            params["moe"], out, num_selected=self.num_selected
        )
        return head_logits(params["head"], out + moe_out), aux

    def apply(self, params, tokens: jax.Array, dropout_key=None) -> jax.Array:
        return self.apply_with_aux(params, tokens)[0]

    def loss(self, params, tokens: jax.Array, dropout_key=None) -> jax.Array:
        """Next-token cross entropy + weighted aux loss."""
        from pytorch_distributed_rnn_tpu.ops.losses import cross_entropy_loss

        logits, aux = self.apply_with_aux(params, tokens[:, :-1])
        targets = tokens[:, 1:]
        ce = cross_entropy_loss(
            logits.reshape(-1, self.vocab_size), targets.reshape(-1)
        )
        return ce + self.aux_weight * aux

    def generate(self, params, prompt: jax.Array, length: int,
                 key: jax.Array | None = None,
                 temperature: float = 1.0) -> jax.Array:
        """The char-RNN bounded-buffer generation contract:
        ``prompt (B, Tp) int32 -> (B, Tp + length)`` - batched backbone
        prefill, then a ``lax.scan`` of shared single-token decode steps
        (``stacked_rnn_decode_step`` + :func:`moe_lm_decode_tail`)."""
        if temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if prompt.ndim != 2 or prompt.shape[1] < 1:
            raise ValueError(
                "prompt must be (batch, >=1 tokens); an empty prompt has "
                "no last-step logits to seed decoding"
            )
        greedy = temperature == 0.0
        if key is None:
            if not greedy:
                raise ValueError("sampling (temperature > 0) needs a key")
            key = jax.random.PRNGKey(0)  # unused by the greedy path

        x = params["embed"][prompt]
        out, finals = stacked_rnn(params["rnn"], x, self.cell, impl="scan")
        logits0 = moe_lm_decode_tail(
            params, out[:, -1, :], self.num_selected
        )

        def pick(k, logits):
            if greedy:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return jax.random.categorical(
                k, logits / temperature, axis=-1
            ).astype(jnp.int32)

        def decode(carry, _):
            carries, logits, k = carry
            k, k_samp = jax.random.split(k)
            tok = pick(k_samp, logits)
            new_carries, h_top = stacked_rnn_decode_step(
                params["rnn"], carries, params["embed"][tok], self.cell
            )
            logits = moe_lm_decode_tail(params, h_top, self.num_selected)
            return (new_carries, logits, k), tok

        _, sampled = lax.scan(
            decode, (finals, logits0, key), None, length=length
        )
        return jnp.concatenate([prompt, sampled.T], axis=1)
