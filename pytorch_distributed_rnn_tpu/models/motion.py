"""Motion sequence classifier: stacked RNN + last-timestep projection.

Capability parity with the reference ``MotionModel``
(``/root/reference/src/motion/model.py:4-17``): a stacked LSTM (default
2 x 32) over (B, 128, 9) windows followed by a Linear head applied to the
last timestep's hidden state; logits out (CrossEntropy applies softmax).
TPU-native differences: pure-functional params pytree, ``lax.scan`` cells
with batched input projections, optional GRU cell and optional Pallas fused
recurrent step.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from pytorch_distributed_rnn_tpu.ops.initializers import linear_init
from pytorch_distributed_rnn_tpu.ops.rnn import init_stacked_rnn, stacked_rnn


@dataclass(frozen=True)
class MotionModel:
    """Functional model: ``params = model.init(key)``,
    ``logits = model.apply(params, x)``."""

    input_dim: int = 9
    hidden_dim: int = 32
    layer_dim: int = 2
    output_dim: int = 6
    cell: str = "lstm"
    unroll: int = 1
    impl: str = "auto"  # "scan" | "fused" (Pallas) | "auto" (fused on TPU)
    precision: str = "f32"  # "bf16": bf16 compute, f32 params (MXU rate)
    remat: bool = False  # recompute activations in backward (HBM lever)
    dropout: float = 0.0  # inter-layer dropout; the reference parses but
    # never uses --dropout (/root/reference/src/motion/main.py:26) - here
    # the flag is real (conscious fix, PARITY.md): train mode passes a
    # dropout_key, eval passes none and stays deterministic

    def init(self, key: jax.Array):
        rnn_key, fc_key = jax.random.split(key)
        return {
            "rnn": init_stacked_rnn(
                rnn_key, self.input_dim, self.hidden_dim, self.layer_dim, self.cell
            ),
            "fc": linear_init(fc_key, self.hidden_dim, self.output_dim),
        }

    def apply(self, params, x: jax.Array, dropout_key=None) -> jax.Array:
        """x: (B, T, input_dim) -> logits (B, output_dim).

        ``dropout_key=None`` = eval/deterministic mode; pass a PRNG key for
        train-mode inter-layer dropout (torch ``nn.LSTM(dropout=...)``
        placement)."""
        from pytorch_distributed_rnn_tpu.ops.rnn import dtype_of

        compute_dtype = dtype_of(self.precision)
        outputs, _ = stacked_rnn(
            params["rnn"], x, self.cell, unroll=self.unroll, impl=self.impl,
            compute_dtype=compute_dtype, remat=self.remat,
            dropout=self.dropout, dropout_key=dropout_key,
        )
        last = outputs[:, -1, :].astype(jnp.float32)
        return last @ params["fc"]["weight"].T + params["fc"]["bias"]
