"""MoE sequence classifier: stacked RNN backbone + per-timestep MoE FFN.

New capability - the reference has no mixture-of-experts anywhere (SURVEY.md
parallelism checklist: expert parallelism **absent**).  This model makes the
``ep`` mesh axis a first-class CLI citizen (``--model moe`` under the
``local`` and ``mesh`` strategies), completing the reference's
strategy-inversion (`/root/reference/src/motion/trainer/__init__.py:10-18`)
for the last parallelism axis.

Shape: the motion classifier's stacked LSTM/GRU backbone (B, T, H), then a
top-1 Switch-style MoE FFN applied to EVERY timestep's hidden state with a
residual connection, then the last-timestep f32 head.  Routing over B*T
tokens gives the expert layer real token counts (the regime the ep
``all_to_all`` dispatch exists for), unlike routing only the B last-step
features.

Two forward paths share one parameter tree:

- :meth:`apply` / :meth:`apply_with_aux` - the dense O(E) path
  (``ops/moe.py::moe_ffn_dense``): exact, single-device; used by ``local``
  training and by evaluation under every strategy (the numerics reference).
- the expert-parallel path - ``parallel/strategy.py::make_moe_mesh_loss_fn``
  shards experts over ``ep`` and batch over dp x ep via
  ``parallel/ep.py::ep_moe_ffn``; for TOKEN-choice routing, ample
  capacity makes it equal the dense path exactly (Switch drop semantics
  otherwise).

Expert-choice caveat (``router_type="expert"``): selection is inherently
GLOBAL over whatever token set the router sees.  The dense path selects
over the full batch; the ep-sharded path selects over each shard's local
tokens (the standard sharded-EC practice - keeps selection
communication-free and every expert exactly balanced per shard).  The
two agree only at one shard; at ep > 1, training (shard-local EC) and
dense-path evaluation (global EC) use slightly different routing
functions - an inherent property of expert-choice under data sharding,
not a bug in either path.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from pytorch_distributed_rnn_tpu.ops.initializers import linear_init
from pytorch_distributed_rnn_tpu.ops.moe import init_moe_ffn, moe_ffn_dense
from pytorch_distributed_rnn_tpu.ops.rnn import init_stacked_rnn, stacked_rnn


@dataclass(frozen=True)
class MoEClassifier:
    """Functional model: ``params = model.init(key)``,
    ``logits = model.apply(params, x)`` (dense-exact path)."""

    input_dim: int = 9
    hidden_dim: int = 32
    layer_dim: int = 2
    output_dim: int = 6
    num_experts: int = 4
    num_selected: int = 1  # experts per token: 1 = Switch (raw max-gate
    # combine weight), 2 = GShard (renormalized top-2 gates, choice-major
    # capacity slots - second choices drop first under pressure)
    router_type: str = "token"  # "token": tokens pick experts (Switch/
    # GShard above); "expert": expert-choice - each expert picks its
    # top-C tokens, perfectly balanced by construction, aux loss 0
    expert_hidden: int | None = None  # default 2 * hidden_dim
    capacity_factor: float = 2.0
    group_size: int | None = None  # token-choice only: route tokens in
    # independent groups of this size on the DISPATCHED/ep path (GShard
    # grouped routing - capacity per group keeps dispatch linear in
    # token count).  The dense-exact local path has no dispatch, so
    # grouping does not change its numerics.
    aux_weight: float = 0.01  # Switch load-balancing loss weight
    cell: str = "lstm"
    unroll: int = 1
    precision: str = "f32"  # "bf16": backbone + expert matmuls in
    # bfloat16 (full MXU rate); the ROUTER stays f32 - routing decisions
    # and the aux loss are the numerics that must not quantize
    remat: bool = False  # recompute the backbone layers and the MoE FFN
    # during backward instead of saving their activations

    def __post_init__(self):
        if not 1 <= self.num_selected <= self.num_experts:
            # validated here (not only in the CLI) so the library surface
            # fails with the flag names instead of a deep lax.top_k
            # trace error ("k > last dimension of operand")
            raise ValueError(
                f"--moe-top-k {self.num_selected} needs at least that "
                f"many experts (--num-experts {self.num_experts})"
            )
        if self.router_type not in ("token", "expert"):
            raise ValueError(
                f"unknown --moe-router {self.router_type!r} - use token "
                "or expert"
            )
        if self.router_type == "expert" and self.num_selected != 1:
            raise ValueError(
                "--moe-top-k is a token-choice knob; expert-choice "
                "routing picks per-expert capacities instead - drop "
                "--moe-top-k or use --moe-router token"
            )
        if self.group_size is not None:
            if self.router_type == "expert":
                raise ValueError(
                    "--moe-group-size is a token-choice knob; expert-"
                    "choice selection is already balanced - drop it or "
                    "use --moe-router token"
                )
            if self.group_size < 1:
                raise ValueError(
                    f"--moe-group-size must be >= 1, got "
                    f"{self.group_size}"
                )
        import math

        # `not (x > 0)` also catches NaN (every comparison is False);
        # isfinite rejects inf - both would otherwise crash deep in
        # moe_capacity's int() without the flag name
        if not (self.capacity_factor > 0
                and math.isfinite(self.capacity_factor)):
            # capacity 0 would silently drop EVERY token (the residual
            # passes all inputs through unchanged - no error, no learning
            # signal from the experts)
            raise ValueError(
                f"--moe-capacity-factor must be a positive finite "
                f"number, got {self.capacity_factor}"
            )

    @property
    def _expert_hidden(self) -> int:
        return self.expert_hidden or 2 * self.hidden_dim

    def init(self, key: jax.Array):
        rnn_key, moe_key, fc_key = jax.random.split(key, 3)
        return {
            "rnn": init_stacked_rnn(
                rnn_key, self.input_dim, self.hidden_dim, self.layer_dim,
                self.cell,
            ),
            "moe": init_moe_ffn(
                moe_key, self.hidden_dim, self.num_experts,
                self._expert_hidden,
            ),
            "fc": linear_init(fc_key, self.hidden_dim, self.output_dim),
        }

    def features(self, params, x: jax.Array) -> jax.Array:
        """Backbone + residual dense MoE: (B, T, in) -> ((B, T, H), aux)."""
        from pytorch_distributed_rnn_tpu.ops.rnn import dtype_of

        compute_dtype = dtype_of(self.precision)
        out, _ = stacked_rnn(
            params["rnn"], x, self.cell, unroll=self.unroll, impl="scan",
            compute_dtype=compute_dtype, remat=self.remat,
        )
        from pytorch_distributed_rnn_tpu.ops.moe import cast_expert_params

        moe_params = cast_expert_params(params["moe"], compute_dtype)

        if self.router_type == "expert":
            from pytorch_distributed_rnn_tpu.ops.moe import (
                moe_ffn_expert_choice,
            )

            def dense(p, h):
                return moe_ffn_expert_choice(
                    p, h, capacity_factor=self.capacity_factor)
        else:
            def dense(p, h):
                return moe_ffn_dense(p, h,
                                     num_selected=self.num_selected)

        moe_fn = jax.checkpoint(dense) if self.remat else dense
        moe_out, aux = moe_fn(moe_params, out)
        return out + moe_out, aux

    def apply_with_aux(self, params, x: jax.Array, dropout_key=None):
        """(logits (B, out), aux scalar).  ``dropout_key`` accepted for the
        shared ``_apply_model`` signature; the family has no dropout (the
        CLI rejects the flag loudly)."""
        h, aux = self.features(params, x)
        last = h[:, -1, :].astype(jnp.float32)
        logits = last @ params["fc"]["weight"].T + params["fc"]["bias"]
        return logits, aux

    def apply(self, params, x: jax.Array, dropout_key=None) -> jax.Array:
        return self.apply_with_aux(params, x, dropout_key)[0]
