"""Causal attention language model: the attention family's LM adapter.

The attention family's existing model is a sequence CLASSIFIER
(``models/attention.py``) - (B, T, features) windows pooled into class
logits - which has no token head and nothing to decode.  Serving needs
every family to honor the char-RNN ``generate(params, prompt, length,
temperature, key)`` contract, so this module is the family's thin LM
adapter: the SAME pre-norm encoder blocks (``init_block`` /
``block_qkv`` / ``block_epilogue`` - one definition of the block math),
run causally over token embeddings with a vocab head.

Decode is bounded-buffer by construction: a fixed-capacity KV cache
(``(B, depth, heads, C, head_dim)``) written in place via per-slot
dynamic updates, never a growing concatenation.  The cache capacity is
an argument of the math, not of the numerics: padded cache columns are
masked to ``-inf`` before the softmax (their probabilities underflow to
exactly 0.0), so the same request decodes identically under
``generate``'s tight ``Tp + length`` cache and the serving engine's
``max_len`` cache - the property the continuous-batching parity tests
pin.

Module-level :func:`attention_prefill` / :func:`attention_decode_step`
are shared with ``serving/adapters.py`` so batched continuous decode
reuses the reference decode math exactly.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from pytorch_distributed_rnn_tpu.models.attention import (
    _layer_norm,
    _linear,
    block_epilogue,
    block_qkv,
    init_block,
)
from pytorch_distributed_rnn_tpu.ops.attention import mha_attention
from pytorch_distributed_rnn_tpu.ops.initializers import linear_init


def _cache_write(cache, kv, pos):
    """Write this step's K or V rows into a per-layer cache.

    ``cache``: (B, H, C, D), ``kv``: (B, H, 1, D), ``pos``: (B,) int32
    write index per batch slot (slots decode at independent depths under
    continuous batching, so the index is per-row, not scalar).
    """
    return jax.vmap(
        lambda c, k, p: lax.dynamic_update_slice_in_dim(c, k, p, axis=1)
    )(cache, kv, pos)


def attention_decode_step(params, k_cache, v_cache, pos, tok,
                          num_heads: int):
    """One cached autoregressive step: ``tok`` (B,) int32 at position
    ``pos`` (B,) int32 -> ``(k_cache, v_cache, logits (B, vocab))``.

    Caches are (B, depth, H, C, head_dim).  Attention spans cache
    columns ``<= pos`` (the new token's K/V included - written before
    the scores); later columns are ``-inf``-masked, reproducing
    :func:`mha_attention`'s causal row for this position exactly.
    """
    h = params["embed"][tok] + jnp.take(params["pos"], pos, axis=0)
    h = h[:, None, :]  # (B, 1, D)
    cols = jnp.arange(k_cache.shape[3])
    mask = (cols[None, :] <= pos[:, None])[:, None, None, :]
    for li, blk in enumerate(params["blocks"]):
        q, k, v = block_qkv(blk, h, num_heads)  # (B, H, 1, hd)
        k_cache = k_cache.at[:, li].set(
            _cache_write(k_cache[:, li], k, pos))
        v_cache = v_cache.at[:, li].set(
            _cache_write(v_cache[:, li], v, pos))
        keys, values = k_cache[:, li], v_cache[:, li]
        scale = q.shape[-1] ** -0.5
        s = jnp.einsum("bhqd,bhkd->bhqk", q, keys,
                       preferred_element_type=jnp.float32) * scale
        s = jnp.where(mask, s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("bhqk,bhkd->bhqd", p.astype(values.dtype), values)
        h = block_epilogue(blk, h, attn)
    top = _layer_norm(h[:, 0], **params["ln_f"])
    return k_cache, v_cache, _linear(params["head"], top)


def attention_prefill(params, tokens, num_heads: int, cache_len: int):
    """Batched prompt pass filling a fresh KV cache.

    ``tokens``: (B, T) int32 with T <= cache_len.  Returns
    ``(k_cache, v_cache, logits (B, T, vocab))`` - caches
    (B, depth, H, cache_len, head_dim) holding the prompt's K/V in
    columns [0, T).  Rows past a caller's true prompt length are
    causal-garbage the caller must ignore (serving pads prompts to
    bucket lengths; column masking at decode plus sequential overwrites
    keep the garbage invisible - see ``serving/adapters.py``).
    """
    b, t = tokens.shape
    depth = len(params["blocks"])
    dim = params["embed"].shape[1]
    hd = dim // num_heads
    h = params["embed"][tokens] + params["pos"][:t]
    k_cache = jnp.zeros((b, depth, num_heads, cache_len, hd), h.dtype)
    v_cache = jnp.zeros((b, depth, num_heads, cache_len, hd), h.dtype)
    for li, blk in enumerate(params["blocks"]):
        q, k, v = block_qkv(blk, h, num_heads)  # (B, H, T, hd)
        k_cache = k_cache.at[:, li, :, :t].set(k)
        v_cache = v_cache.at[:, li, :, :t].set(v)
        attn = mha_attention(q, k, v, causal=True)
        h = block_epilogue(blk, h, attn)
    top = _layer_norm(h, **params["ln_f"])
    return k_cache, v_cache, _linear(params["head"], top)


@dataclass(frozen=True)
class AttentionLM:
    """``params = model.init(key)``; ``logits = model.apply(params,
    tokens)`` maps (B, T) int tokens -> (B, T, vocab) next-token logits
    through causally-masked pre-norm encoder blocks."""

    vocab_size: int = 256
    dim: int = 64
    depth: int = 2
    num_heads: int = 4
    max_len: int = 512

    def __post_init__(self):
        if self.dim % self.num_heads != 0:
            raise ValueError(
                f"dim {self.dim} must be divisible by num_heads "
                f"{self.num_heads} (head splitting would silently "
                "truncate projections)"
            )

    def init(self, key: jax.Array):
        ks = jax.random.split(key, self.depth + 3)
        scale = self.dim ** -0.5
        return {
            "embed": jax.random.normal(
                ks[0], (self.vocab_size, self.dim)) * scale,
            "pos": jax.random.normal(ks[1], (self.max_len, self.dim)) * 0.02,
            "blocks": [
                init_block(ks[2 + i], self.dim, self.num_heads)
                for i in range(self.depth)
            ],
            "ln_f": {"scale": jnp.ones((self.dim,)),
                     "bias": jnp.zeros((self.dim,))},
            "head": linear_init(ks[-1], self.dim, self.vocab_size),
        }

    def apply(self, params, tokens: jax.Array, dropout_key=None) -> jax.Array:
        """tokens: (B, T) int32 -> logits (B, T, vocab).  The family has
        no train-mode dropout here; ``dropout_key`` is accepted for the
        shared model-apply signature and ignored."""
        t = tokens.shape[1]
        if t > self.max_len:
            raise ValueError(
                f"sequence length {t} exceeds max_len {self.max_len}"
            )
        h = params["embed"][tokens] + params["pos"][:t]
        for blk in params["blocks"]:
            q, k, v = block_qkv(blk, h, self.num_heads)
            h = block_epilogue(blk, h, mha_attention(q, k, v, causal=True))
        h = _layer_norm(h, **params["ln_f"])
        return _linear(params["head"], h)

    def loss(self, params, tokens: jax.Array, dropout_key=None) -> jax.Array:
        """Next-token cross entropy (``CharRNN.loss`` semantics)."""
        from pytorch_distributed_rnn_tpu.ops.losses import cross_entropy_loss

        logits = self.apply(params, tokens[:, :-1])
        targets = tokens[:, 1:]
        return cross_entropy_loss(
            logits.reshape(-1, self.vocab_size), targets.reshape(-1)
        )

    def generate(self, params, prompt: jax.Array, length: int,
                 key: jax.Array | None = None,
                 temperature: float = 1.0) -> jax.Array:
        """The char-RNN bounded-buffer generation contract:
        ``prompt (B, Tp) int32 -> (B, Tp + length)``.

        Prefill fills a fixed ``Tp + length`` KV cache in one batched
        causal pass; a ``lax.scan`` of :func:`attention_decode_step`
        single-token steps decodes (static trip count, in-place cache
        writes, no growing buffers).  ``temperature=0`` is greedy
        argmax; otherwise tokens draw from ``softmax(logits /
        temperature)`` with the same key-splitting schedule as
        ``CharRNN.generate``.
        """
        if temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if prompt.ndim != 2 or prompt.shape[1] < 1:
            raise ValueError(
                "prompt must be (batch, >=1 tokens); an empty prompt has "
                "no last-step logits to seed decoding"
            )
        if prompt.shape[1] + length > self.max_len:
            raise ValueError(
                f"prompt ({prompt.shape[1]}) + length ({length}) exceeds "
                f"max_len {self.max_len}: the bounded KV cache (and the "
                "learned positions) end there"
            )
        greedy = temperature == 0.0
        if key is None:
            if not greedy:
                raise ValueError("sampling (temperature > 0) needs a key")
            key = jax.random.PRNGKey(0)  # unused by the greedy path

        b, tp = prompt.shape
        k_cache, v_cache, logits_all = attention_prefill(
            params, prompt, self.num_heads, cache_len=tp + length
        )
        logits0 = logits_all[:, -1, :]
        pos0 = jnp.full((b,), tp, jnp.int32)

        def pick(k, logits):
            if greedy:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return jax.random.categorical(
                k, logits / temperature, axis=-1
            ).astype(jnp.int32)

        def decode(carry, _):
            kc, vc, pos, logits, k = carry
            k, k_samp = jax.random.split(k)
            tok = pick(k_samp, logits)
            kc, vc, logits = attention_decode_step(
                params, kc, vc, pos, tok, self.num_heads
            )
            return (kc, vc, pos + 1, logits, k), tok

        _, sampled = lax.scan(
            decode, (k_cache, v_cache, pos0, logits0, key), None,
            length=length,
        )
        return jnp.concatenate([prompt, sampled.T], axis=1)
