"""Character-level RNN language model: the LM stress family.

BASELINE.json's stress configs name a toy char-RNN and a "stacked-LSTM
language model 50M params (stress XLA scan + grad psum)"; the reference
itself only ships the motion classifier (`/root/reference/src/motion/
model.py:4-17`), so this family is the framework's coverage of the
sequence-to-sequence-logits shape: embedding -> stacked LSTM/GRU (the same
``ops/rnn`` cells as the motion model, scan or fused Pallas path) ->
per-timestep vocab projection.  Next-token loss lives here too so every
trainer/strategy can drive the family unchanged.

``char_rnn_50m()`` pins the ~50M-param preset the stress benchmarks use.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from pytorch_distributed_rnn_tpu.ops.initializers import linear_init
from pytorch_distributed_rnn_tpu.ops.losses import cross_entropy_loss
from pytorch_distributed_rnn_tpu.ops.rnn import (
    head_logits,
    init_stacked_rnn,
    stacked_rnn,
)


@dataclass(frozen=True)
class CharRNN:
    """``params = model.init(key)``; ``logits = model.apply(params, tokens)``
    maps (B, T) int tokens -> (B, T, vocab) next-token logits."""

    vocab_size: int = 256
    embed_dim: int = 128
    hidden_dim: int = 256
    layer_dim: int = 2
    cell: str = "lstm"
    unroll: int = 1
    impl: str = "auto"  # "scan" | "fused" (Pallas) | "auto"
    precision: str = "f32"  # "bf16": bf16 compute, f32 params (MXU rate)
    remat: bool = False  # recompute activations in backward (HBM lever)
    dropout: float = 0.0  # inter-layer dropout (train mode only)

    def init(self, key: jax.Array):
        k_embed, k_rnn, k_head = jax.random.split(key, 3)
        scale = self.embed_dim ** -0.5
        return {
            "embed": jax.random.normal(
                k_embed, (self.vocab_size, self.embed_dim)) * scale,
            "rnn": init_stacked_rnn(
                k_rnn, self.embed_dim, self.hidden_dim, self.layer_dim,
                self.cell,
            ),
            "head": linear_init(k_head, self.hidden_dim, self.vocab_size),
        }

    def apply(self, params, tokens: jax.Array, dropout_key=None) -> jax.Array:
        """tokens: (B, T) int32 -> logits (B, T, vocab).

        ``dropout_key=None`` = eval/deterministic; pass a key for
        train-mode inter-layer dropout."""
        from pytorch_distributed_rnn_tpu.ops.rnn import dtype_of

        compute_dtype = dtype_of(self.precision)
        x = params["embed"][tokens]
        outputs, _ = stacked_rnn(
            params["rnn"], x, self.cell, unroll=self.unroll, impl=self.impl,
            compute_dtype=compute_dtype, remat=self.remat,
            dropout=self.dropout, dropout_key=dropout_key,
        )
        return head_logits(params["head"], outputs)

    def loss(self, params, tokens: jax.Array, dropout_key=None) -> jax.Array:
        """Next-token cross entropy: predict tokens[:, 1:] from
        tokens[:, :-1], mean over all positions."""
        logits = self.apply(params, tokens[:, :-1], dropout_key=dropout_key)
        targets = tokens[:, 1:]
        return cross_entropy_loss(
            logits.reshape(-1, self.vocab_size), targets.reshape(-1)
        )

    def generate(self, params, prompt: jax.Array, length: int,
                 key: jax.Array | None = None,
                 temperature: float = 1.0) -> jax.Array:
        """Autoregressive sampling: ``prompt (B, Tp) int32 ->
        (B, Tp + length)``.

        The prompt is consumed in one batched ``stacked_rnn`` pass (the
        MXU-friendly prefill), whose per-layer final carries seed a
        ``lax.scan`` decode loop of single-token cell steps - the
        compiler-friendly shape for autoregression on TPU (static trip
        count, no growing buffers).  ``temperature=0`` is greedy argmax
        (deterministic, no key needed); otherwise tokens are drawn from
        ``softmax(logits / temperature)``.  Generation runs in f32
        regardless of ``precision`` - decode is latency-bound, not
        MXU-bound, and sampling is sensitive to logit rounding.
        """
        from pytorch_distributed_rnn_tpu.ops.rnn import stacked_rnn_decode_step

        if temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if prompt.ndim != 2 or prompt.shape[1] < 1:
            raise ValueError(
                "prompt must be (batch, >=1 tokens); an empty prompt has "
                "no last-step logits to seed decoding"
            )
        greedy = temperature == 0.0
        if key is None:
            if not greedy:
                raise ValueError("sampling (temperature > 0) needs a key")
            key = jax.random.PRNGKey(0)  # unused by the greedy path

        x = params["embed"][prompt]
        outputs, finals = stacked_rnn(
            params["rnn"], x, self.cell, unroll=self.unroll, impl=self.impl,
        )
        logits0 = head_logits(params["head"], outputs[:, -1, :])

        def pick(k, logits):
            if greedy:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return jax.random.categorical(
                k, logits / temperature, axis=-1
            ).astype(jnp.int32)

        def decode_step(carry, _):
            carries, logits, k = carry
            k, k_samp = jax.random.split(k)
            tok = pick(k_samp, logits)
            new_carries, h_top = stacked_rnn_decode_step(
                params["rnn"], carries, params["embed"][tok], self.cell
            )
            logits = head_logits(params["head"], h_top)
            return (new_carries, logits, k), tok

        _, sampled = lax.scan(
            decode_step, (finals, logits0, key), None, length=length
        )
        return jnp.concatenate([prompt, sampled.T], axis=1)


def char_rnn_50m(impl: str = "auto", precision: str = "f32",
                 remat: bool = False, unroll: int = 1) -> CharRNN:
    """The BASELINE.json stress config: ~50M-param stacked-LSTM LM
    (vocab 256, embed 512, 4 x 1280 hidden -> 49.9M params).
    ``precision="bf16"`` / ``remat=True`` are the intended levers for
    running this preset at depth on real hardware; ``unroll`` feeds the
    scan path's ``lax.scan(unroll=...)`` (more ILP per loop iteration at
    the cost of program size)."""
    return CharRNN(vocab_size=256, embed_dim=512, hidden_dim=1280,
                   layer_dim=4, cell="lstm", impl=impl,
                   precision=precision, remat=remat, unroll=unroll)


def num_params(params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))
