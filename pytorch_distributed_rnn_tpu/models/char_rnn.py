"""Character-level RNN language model: the LM stress family.

BASELINE.json's stress configs name a toy char-RNN and a "stacked-LSTM
language model 50M params (stress XLA scan + grad psum)"; the reference
itself only ships the motion classifier (`/root/reference/src/motion/
model.py:4-17`), so this family is the framework's coverage of the
sequence-to-sequence-logits shape: embedding -> stacked LSTM/GRU (the same
``ops/rnn`` cells as the motion model, scan or fused Pallas path) ->
per-timestep vocab projection.  Next-token loss lives here too so every
trainer/strategy can drive the family unchanged.

``char_rnn_50m()`` pins the ~50M-param preset the stress benchmarks use.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from pytorch_distributed_rnn_tpu.ops.initializers import linear_init
from pytorch_distributed_rnn_tpu.ops.losses import cross_entropy_loss
from pytorch_distributed_rnn_tpu.ops.rnn import init_stacked_rnn, stacked_rnn


@dataclass(frozen=True)
class CharRNN:
    """``params = model.init(key)``; ``logits = model.apply(params, tokens)``
    maps (B, T) int tokens -> (B, T, vocab) next-token logits."""

    vocab_size: int = 256
    embed_dim: int = 128
    hidden_dim: int = 256
    layer_dim: int = 2
    cell: str = "lstm"
    unroll: int = 1
    impl: str = "auto"  # "scan" | "fused" (Pallas) | "auto"
    precision: str = "f32"  # "bf16": bf16 compute, f32 params (MXU rate)
    remat: bool = False  # recompute activations in backward (HBM lever)
    dropout: float = 0.0  # inter-layer dropout (train mode only)

    def init(self, key: jax.Array):
        k_embed, k_rnn, k_head = jax.random.split(key, 3)
        scale = self.embed_dim ** -0.5
        return {
            "embed": jax.random.normal(
                k_embed, (self.vocab_size, self.embed_dim)) * scale,
            "rnn": init_stacked_rnn(
                k_rnn, self.embed_dim, self.hidden_dim, self.layer_dim,
                self.cell,
            ),
            "head": linear_init(k_head, self.hidden_dim, self.vocab_size),
        }

    def apply(self, params, tokens: jax.Array, dropout_key=None) -> jax.Array:
        """tokens: (B, T) int32 -> logits (B, T, vocab).

        ``dropout_key=None`` = eval/deterministic; pass a key for
        train-mode inter-layer dropout."""
        compute_dtype = jnp.bfloat16 if self.precision == "bf16" else None
        x = params["embed"][tokens]
        outputs, _ = stacked_rnn(
            params["rnn"], x, self.cell, unroll=self.unroll, impl=self.impl,
            compute_dtype=compute_dtype, remat=self.remat,
            dropout=self.dropout, dropout_key=dropout_key,
        )
        outputs = outputs.astype(jnp.float32)
        return (
            outputs @ params["head"]["weight"].T + params["head"]["bias"]
        )

    def loss(self, params, tokens: jax.Array, dropout_key=None) -> jax.Array:
        """Next-token cross entropy: predict tokens[:, 1:] from
        tokens[:, :-1], mean over all positions."""
        logits = self.apply(params, tokens[:, :-1], dropout_key=dropout_key)
        targets = tokens[:, 1:]
        return cross_entropy_loss(
            logits.reshape(-1, self.vocab_size), targets.reshape(-1)
        )


def char_rnn_50m(impl: str = "auto", precision: str = "f32",
                 remat: bool = False) -> CharRNN:
    """The BASELINE.json stress config: ~50M-param stacked-LSTM LM
    (vocab 256, embed 512, 4 x 1280 hidden -> 49.9M params).
    ``precision="bf16"`` / ``remat=True`` are the intended levers for
    running this preset at depth on real hardware."""
    return CharRNN(vocab_size=256, embed_dim=512, hidden_dim=1280,
                   layer_dim=4, cell="lstm", impl=impl,
                   precision=precision, remat=remat)


def num_params(params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))
