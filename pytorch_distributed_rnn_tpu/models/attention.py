"""Attention sequence classifier: the long-context model family.

The reference's only model is the motion LSTM
(``/root/reference/src/motion/model.py:4-17``).  This family covers the same
task shape - (B, T, features) window -> class logits - with a pre-norm
Transformer encoder, so the framework's sequence/context-parallel execution
paths (ring attention / Ulysses, ``ops/attention.py``) have a first-class
model to drive.  Same functional API as :class:`MotionModel`:
``params = model.init(key)``, ``logits = model.apply(params, x)``.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from pytorch_distributed_rnn_tpu.ops.attention import mha_attention
from pytorch_distributed_rnn_tpu.ops.initializers import linear_init


def _layer_norm(x, scale, bias, eps=1e-5):
    # stats in f32 regardless of the compute dtype (bf16 mean/var loses
    # the small differences normalization exists to measure); the affine
    # output follows the input dtype.  All casts are no-ops in pure f32.
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = ((xf - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype)
    return y * scale + bias


def init_block(key, dim: int, num_heads: int, mlp_ratio: int = 4):
    """One pre-norm encoder block's params."""
    ks = jax.random.split(key, 6)
    return {
        "ln1": {"scale": jnp.ones((dim,)), "bias": jnp.zeros((dim,))},
        "wq": linear_init(ks[0], dim, dim),
        "wk": linear_init(ks[1], dim, dim),
        "wv": linear_init(ks[2], dim, dim),
        "wo": linear_init(ks[3], dim, dim),
        "ln2": {"scale": jnp.ones((dim,)), "bias": jnp.zeros((dim,))},
        "fc1": linear_init(ks[4], dim, mlp_ratio * dim),
        "fc2": linear_init(ks[5], mlp_ratio * dim, dim),
    }


def _linear(p, x):
    return x @ p["weight"].T + p["bias"]


def _split_heads(x, num_heads):
    b, t, d = x.shape
    return x.reshape(b, t, num_heads, d // num_heads).transpose(0, 2, 1, 3)


def _merge_heads(x):
    b, h, t, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * d)


def block_qkv(params, x, num_heads: int):
    """Pre-norm + QKV projections: the position-wise prologue every
    sequence-parallel strategy runs locally on its chunk."""
    y = _layer_norm(x, **params["ln1"])
    q = _split_heads(_linear(params["wq"], y), num_heads)
    k = _split_heads(_linear(params["wk"], y), num_heads)
    v = _split_heads(_linear(params["wv"], y), num_heads)
    return q, k, v


def _dropout(x, key, rate: float):
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


def block_epilogue(params, x, attn_out, dropout: float = 0.0,
                   dropout_key=None):
    """Output projection + residual + MLP: position-wise, runs locally on
    any sequence chunk.  ``dropout`` masks the two residual-path sublayer
    outputs (torch dropout1/dropout2) and the FFN activation between
    fc1 and fc2 (torch's inner ``self.dropout``).  Torch's fourth site -
    dropout on the attention probabilities inside MHA - is NOT applied
    here: the attention callable is strategy-injected (ring/Ulysses), so
    probabilities never pass through this epilogue.
    ``dropout_key=None`` = eval/deterministic mode."""
    attn_proj = _linear(params["wo"], _merge_heads(attn_out))
    train = dropout > 0.0 and dropout_key is not None
    if train:
        k1, k2, k3 = jax.random.split(dropout_key, 3)
        attn_proj = _dropout(attn_proj, k1, dropout)
    x = x + attn_proj
    y = _layer_norm(x, **params["ln2"])
    y = jax.nn.gelu(_linear(params["fc1"], y))
    if train:
        y = _dropout(y, k2, dropout)
    y = _linear(params["fc2"], y)
    if train:
        y = _dropout(y, k3, dropout)
    return x + y


def apply_block(params, x, num_heads: int, attention=None,
                dropout: float = 0.0, dropout_key=None):
    """One encoder block.  ``attention(q, k, v) -> out`` defaults to full
    attention; sequence-parallel callers inject ring/Ulysses attention."""
    q, k, v = block_qkv(params, x, num_heads)
    attn = attention if attention is not None else (
        lambda q, k, v: mha_attention(q, k, v)
    )
    return block_epilogue(params, x, attn(q, k, v),
                          dropout=dropout, dropout_key=dropout_key)


@dataclass(frozen=True)
class AttentionClassifier:
    """Pre-norm Transformer encoder over (B, T, input_dim) windows, mean
    pooled into class logits."""

    input_dim: int = 9
    dim: int = 64
    depth: int = 2
    num_heads: int = 4
    output_dim: int = 6
    max_len: int = 4096
    dropout: float = 0.0  # residual-path (dropout1/dropout2) + inner-FFN
    # dropout; train-mode only (apply threads a key; eval passes none and
    # stays deterministic).  See block_epilogue for the site placement.
    impl: str = "auto"  # "dense" | "flash" (Pallas) | "auto" (flash on
    # TPU) - only governs the default attention; an injected ring/Ulysses
    # callable (sequence-parallel strategies) takes precedence
    precision: str = "f32"  # "bf16": block params + activations in
    # bfloat16 (full MXU rate, half the HBM traffic); layernorm stats
    # and the pooled head stay f32 (the RNN families' lever contract)
    remat: bool = False  # recompute each encoder block during backward
    # (jax.checkpoint per block) instead of saving its activations

    def __post_init__(self):
        if self.dim % self.num_heads != 0:
            raise ValueError(
                f"dim {self.dim} must be divisible by num_heads "
                f"{self.num_heads} (head splitting would silently "
                f"truncate projections)"
            )

    def init(self, key: jax.Array):
        ks = jax.random.split(key, self.depth + 3)
        return {
            "embed": linear_init(ks[0], self.input_dim, self.dim),
            "pos": jax.random.normal(ks[1], (self.max_len, self.dim)) * 0.02,
            "blocks": [
                init_block(ks[2 + i], self.dim, self.num_heads)
                for i in range(self.depth)
            ],
            "head": linear_init(ks[-1], self.dim, self.output_dim),
        }

    def apply(self, params, x: jax.Array, attention=None,
              dropout_key=None) -> jax.Array:
        """x: (B, T, input_dim) -> logits (B, output_dim).  ``attention``
        overrides the per-block attention (ring/Ulysses injection point);
        positions are added by the caller for sequence-parallel chunks.
        ``dropout_key=None`` selects eval/deterministic mode; pass a PRNG
        key for train-mode per-sublayer dropout."""
        t = x.shape[1]
        h = _linear(params["embed"], x) + params["pos"][:t]
        if attention is None:
            # lazy import keeps Pallas off the CPU/RNN-only startup path
            # (the package convention - see ops/rnn.py:resolve_rnn_impl)
            from pytorch_distributed_rnn_tpu.ops.pallas_attention import (
                flash_attention,
                resolve_attention_impl,
            )

            if resolve_attention_impl(self.impl) == "flash":
                attention = lambda q, k, v: flash_attention(q, k, v)  # noqa: E731
        from pytorch_distributed_rnn_tpu.ops.rnn import dtype_of

        compute_dtype = dtype_of(self.precision)
        if compute_dtype is not None:
            h = h.astype(compute_dtype)
        def block_fn(blk, h, blk_key):
            return apply_block(blk, h, self.num_heads, attention,
                               dropout=self.dropout, dropout_key=blk_key)

        if self.remat:
            # num_heads/attention/dropout ride the closure (they are
            # static); only arrays (and the optional key) are traced
            block_fn = jax.checkpoint(block_fn)
        for i, blk in enumerate(params["blocks"]):
            blk_key = (None if dropout_key is None
                       else jax.random.fold_in(dropout_key, i))
            if compute_dtype is not None:
                blk = jax.tree.map(
                    lambda p: p.astype(compute_dtype), blk
                )
            h = block_fn(blk, h, blk_key)
        # pooled head in f32 regardless of compute dtype (model contract)
        pooled = jnp.mean(h.astype(jnp.float32), axis=1)
        return _linear(params["head"], pooled)
