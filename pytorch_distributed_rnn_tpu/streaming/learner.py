"""Streaming learner: bounded-staleness experience ingest + failover.

The learner half of the Podracer-style actor/learner split
(``streaming/__init__.py``).  One process owns the authoritative
params + optimizer, listens on the PS wire, and serves an elastic actor
fleet; unlike the PS master its update cadence is DECOUPLED from the
pushers' - experience lands in a bounded queue and a single apply loop
drains it, so a burst of actors never serializes behind one optimizer
step and a slow optimizer step never stalls the wire.

Ingest verdicts (the EXPERIENCE reply contract, ``protocol.py``):

  DUPLICATE  seq at-or-below the actor's push-seq watermark - a retried
             push whose original landed, or a respawned/reconnected
             actor's stale in-flight push.  Acknowledged (the actor
             moves on) but never applied twice: EXACTLY-ONCE ingest.
  STALE      generated more than ``max_staleness`` versions ago.
             Counted and refused - never silently dropped - and the
             actor refreshes params before re-sending: BOUNDED
             STALENESS.  Staleness is also re-checked at APPLY time
             (the version advances while a batch queues), so the bound
             holds on what is applied, not just on what is accepted.
  BACKOFF    the bounded queue is full.  The reply carries a throttle
             hint and the watermark does NOT advance, so the actor
             re-sends the same seq after a sleep: BACKPRESSURE without
             stalling the wire or dropping work.
  OK         watermark advanced, batch enqueued.

Failover: every ``checkpoint_updates`` applied updates the learner
snapshots params + optimizer + its params version + the per-actor
watermarks into ONE crash-safe checkpoint (``training/checkpoint.py``
``extra`` header - atomic with the params, so a crash can never leave
new params with stale watermarks).  A ``--resume auto`` restart
re-listens on the same port; live actors' transport retries reconnect
(star re-join + REGISTER) and their restored watermarks dedupe any
re-sent experience the dead incarnation already applied.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time

import numpy as np

from pytorch_distributed_rnn_tpu.param_server import protocol
from pytorch_distributed_rnn_tpu.resilience import membership
from pytorch_distributed_rnn_tpu.utils import threadcheck

log = logging.getLogger(__name__)

# staleness samples kept for the p50/p95 summary: bounded so a
# long-running learner cannot grow host memory with telemetry
_MAX_STALENESS_SAMPLES = 100_000


class ExperienceLearner:
    """Owns params/optimizer/version/watermarks; serves the actor fleet.

    ``update_fn(flat_params, opt_state, flat_grads) -> (flat, opt)`` is
    the jitted optimizer step (the caller closes over optax + unravel);
    ``checkpoint_cb(version, flat, opt, watermarks, counters)``, when
    given, is invoked every ``checkpoint_updates`` applied updates and
    once more synchronously at the end of :meth:`serve`.
    """

    def __init__(self, comm, flat_params: np.ndarray, opt_state,
                 update_fn, *, max_staleness: int = 4,
                 queue_depth: int = 8, throttle_hint_s: float = 0.05,
                 join_timeout: float = 30.0, max_world: int = 16,
                 version: int = 0, watermarks: dict | None = None,
                 checkpoint_cb=None, checkpoint_updates: int = 0,
                 recorder=None, faults=None):
        from pytorch_distributed_rnn_tpu.obs.recorder import NULL_RECORDER

        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self.comm = comm
        self.params = np.asarray(flat_params, np.float32)
        self.opt_state = opt_state
        self.update_fn = update_fn
        self.num_params = int(self.params.size)
        self.max_staleness = int(max_staleness)
        self.throttle_hint_s = float(throttle_hint_s)
        self.join_timeout = float(join_timeout)
        self.max_world = int(max_world)
        self.checkpoint_cb = checkpoint_cb
        self.checkpoint_updates = int(checkpoint_updates)
        self.faults = faults
        # params + version are one atomic pair under this lock: every
        # reply that quotes the version (STATE_SYNC, PARAMS_AT, verdicts)
        # reads both together, so an actor can never stamp new params
        # with an old version number
        self.lock = threadcheck.lock(threading.Lock(), "learner.state")  # guards: params, opt_state, version, accepted, duplicates, stale_rejected, queue_sheds, poisoned
        self.version = int(version)
        # the bounded ingest queue - the backpressure boundary.  Service
        # threads put_nowait; only the apply loop gets.
        self.queue: queue_mod.Queue = queue_mod.Queue(
            maxsize=max(1, int(queue_depth))
        )
        # membership: same roster as the PS master, but NEVER
        # bootstrapped - every actor (launch-time or late) enters via
        # star-join + REGISTER, so the learner is elastic by
        # construction and a restart needs no rendezvous arithmetic
        self.roster = membership.Roster(recorder=self.recorder)
        if watermarks:
            # failover restore: dead incarnation's exactly-once state
            self.roster.restore_watermarks(watermarks)
        # counters (reported in run_summary; None-vs-0 semantics are the
        # summary's job - here they are honest zeros)
        self.updates_applied = 0
        self.accepted = 0
        self.duplicates = 0
        self.stale_rejected = 0
        self.queue_sheds = 0
        self.poisoned = 0
        self.duration_s = 0.0
        self._staleness_samples: list[int] = []
        # elastic service-thread bookkeeping (master.py idiom): a stale
        # thread dying after its rank was re-accepted must not mark the
        # NEW incarnation dead
        # lock-order: StreamingLearner._gen_lock -> StreamingLearner.lock -> Roster._lock
        self._thread_gen: dict[int, int] = {}
        self._gen_lock = threadcheck.lock(threading.Lock(), "learner.gen")  # guards: _thread_gen
        self._tolerated: dict[int, BaseException] = {}
        self._member_cv = threading.Condition(
            threadcheck.lock(threading.Lock(), "learner.member"))

    # -- ingest verdict ------------------------------------------------------

    def ingest(self, rank: int, seq: int, version: int,
               payload: np.ndarray):
        """Verdict one EXPERIENCE push.  Returns ``(status,
        learner_version, throttle_hint_s)`` - the exact reply triple.

        Check order matters: DUPLICATE before STALE (a retried push
        whose original applied must be ACKed as applied even if it
        would fail the staleness gate by now - the actor treats
        DUPLICATE as success and moves on); the watermark advances only
        after the enqueue succeeded, so a BACKOFF or STALE refusal
        leaves the actor free to re-send the same seq."""
        member = self.roster.member_for_rank(rank)
        if member is None:
            raise RuntimeError(
                f"experience push from unrostered rank {rank} without "
                "REGISTER; actor-fleet entry requires the join protocol"
            )
        if member.state == membership.DEAD:
            raise RuntimeError(
                f"experience push from dead member (worker-id "
                f"{member.worker_id}, rank {rank}) without REGISTER; "
                "membership re-entry requires the join protocol"
            )
        with self.lock:
            current = self.version
        if seq <= member.push_seq:
            with self.lock:
                self.duplicates += 1
            self._reject("duplicate", member, seq, version, current)
            return protocol.EXP_DUPLICATE, current, 0.0
        if version < current - self.max_staleness:
            with self.lock:
                self.stale_rejected += 1
            self._reject("stale", member, seq, version, current)
            return protocol.EXP_STALE, current, 0.0
        item = (member.worker_id, seq, version,
                np.asarray(payload, np.float32))
        try:
            self.queue.put_nowait(item)
        except queue_mod.Full:
            with self.lock:
                self.queue_sheds += 1
            self._reject("backoff", member, seq, version, current)
            return protocol.EXP_BACKOFF, current, self.throttle_hint_s
        self.roster.note_push(rank, seq)
        with self.lock:
            self.accepted += 1
        return protocol.EXP_OK, current, 0.0

    def _reject(self, reason: str, member, seq: int, version: int,
                current: int):
        log.warning(
            f"experience {reason}: worker-id {member.worker_id} seq "
            f"{seq} version {version} (learner @ {current})"
        )
        if self.recorder.enabled:
            self.recorder.record(
                "experience_reject", reason=reason,
                worker_id=member.worker_id, seq=seq,
                batch_version=version, learner_version=current,
            )

    # -- apply loop ----------------------------------------------------------

    def _apply(self, item) -> None:
        worker_id, seq, batch_version, payload = item
        with self.lock:
            current = self.version
        if batch_version < current - self.max_staleness:
            # the version advanced while the batch queued: the bound is
            # on what is APPLIED, so refuse here too - counted, and the
            # watermark already covers the seq so the actor (correctly)
            # does not re-send this batch
            with self.lock:
                self.stale_rejected += 1
            if self.recorder.enabled:
                self.recorder.record(
                    "experience_reject", reason="stale_at_apply",
                    worker_id=worker_id, seq=seq,
                    batch_version=batch_version, learner_version=current,
                )
            return
        if payload.size != self.num_params + 1 or not np.isfinite(
            payload
        ).all():
            # a poisoned batch (chaos nan injection, torn payload) must
            # not kill the learner mid-fleet: count and drop, loudly
            with self.lock:
                self.poisoned += 1
            log.warning(
                f"dropping poisoned experience batch: worker-id "
                f"{worker_id} seq {seq} (size {payload.size}, "
                f"finite={bool(np.isfinite(payload).all())})"
            )
            if self.recorder.enabled:
                self.recorder.record(
                    "experience_reject", reason="poisoned",
                    worker_id=worker_id, seq=seq,
                    batch_version=batch_version,
                )
            return
        loss = float(payload[0])
        t0 = time.perf_counter()
        with self.lock:
            new_flat, new_opt = self.update_fn(
                self.params, self.opt_state, payload[1:]
            )
            self.params = np.asarray(new_flat, np.float32)
            self.opt_state = new_opt
            self.version += 1  # strictly monotone, one bump per update
            applied_version = self.version
        self.updates_applied += 1
        staleness = applied_version - 1 - batch_version
        if len(self._staleness_samples) < _MAX_STALENESS_SAMPLES:
            self._staleness_samples.append(staleness)
        self.recorder.note_progress(self.updates_applied)
        if self.recorder.enabled and self.recorder.is_sample_step(
            self.updates_applied
        ):
            # the learner's "step" is one applied update: the standard
            # step event keeps summarize/health/timeline progress
            # semantics; the span lands on the actor lane with the
            # async-specific attrs
            self.recorder.record(
                "step", step=self.updates_applied, loss=loss,
            )
            self.recorder.emit_span(
                "learner_update", t0, time.perf_counter() - t0,
                cat="actor", version=applied_version,
                staleness=staleness, worker_id=worker_id,
                queue_depth=self.queue.qsize(),
            )
        if (
            self.checkpoint_cb is not None
            and self.checkpoint_updates
            and self.updates_applied % self.checkpoint_updates == 0
        ):
            self._submit_checkpoint()
        if self.faults is not None:
            # learner-side chaos (the failover drill): kill/respawn
            # addressed at the learner fires between applied updates,
            # never mid-update
            self.faults.maybe_kill(step=self.updates_applied)

    def _submit_checkpoint(self) -> None:
        # params/opt are REPLACED per update (never mutated), so the
        # reference pair grabbed under the lock is consistent; the
        # watermark snapshot may run AHEAD of the applied state (a batch
        # enqueued but not yet applied) - the safe direction: a restart
        # can lose bounded enqueued work but can never re-apply
        with self.lock:
            flat, opt, version = self.params, self.opt_state, self.version
        self.checkpoint_cb(
            version, flat, opt, self.roster.watermarks(), self.counters()
        )

    def counters(self) -> dict:
        with self.lock:
            return {
                "accepted": self.accepted,
                "duplicates": self.duplicates,
                "stale_rejected": self.stale_rejected,
                "queue_sheds": self.queue_sheds,
                "poisoned": self.poisoned,
            }

    # -- wire service --------------------------------------------------------

    def _register_actor(self, rank: int, worker_id: int) -> None:
        """REGISTER -> STATE_SYNC: roster the (re)join and reply with
        the current params, the learner's params VERSION (the step slot
        of the PS state-sync header - what the actor stamps its batches
        with) and the actor's push-seq watermark (where its experience
        numbering resumes)."""
        member = self.roster.join(worker_id, rank)
        self._tolerated.pop(rank, None)
        with self.lock:
            # the span window lives entirely inside the params lock:
            # concurrent join threads serialize here, so the member-lane
            # state_sync spans can never partially overlap on the
            # learner's timeline row (the trace validator forbids it)
            t0 = time.perf_counter()
            version = self.version
            seq_watermark = member.push_seq
            # protocol: ps reply REGISTER
            protocol.send_state_sync(  # noqa: PD302 - deliberate: the reply must quote the params/version pair it snapshotted (see comment above)
                self.comm, rank, self.params, version, seq_watermark
            )
            if self.recorder.enabled:
                self.recorder.emit_span(
                    "state_sync", t0, time.perf_counter() - t0,
                    cat="member", worker_id=worker_id, rank_slot=rank,
                    incarnation=member.incarnation, step=version,
                    seq=seq_watermark,
                )
        log.info(
            f"state sync: actor worker-id {worker_id} (rank {rank}, "
            f"incarnation {member.incarnation}) <- {self.num_params} "
            f"params @ version {version}, push-seq watermark "
            f"{seq_watermark}"
        )
        with self._member_cv:
            self._member_cv.notify_all()

    def _serve_actor(self, rank: int, gen: int) -> None:
        while True:
            with self._gen_lock:
                stale = self._thread_gen.get(rank) != gen
            if stale:
                # the rank's socket slot was re-accepted: the new fd
                # belongs to the replacement thread
                return
            # protocol: ps handles DONE, REGISTER, DEREGISTER, PARAMS_AT, EXPERIENCE
            opcode, _, seq = protocol.recv_request(
                self.comm, rank, self.num_params
            )
            if opcode == protocol.OP_DONE:
                self.roster.complete(rank)
                with self._member_cv:
                    self._member_cv.notify_all()
                return
            if opcode == protocol.OP_REGISTER:
                self._register_actor(rank, worker_id=seq or rank)
                continue
            if opcode == protocol.OP_DEREGISTER:
                self.roster.drain(rank, seq=seq)
                with self._member_cv:
                    self._member_cv.notify_all()
                return
            if opcode == protocol.OP_PARAMS_AT:
                with self.lock:
                    # hold contract: version and params are one atomic
                    # pair; a send outside the lock could quote a version
                    # the params no longer match
                    # protocol: ps reply PARAMS_AT
                    protocol.send_params_at(  # noqa: PD302 - deliberate send-under-lock, see comment
                        self.comm, rank, self.version, self.params
                    )
                continue
            if opcode == protocol.OP_EXPERIENCE:
                version, payload = protocol.recv_experience_ext(
                    self.comm, rank
                )
                status, current, throttle = self.ingest(
                    rank, seq, version, payload
                )
                # protocol: ps reply EXPERIENCE
                protocol.send_experience_reply(
                    self.comm, rank, status, current, throttle
                )
                continue
            raise RuntimeError(
                f"learner received unsupported opcode {opcode} from "
                f"rank {rank} (the streaming wire speaks REGISTER/"
                "DEREGISTER/DONE/PARAMS_AT/EXPERIENCE)"
            )

    def _mark_dead(self, rank: int, exc: BaseException) -> None:
        log.warning(
            f"actor rank {rank} dropped from the fleet "
            f"({type(exc).__name__}: {exc}); awaiting rejoin"
        )
        self.roster.mark_dead(
            rank, error=f"{type(exc).__name__}: {str(exc)[:200]}"
        )

    # -- serve ---------------------------------------------------------------

    def serve(self) -> np.ndarray:
        """Accept actors, ingest experience, apply updates; block until
        the fleet reaches a terminal state - every rostered actor done
        or drained, no dead actor still inside its rejoin window, and
        the queue drained.  An empty roster waits ``join_timeout`` for
        the first actor (a restarted learner's roster is pre-seeded
        DEAD from the checkpoint watermarks, so it waits for the live
        fleet to reconnect)."""
        serve_tm0 = time.perf_counter()
        stop_accept = threading.Event()
        threads: list[threading.Thread] = []

        def guarded(rank, gen):
            try:
                self._serve_actor(rank, gen)
            except BaseException as exc:  # noqa: BLE001 - fleet-tolerated
                with self._gen_lock:
                    if self._thread_gen.get(rank) != gen:
                        log.info(
                            f"stale service thread for rank {rank} "
                            f"exited ({type(exc).__name__}); rank re-owned"
                        )
                    else:
                        self._tolerated[rank] = exc
                        self._mark_dead(rank, exc)
            finally:
                with self._member_cv:
                    self._member_cv.notify_all()

        def spawn(rank):
            with self._gen_lock:
                gen = self._thread_gen.get(rank, 0) + 1
                self._thread_gen[rank] = gen
            t = threading.Thread(
                target=guarded, args=(rank, gen), daemon=True
            )
            t.start()
            threads.append(t)

        # BEFORE the acceptor: the reserve reallocates the peer table
        self.comm.reserve(self.max_world)

        def accept_loop():
            while not stop_accept.is_set():
                rank = self.comm.accept_peer(timeout_s=0.25)
                if rank is not None:
                    log.info(
                        f"actor accept: rank {rank} connected; awaiting "
                        "REGISTER"
                    )
                    spawn(rank)

        acceptor = threading.Thread(target=accept_loop, daemon=True)
        acceptor.start()

        try:
            while True:
                try:
                    item = self.queue.get(timeout=0.2)
                except queue_mod.Empty:
                    if self._fleet_terminal(serve_tm0):
                        break
                    continue
                self._apply(item)
        finally:
            stop_accept.set()
            acceptor.join(timeout=5.0)
            for t in list(threads):
                t.join(timeout=5.0)

        if self.updates_applied == 0 and self._tolerated:
            rank, exc = next(iter(self._tolerated.items()))
            raise RuntimeError(
                f"streaming learner applied no updates and actor "
                f"rank(s) {sorted(self._tolerated)} died (first: rank "
                f"{rank})"
            ) from exc
        if self.checkpoint_cb is not None:
            # the authoritative final state, written synchronously
            self._submit_checkpoint()
        self._summarize(serve_tm0)
        with self.lock:
            return self.params

    def _fleet_terminal(self, serve_tm0: float) -> bool:
        members = self.roster.members()
        now = time.perf_counter()
        if not members:
            # nobody ever joined: give the fleet one join window
            return now - serve_tm0 > self.join_timeout
        joined = any(m.state == membership.JOINED for m in members)
        awaiting = any(
            m.state == membership.DEAD and m.died_tm is not None
            and now - m.died_tm < self.join_timeout
            for m in members
        )
        return not joined and not awaiting and self.queue.empty()

    def _summarize(self, serve_tm0: float) -> None:
        duration = time.perf_counter() - serve_tm0
        self.duration_s = duration
        counts = self.roster.counts()
        samples = sorted(self._staleness_samples)
        # one consistent snapshot of the guarded counters (the service
        # threads are joined by now, but the guard contract is absolute)
        cnt = self.counters()
        with self.lock:
            version = self.version

        def pct(q):
            if not samples:
                return None
            return int(samples[min(len(samples) - 1,
                                   int(q * len(samples)))])

        log.info(
            f"streaming learner done: {self.updates_applied} updates "
            f"(version {version}), {cnt['accepted']} batches "
            f"accepted, {cnt['duplicates']} duplicate(s), "
            f"{cnt['stale_rejected']} stale-rejected, "
            f"{cnt['queue_sheds']} queue shed(s), roster {counts}"
        )
        if not self.recorder.enabled:
            return
        self.recorder.record(
            "learner_summary", updates=self.updates_applied,
            final_version=version, rejoins=self.roster.rejoins,
            **cnt,
        )
        # the run_summary carries the streaming verdict so
        # `pdrnn-metrics summarize`/`health` read experience rates and
        # rejection counters off the learner's sidecar like any other
        # run outcome (None-vs-0 on non-streaming runs is the summary's
        # gate on these keys being PRESENT at all)
        self.recorder.record(
            "run_summary",
            duration_s=duration,
            steps=self.updates_applied,
            roster=counts, rejoins=self.roster.rejoins,
            experience_batches=cnt["accepted"],
            experience_per_s=(
                cnt["accepted"] / duration if duration > 0 else 0.0
            ),
            updates_per_s=(
                self.updates_applied / duration if duration > 0 else 0.0
            ),
            stale_rejected=cnt["stale_rejected"],
            queue_sheds=cnt["queue_sheds"],
            duplicates=cnt["duplicates"],
            poisoned=cnt["poisoned"],
            staleness_p50=pct(0.50),
            staleness_p95=pct(0.95),
            final_version=version,
        )
        self.recorder.flush()


def run_learner(args):
    """The learner process (rank 0 of the streaming world).

    Listener-only transport: the learner never performs a rendezvous -
    actors star-join whenever they come up, which is exactly what makes
    RESTART cheap (a ``--resume auto`` reincarnation re-listens on the
    same port and the live fleet's transport retries find it)."""
    import jax
    import optax
    from jax.flatten_util import ravel_pytree

    from pytorch_distributed_rnn_tpu.obs import MetricsRecorder
    from pytorch_distributed_rnn_tpu.param_server.runner import (
        AsyncCheckpointWriter,
        _build_model_and_flat_params,
        _load_datasets,
    )
    from pytorch_distributed_rnn_tpu.resilience import FaultSchedule
    from pytorch_distributed_rnn_tpu.runtime import Communicator
    from pytorch_distributed_rnn_tpu.training import families

    logging.basicConfig(level=args.log)
    families.require_family(args, ("rnn", "char"), "streaming")
    training_set, _, _ = _load_datasets(args)
    _, flat, unravel = _build_model_and_flat_params(
        args, training_set, args.seed
    )
    optimizer = optax.adam(args.learning_rate)
    opt_state = optimizer.init(unravel(flat))

    # failover bootstrap: restore params + optimizer + version +
    # watermarks from the newest VALID checkpoint (corrupt files are
    # skipped by the loader) - the whole exactly-once state, because
    # it was written as one atomic file
    version = 0
    watermarks: dict | None = None
    ckpt_dir = getattr(args, "checkpoint_directory", None)
    if getattr(args, "resume", None) is not None and ckpt_dir:
        from pytorch_distributed_rnn_tpu.training.checkpoint import (
            find_latest_checkpoint,
            load_checkpoint,
        )

        latest = find_latest_checkpoint(ckpt_dir)
        if latest is not None:
            params, opt_state, meta = load_checkpoint(
                latest, unravel(flat), opt_state
            )
            flat = np.asarray(ravel_pytree(params)[0], np.float32)
            extra = meta.get("extra") or {}
            version = int(extra.get("version", meta["epoch"]))
            watermarks = extra.get("watermarks")
            log.info(
                f"learner bootstrap: restored {latest} @ version "
                f"{version}, {len(watermarks or {})} actor watermark(s)"
            )

    @jax.jit
    def _update(flat_params, opt_state, flat_grads):
        params = unravel(flat_params)
        grads = unravel(flat_grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_flat, _ = ravel_pytree(new_params)
        return new_flat, opt_state

    recorder = MetricsRecorder.resolve(
        args, rank=0, meta={"role": "learner"}
    )
    plane = None
    if recorder.enabled:
        from pytorch_distributed_rnn_tpu.obs.live import LivePlane
        from pytorch_distributed_rnn_tpu.obs.watchdog import (
            install_stack_dump_handler,
        )

        install_stack_dump_handler(recorder.path)
        plane = LivePlane.resolve(args, recorder, rank=0, role="learner")

    faults = FaultSchedule.resolve(args, rank=0)
    if faults is not None and getattr(args, "stream_rejoin", False):
        # a reincarnated learner must not replay the deterministic
        # lifetime fault that killed its predecessor
        faults = faults.for_rejoin()

    ckpt_writer = None
    save_version = [version]

    def _save_learner_checkpoint(version_now, flat_now, opt_now,
                                 watermarks_now, counters_now):
        from pytorch_distributed_rnn_tpu.training.checkpoint import (
            save_checkpoint,
        )

        path = save_checkpoint(
            ckpt_dir, int(version_now) - 1, unravel(flat_now), opt_now,
            loss=0.0,
            extra={
                "version": int(version_now),
                "watermarks": {
                    str(k): int(v) for k, v in watermarks_now.items()
                },
                "counters": counters_now,
            },
        )
        save_version[0] = int(version_now)
        log.info(f"learner checkpoint: {path} @ version {version_now}")

    checkpoint_updates = int(
        getattr(args, "checkpoint_updates", 0) or 0
    )
    if ckpt_dir and checkpoint_updates:
        ckpt_writer = AsyncCheckpointWriter(_save_learner_checkpoint)

    comm = Communicator.listener(
        int(args.master_port), 1 + int(args.actors) + 8
    )
    try:
        learner = ExperienceLearner(
            comm, flat, opt_state, _update,
            max_staleness=int(args.max_staleness),
            queue_depth=int(args.queue_depth),
            throttle_hint_s=float(
                getattr(args, "throttle_hint_s", 0.05)
            ),
            join_timeout=float(getattr(args, "join_timeout", 30.0)),
            max_world=1 + int(args.actors) + 8,
            version=version,
            watermarks=watermarks,
            checkpoint_cb=(
                ckpt_writer.submit if ckpt_writer is not None else None
            ),
            checkpoint_updates=checkpoint_updates,
            recorder=recorder,
            faults=faults,
        )
        final = learner.serve()
        if getattr(args, "results", None):
            # the CI assertion gate reads these: the final incarnation
            # (failover drill included) owns the file
            import json

            duration = learner.duration_s or 1e-9
            with open(args.results, "w") as f:
                json.dump(
                    {
                        "updates": learner.updates_applied,
                        "final_version": learner.version,
                        "duration_s": learner.duration_s,
                        "updates_per_s": (
                            learner.updates_applied / duration
                        ),
                        "rejoins": learner.roster.rejoins,
                        "roster": learner.roster.counts(),
                        "watermarks": {
                            str(k): int(v) for k, v in
                            learner.roster.watermarks().items()
                        },
                        **learner.counters(),
                    },
                    f,
                )
        if ckpt_writer is not None:
            # drain the coalescing writer, then persist the
            # authoritative final state synchronously (no lock held)
            ckpt_writer.close()
            _save_learner_checkpoint(
                learner.version, learner.params, learner.opt_state,
                learner.roster.watermarks(), learner.counters(),
            )
    finally:
        if ckpt_writer is not None:
            ckpt_writer.close()
        comm.close()
        recorder.close()
        if plane is not None:
            plane.close()
    return final
