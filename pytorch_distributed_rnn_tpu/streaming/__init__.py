"""Streaming actor/learner training (Podracer-style, `pdrnn-stream`).

The repo's first ASYNCHRONOUS workload: N actor processes continuously
roll out the motion/char model on their data shard and push
version-stamped experience batches over the parameter-server wire
(``param_server/protocol.py`` EXPERIENCE/PARAMS_AT ops); ONE learner
ingests them through a bounded queue and applies jitted updates off the
actors' cadence - the Anakin/Sedna split from the Podracer
architectures paper (PAPERS.md), built on the elastic-membership /
chaos machinery of PRs 2/7/11.

Robustness is the headline:

- **bounded staleness** - every batch carries the params version it was
  generated under; the learner rejects batches older than
  ``--max-staleness`` (counted, never silently dropped) and actors
  refresh params on rejection;
- **exactly-once ingest** - per-actor push-seq watermarks on the
  elastic roster, persisted WITH the params in every learner
  checkpoint, so a retried / post-respawn / post-failover push is never
  applied twice;
- **elastic actor fleet** - actors REGISTER/STATE_SYNC mid-run, drain
  on SIGTERM, and are respawned under stable worker-ids by an
  :class:`~..launcher.supervisor.ActorSupervisor`;
- **backpressure** - a full learner queue NACKs with a throttle hint
  instead of stalling the wire;
- **learner failover** - crash-safe checkpoints of
  params+optimizer+version+watermarks; a ``--resume auto`` restart
  re-listens on the same port and live actors reconnect and resume
  above their watermark.
"""

from pytorch_distributed_rnn_tpu.streaming.actor import StreamingActor, run_actor
from pytorch_distributed_rnn_tpu.streaming.learner import (
    ExperienceLearner,
    run_learner,
)
from pytorch_distributed_rnn_tpu.streaming.runner import build_parser, main, run

__all__ = [
    "ExperienceLearner",
    "StreamingActor",
    "build_parser",
    "main",
    "run",
    "run_actor",
    "run_learner",
]
