from pytorch_distributed_rnn_tpu.streaming.runner import main

if __name__ == "__main__":
    main()
