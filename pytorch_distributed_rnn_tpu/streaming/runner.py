"""``pdrnn-stream``: launch + supervise the streaming actor/learner world.

Topology (single-machine fake-cluster, SURVEY §4.2): rank 0 is the
learner (listener transport - it never joins a rendezvous), ranks >= 1
are actors that star-dial it.  BOTH sides are supervised, differently:

- the LEARNER runs under its own one-slot :class:`RespawnSupervisor`:
  a crash is respawned with ``--resume auto`` forced, so the
  reincarnation restores params + version + watermarks from its
  crash-safe checkpoint and re-listens on the same port (live actors
  reconnect via their transport-retry path) - the failover drill;
- the ACTOR fleet runs under an :class:`ActorSupervisor`: a dead actor
  is respawned under its stable worker-id (watermark carries over), the
  pool floor is ``--min-actors``, and ``--join-after``/``--join-actors``
  drives the elastic-join drill by :meth:`adopt`-ing brand-new actors
  mid-run.

Supervision events from both supervisors flow through the shared
``supervision_alert_hook`` (``launcher/supervisor.py``) onto the
runner's own sidecar and - when a live plane is up - the fleet
aggregator, same contract as the PS and MPMD runners.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import time

log = logging.getLogger(__name__)


def _spawn_entry(args, rank, worker_id=None, rejoin=False):
    # force CPU in spawned children: each child would otherwise race to
    # claim the single local accelerator
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
    if rank == 0:
        from pytorch_distributed_rnn_tpu.streaming.learner import run_learner

        if rejoin:
            # the failover path: a respawned learner MUST restore the
            # exactly-once state its predecessor checkpointed
            args.resume = "auto"
            args.stream_rejoin = True
        run_learner(args)
    else:
        from pytorch_distributed_rnn_tpu.streaming.actor import run_actor

        run_actor(args, rank, worker_id=worker_id, rejoin=rejoin)


def run(args):
    from pytorch_distributed_rnn_tpu.launcher.supervisor import (
        ActorSupervisor,
        RespawnSupervisor,
        supervision_alert_hook,
    )
    from pytorch_distributed_rnn_tpu.obs import MetricsRecorder
    from pytorch_distributed_rnn_tpu.obs.live import resolve_event_push
    from pytorch_distributed_rnn_tpu.resilience import FaultSchedule

    logging.basicConfig(level=args.log)
    num_actors = int(args.actors)
    if num_actors < 1:
        raise SystemExit("pdrnn-stream needs --actors >= 1")
    join_actors = int(getattr(args, "join_actors", 0) or 0)
    join_after = float(getattr(args, "join_after", 0.0) or 0.0)
    if join_after <= 0:
        join_actors = 0

    # bridge the chaos schedule's net events onto the transport contract
    # BEFORE spawning (children inherit the env)
    faults = FaultSchedule.resolve(args)
    if faults is not None:
        faults.export_network()

    ctx = mp.get_context("spawn")

    def spawn_learner(rank, worker_id, rejoin):
        p = ctx.Process(target=_spawn_entry, args=(args, 0, 0, rejoin))
        p.start()
        return p

    def spawn_actor(rank, worker_id, rejoin):
        p = ctx.Process(
            target=_spawn_entry, args=(args, rank, worker_id, rejoin)
        )
        p.start()
        return p

    # the runner's own sidecar (rank past every actor + joiner slot):
    # supervision alerts land here AND on the aggregator when a live
    # plane is up - the uniform hook the PS/MPMD runners share
    sup_rank = 1 + num_actors + join_actors
    recorder = MetricsRecorder.resolve(
        args, rank=sup_rank, meta={"role": "actor-sup"}
    )
    on_event = supervision_alert_hook(
        recorder=recorder,
        push=resolve_event_push(args, role="actor-sup"),
    )

    learner_sup = RespawnSupervisor(
        spawn_learner, min_workers=1,
        max_respawns=int(getattr(args, "learner_respawns", 2)),
        on_event=on_event,
    )
    learner_sup.launch([0])
    actor_sup = ActorSupervisor(
        spawn_actor,
        min_workers=int(getattr(args, "min_actors", 1) or 1),
        max_respawns=int(args.max_respawns),
        on_event=on_event,
    )
    actor_sup.launch(range(1, num_actors + 1))

    join_pending = list(
        range(num_actors + 1, num_actors + 1 + join_actors)
    )
    t0 = time.monotonic()
    failed_reason = None
    try:
        while True:
            healthy = learner_sup.poll() and actor_sup.poll()
            if not healthy:
                failed_reason = "pool collapsed below its floor"
                break
            if join_pending and time.monotonic() - t0 >= join_after:
                rank = join_pending.pop(0)
                log.info(
                    f"elastic join drill: adopting actor rank {rank} "
                    f"at t+{time.monotonic() - t0:.1f}s"
                )
                actor_sup.adopt(rank)
            learner_slot = learner_sup.slots[0]
            if learner_slot.completed or learner_slot.failed:
                break
            time.sleep(0.05)
        # the learner exits only once the fleet is terminal - give the
        # actors a short grace to finish reaping, then settle verdicts
        grace = time.monotonic() + 10.0
        while time.monotonic() < grace:
            actor_sup.poll()
            if all(
                s.completed or s.failed for s in actor_sup.slots.values()
            ):
                break
            time.sleep(0.05)
    finally:
        actor_sup.shutdown()
        learner_sup.shutdown()
        recorder.close()

    lv = learner_sup.verdict()
    av = actor_sup.verdict()
    log.info(f"stream supervisors: learner {lv}, actors {av}")
    if failed_reason is None and not learner_sup.slots[0].completed:
        failed_reason = "learner failed past its respawn budget"
    if failed_reason is None and av["failed"]:
        failed_reason = f"{av['failed']} actor(s) failed past budget"
    if failed_reason is not None:
        raise SystemExit(
            f"streaming run failed: {failed_reason} "
            f"(learner {lv}, actors {av})"
        )
    return 0


def build_parser(parser=None):
    import argparse
    from pathlib import Path

    if parser is None:
        parser = argparse.ArgumentParser(
            prog="pdrnn-stream",
            description=(
                "streaming actor/learner training: bounded-staleness "
                "experience ingest, elastic actor fleet, learner "
                "failover"
            ),
        )
    # family/data surface (shared with the PS entrypoints)
    parser.add_argument("--dataset-path", default=Path("data"), type=Path)
    parser.add_argument("--output-path", default=None, type=Path)
    parser.add_argument("--validation-fraction", default=0.1, type=float)
    parser.add_argument("--model", default="rnn", choices=["rnn", "char"])
    parser.add_argument("--hidden-units", default=32, type=int)
    parser.add_argument("--stacked-layer", default=2, type=int)
    parser.add_argument("--cell", default="lstm", choices=["lstm", "gru"])
    parser.add_argument("--seq-length", default=None, type=int)
    # deterministic rollouts: the actor's jitted program applies the
    # model without a dropout stream (the learner owns no RNG either)
    parser.add_argument("--dropout", default=0.0, type=float)
    parser.add_argument("--batch-size", default=128, type=int)
    parser.add_argument("--learning-rate", default=0.0025, type=float)
    parser.add_argument("--seed", default=0, type=int)
    # topology
    parser.add_argument("--actors", default=3, type=int)
    parser.add_argument("--master-address", default="127.0.0.1")
    parser.add_argument("--master-port", default=29600, type=int)
    # streaming semantics
    parser.add_argument(
        "--actor-steps", default=120, type=int,
        help="experience batches per actor STREAM (a respawn resumes "
        "above its watermark, not from zero)",
    )
    parser.add_argument(
        "--max-staleness", default=4, type=int, metavar="K",
        help="reject batches generated more than K params versions ago "
        "(counted, never silently dropped; actors refresh on rejection)",
    )
    parser.add_argument(
        "--queue-depth", default=8, type=int,
        help="bounded learner ingest queue; a full queue NACKs with a "
        "throttle hint (backpressure) instead of stalling the wire",
    )
    parser.add_argument(
        "--refresh-every", default=2, type=int,
        help="proactively refresh actor params once the learner version "
        "has advanced this far past the actor's",
    )
    parser.add_argument("--throttle-hint-s", default=0.05, type=float)
    parser.add_argument("--transport-retries", default=3, type=int)
    parser.add_argument(
        "--reconnect-deadline", dest="reconnect_deadline_s",
        default=30.0, type=float,
        help="per-actor budget to re-dial + re-REGISTER after the "
        "learner restarts",
    )
    parser.add_argument(
        "--join-timeout", default=15.0, type=float,
        help="learner-side window a dead actor is awaited for rejoin",
    )
    # robustness drills
    parser.add_argument("--max-respawns", default=3, type=int,
                        help="per-actor respawn budget")
    parser.add_argument("--learner-respawns", default=2, type=int)
    parser.add_argument("--min-actors", default=1, type=int)
    parser.add_argument(
        "--join-after", default=0.0, type=float, metavar="S",
        help="adopt --join-actors brand-new actors S seconds into the "
        "run (0 disables the elastic-join drill)",
    )
    parser.add_argument("--join-actors", default=1, type=int)
    parser.add_argument("--checkpoint-directory", default=None, type=Path)
    parser.add_argument(
        "--checkpoint-updates", default=0, type=int,
        help="learner checkpoint cadence in applied updates (0 = off); "
        "each checkpoint atomically bundles params + optimizer + "
        "version + per-actor watermarks",
    )
    parser.add_argument(
        "--resume", default=None, choices=["auto"],
        help="bootstrap the learner from the newest valid checkpoint "
        "(forced for a supervised learner respawn)",
    )
    parser.add_argument(
        "--results", default=None, type=Path,
        help="learner writes its final counters here as JSON",
    )
    # obs + chaos
    parser.add_argument("--faults", default=None,
                        help="chaos schedule, e.g. 'step:20:respawn@2'")
    parser.add_argument("--metrics", default=None,
                        help="metrics sidecar path (per-process -r<k>)")
    parser.add_argument("--live", default=None,
                        help="live plane spec (serve on the learner)")
    parser.add_argument("--log", default="INFO")
    return parser


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    from pytorch_distributed_rnn_tpu.utils import leakcheck

    # before any socket/thread/file exists, so every acquisition is seen
    leakcheck.maybe_install()
    run(args)


# ---------------------------------------------------------------------------
# trace-registry provider (lint deep pass)


def _lint_model():
    from pytorch_distributed_rnn_tpu.models import MotionModel

    # tiny abstract geometry: the rules are shape-generic
    return MotionModel(input_dim=9, hidden_dim=8, layer_dim=1,
                       output_dim=6)


def declare_trace_entries(register):
    """The two streaming programs for ``pdrnn-lint --deep``: the actor's
    jitted rollout value_and_grad and the learner's flat update - the
    exact programs :mod:`.actor` / :mod:`.learner` jit, built abstractly
    (no dataset, no transport)."""
    from pytorch_distributed_rnn_tpu.lint.trace_registry import sds

    def build_actor_grad():
        import argparse

        import jax
        import jax.numpy as jnp

        from pytorch_distributed_rnn_tpu.streaming.actor import (
            make_rollout_loss,
        )

        model = _lint_model()
        params = jax.tree.map(
            lambda a: sds(a.shape, a.dtype),
            model.init(jax.random.PRNGKey(0)),
        )
        loss_fn = make_rollout_loss(
            argparse.Namespace(model="rnn"), model
        )
        batch = (sds((4, 12, 9), jnp.float32), sds((4,), jnp.int32))
        return jax.value_and_grad(loss_fn), (params, batch)

    def build_learner_update():
        import jax
        import jax.numpy as jnp
        import optax
        from jax.flatten_util import ravel_pytree

        model = _lint_model()
        params = model.init(jax.random.PRNGKey(0))
        flat, unravel = ravel_pytree(params)
        optimizer = optax.adam(1e-3)

        def update(flat_params, opt_state, flat_grads):
            p = unravel(flat_params)
            g = unravel(flat_grads)
            updates, opt_state = optimizer.update(g, opt_state, p)
            new_flat, _ = ravel_pytree(optax.apply_updates(p, updates))
            return new_flat, opt_state

        n = int(flat.size)
        opt_abstract = jax.tree.map(
            lambda a: sds(a.shape, a.dtype), optimizer.init(params)
        )
        return update, (
            sds((n,), jnp.float32), opt_abstract, sds((n,), jnp.float32),
        )

    path = "pytorch_distributed_rnn_tpu/streaming"
    register(
        name="streaming.actor_grad", family="streaming",
        path=f"{path}/actor.py", build=build_actor_grad,
        kind="train_step",
    )
    register(
        name="streaming.learner_update", family="streaming",
        path=f"{path}/learner.py", build=build_learner_update,
        kind="update",
    )
