"""Streaming actor: continuous rollouts, version-stamped experience.

The actor half of the actor/learner split (``streaming/__init__.py``).
An actor owns its data shard (stable worker-id, so a respawn re-reads
ITS stream) and a jitted forward+backward program; the learner owns the
optimizer.  Per step the actor computes a gradient batch under its
current params, stamps it with the params VERSION those rollouts were
generated under, and pushes it over the PS wire - then reacts to the
learner's verdict:

  OK / DUPLICATE  applied (or already applied - a retry landed twice):
                  move on.
  STALE           the batch exceeded the learner's staleness bound:
                  refresh params via PARAMS_AT and RECOMPUTE the same
                  batch under the fresh version - work is re-done, not
                  lost, and the re-send carries the SAME seq (exactly-
                  once bookkeeping is the learner's watermark).
  BACKOFF         the learner queue is full: sleep the throttle hint
                  and re-send the same payload - backpressure without
                  abandoning the batch.

Membership is join-protocol-only: EVERY actor - launch-time, late
joiner, respawn - star-dials the learner's listener and REGISTERs under
its stable worker-id (there is no rendezvous world), which is also what
makes LEARNER failover survivable: when an exchange exhausts its
transport retries the actor re-dials, re-REGISTERs, resumes its seq
above the watermark the restarted learner restored from its checkpoint,
and replays the in-flight push (a duplicate verdict means the dead
incarnation already applied it).

SIGTERM is a drain: finish the in-flight exchange, DEREGISTER, exit 0.
"""

from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from pytorch_distributed_rnn_tpu.data.loader import DataLoader
from pytorch_distributed_rnn_tpu.data.sampler import DistributedSampler
from pytorch_distributed_rnn_tpu.param_server import protocol
from pytorch_distributed_rnn_tpu.resilience.retry import retry_transport
from pytorch_distributed_rnn_tpu.runtime import Communicator
from pytorch_distributed_rnn_tpu.training import families

log = logging.getLogger(__name__)

# an actor that drains on SIGTERM exits 0 on purpose (the supervisor
# must not respawn a voluntary leave) - same contract as the PS worker
DRAIN_EXIT_CODE = 0


def make_rollout_loss(args, model):
    """The family's scalar loss over one ``(x, y)`` batch - the
    standalone surface the actor jits ``value_and_grad`` over (the
    Trainer mixin stack is a training-loop contract; the actor has no
    optimizer, no epochs, no eval, so it carries only the loss)."""
    from pytorch_distributed_rnn_tpu.ops.losses import cross_entropy_loss

    if families.family_of(args) == "char":

        def loss_fn(params, batch):
            tokens, _ = batch
            logits = model.apply(params, tokens[:, :-1]).astype(
                jnp.float32
            )
            vocab = logits.shape[-1]
            return cross_entropy_loss(
                logits.reshape(-1, vocab), tokens[:, 1:].reshape(-1)
            )

        return loss_fn

    def loss_fn(params, batch):
        x, y = batch
        # labels arrive (B, 1) off the motion loader; the loss wants (B,)
        return cross_entropy_loss(
            model.apply(params, x), jnp.asarray(y).reshape(-1)
        )

    return loss_fn


class StreamingActor:
    """One actor process: shard -> rollouts -> stamped experience."""

    def __init__(self, args, model, training_set, *, rank: int,
                 worker_id: int, drain_signal=None, faults=None,
                 recorder=None):
        from pytorch_distributed_rnn_tpu.obs.recorder import NULL_RECORDER

        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self.args = args
        self.rank = int(rank)
        self.worker_id = int(worker_id)
        self._drain = drain_signal
        self.faults = faults
        self.actor_steps = int(args.actor_steps)
        self.refresh_every = max(1, int(getattr(args, "refresh_every", 2)))
        self._transport_retries = int(
            getattr(args, "transport_retries", 3)
        )
        self._reconnect_deadline = float(
            getattr(args, "reconnect_deadline_s", 30.0)
        )
        num_actors = max(1, int(args.actors))
        # the shard follows the stable worker-id; a late joiner beyond
        # the launch fleet wraps onto an existing shard (experience
        # semantics tolerate overlap - batches just repeat sooner)
        shard = (self.worker_id - 1) % num_actors
        sampler = DistributedSampler(
            len(training_set),
            num_replicas=num_actors,
            rank=shard,
            seed=args.seed or 0,
        )
        self._sampler = sampler
        self._loader = DataLoader(
            training_set,
            batch_size=max(1, int(args.batch_size) // num_actors),
            sampler=sampler,
        )
        self._epoch = 0
        self._batches = iter(())
        self._grad_fn = jax.jit(
            jax.value_and_grad(make_rollout_loss(args, model))
        )
        params = model.init(
            jax.random.PRNGKey(args.seed if args.seed is not None else 0)
        )
        flat, self._unravel = ravel_pytree(params)
        self.params = params
        self.num_params = int(flat.size)
        self.version = 0  # the learner params version rollouts run under
        self.seq = 0  # push numbering; resumes above the watermark
        self.comm = None
        self._connect(register_what="register")

    # -- join protocol -------------------------------------------------------

    def _dial(self):
        num_actors = max(1, int(self.args.actors))
        return Communicator(
            self.args.master_address, int(self.args.master_port),
            self.rank, max(self.rank + 1, 1 + num_actors), star=True,
        )

    def _connect(self, register_what: str) -> None:
        """Star-dial the learner's listener and REGISTER: the ONLY entry
        path (launch, late join, respawn, learner-failover reconnect all
        look identical on the wire).  The STATE_SYNC reply carries the
        current params, the learner's params version, and this worker-
        id's push-seq watermark - seq numbering resumes ABOVE it, so
        anything the learner (or its dead incarnation) already applied
        dedupes away."""
        self.comm = self._exchange(
            self._dial, what=f"{register_what} dial"
        )

        def register():
            # protocol: ps request REGISTER
            protocol.send_request(
                self.comm, protocol.OP_REGISTER, seq=self.worker_id
            )
            # protocol: ps handles STATE_SYNC
            return protocol.recv_state_sync(self.comm, self.num_params)

        t0 = time.perf_counter()
        flat, version, seq_wm = self._exchange(register, what=register_what)
        self._adopt(flat, version)
        self.seq = max(self.seq, int(seq_wm))
        log.info(
            f"state sync: actor worker-id {self.worker_id} (rank "
            f"{self.rank}) joined @ learner version {version}, push-seq "
            f"watermark {seq_wm}"
        )
        if self.recorder.enabled:
            self.recorder.emit_span(
                "state_sync", t0, time.perf_counter() - t0, cat="member",
                worker_id=self.worker_id, rank_slot=self.rank,
                step=int(version), seq=int(seq_wm),
            )

    def _reconnect(self) -> bool:
        """Learner-failover path: the wire died past its retry budget.
        Re-dial + re-REGISTER under a backoff loop until
        ``--reconnect-deadline`` expires; returns False when the learner
        never came back (the actor then dies loudly)."""
        deadline = time.perf_counter() + self._reconnect_deadline
        attempt = 0
        if self.comm is not None:
            try:
                self.comm.close()
            except Exception:  # noqa: BLE001 - the fd may already be dead
                pass
            self.comm = None
        while time.perf_counter() < deadline:
            attempt += 1
            try:
                self._connect(register_what="reconnect")
            except Exception as exc:  # noqa: BLE001 - retried until deadline
                log.warning(
                    f"actor worker-id {self.worker_id}: reconnect "
                    f"attempt {attempt} failed ({exc}); retrying"
                )
                time.sleep(min(2.0, 0.2 * attempt))
                continue
            log.info(
                f"actor worker-id {self.worker_id} reconnected after "
                f"{attempt} attempt(s); resuming above seq {self.seq}"
            )
            if self.recorder.enabled:
                self.recorder.record(
                    "actor_reconnect", worker_id=self.worker_id,
                    attempts=attempt, seq=self.seq,
                    version=self.version,
                )
            return True
        return False

    # -- wire helpers --------------------------------------------------------

    def _exchange(self, fn, what: str, seq: int | None = None):
        """One exchange under the transport retry policy (whole-exchange
        retries; pushes are safe because the seq header dedupes)."""
        return retry_transport(
            fn, retries=self._transport_retries, seed=self.rank,
            what=f"{what} (actor {self.worker_id})",
            deadline_s=self._reconnect_deadline,
        )

    def _adopt(self, flat: np.ndarray, version: int) -> None:
        assert flat.size == self.num_params, "parameter size mismatch"
        self.params = self._unravel(jnp.asarray(flat))
        self.version = int(version)

    def _refresh_params(self) -> None:
        def params_at():
            protocol.send_request(self.comm, protocol.OP_PARAMS_AT)  # protocol: ps request PARAMS_AT
            return protocol.recv_params_at(self.comm, self.num_params)

        flat, version = self._exchange(params_at, what="params refresh")
        old = self.version
        self._adopt(flat, version)
        if self.recorder.enabled:
            self.recorder.record(
                "params_refresh", worker_id=self.worker_id,
                from_version=old, to_version=self.version,
            )

    # -- rollout loop --------------------------------------------------------

    def _next_batch(self):
        try:
            return next(self._batches)
        except StopIteration:
            self._sampler.set_epoch(self._epoch)
            self._epoch += 1
            self._batches = iter(self._loader)
            return next(self._batches)

    def _compute(self, batch):
        loss, grads = self._grad_fn(self.params, batch)
        flat_grads, _ = ravel_pytree(grads)
        return float(loss), np.asarray(flat_grads, np.float32)

    def _push(self, seq: int, loss: float, flat_grads: np.ndarray):
        payload = np.concatenate(
            [np.array([loss], np.float32), flat_grads]
        )
        version = self.version

        def push():
            protocol.send_experience(self.comm, seq, version, payload)  # protocol: ps request EXPERIENCE
            return protocol.recv_experience_reply(self.comm)

        return self._exchange(push, what="experience push", seq=seq)

    def _step(self, batch) -> None:
        """One experience batch, pushed to a terminal verdict.  The seq
        is burned ONCE per batch; STALE recomputes under fresh params
        and BACKOFF/reconnect re-send under the SAME seq."""
        step = self.seq  # pre-increment ordinal for fault addressing
        if self.faults is not None:
            self.faults.on_producer_item(step)
            self.faults.maybe_kill(step=step)
        loss, flat_grads = self._compute(batch)
        self.seq += 1
        seq = self.seq
        t0 = time.perf_counter()
        retries = 0
        backoffs = 0
        while True:
            try:
                status, learner_version, throttle = self._push(
                    seq, loss, flat_grads
                )
            except Exception:
                if not self._reconnect():
                    raise
                retries += 1
                continue  # replay the SAME seq; the watermark dedupes
            if status == protocol.EXP_BACKOFF:
                backoffs += 1
                time.sleep(throttle if throttle > 0 else 0.05)
                continue
            if status == protocol.EXP_STALE:
                # past the staleness bound: refresh, RECOMPUTE this
                # batch under the fresh version, re-send the same seq
                self._refresh_params()
                loss, flat_grads = self._compute(batch)
                retries += 1
                continue
            break  # EXP_OK, or EXP_DUPLICATE (already applied)
        if (
            learner_version - self.version >= self.refresh_every
            and status == protocol.EXP_OK
        ):
            # the learner moved on while we rolled out: refresh now so
            # the NEXT batch is stamped close to head (the bounded-
            # staleness contract's proactive half)
            self._refresh_params()
        if self.recorder.enabled:
            dur = time.perf_counter() - t0
            self.recorder.emit_span(
                "experience_push", t0, dur, cat="actor", seq=seq,
                version=self.version, status=int(status),
                retries=retries, backoffs=backoffs,
            )
            if self.recorder.is_sample_step(seq):
                self.recorder.record("step", step=seq, loss=loss)
        self.recorder.note_progress(seq)

    def run(self) -> int:
        """Roll out and push until this worker-id's stream reaches
        ``--actor-steps`` (a respawn resumes above its watermark, so the
        stream's TOTAL length is bounded, not restarted).  Returns the
        number of batches pushed this incarnation."""
        tm0 = time.perf_counter()
        pushed = 0
        while self.seq < self.actor_steps:
            self._step(self._next_batch())
            pushed += 1
            if self._drain is not None:
                # the in-flight exchange is complete: honor a pending
                # SIGTERM here, so the last push is applied exactly once
                self._drain.check()
        self._exchange(
            # protocol: ps request DONE
            lambda: protocol.send_request(self.comm, protocol.OP_DONE),
            what="done",
        )
        log.info(
            f"actor worker-id {self.worker_id} done: stream reached "
            f"{self.seq}/{self.actor_steps} ({pushed} pushed this "
            "incarnation)"
        )
        if self.recorder.enabled:
            # the finished marker pdrnn-metrics health keys on: without
            # it a completed actor's silent sidecar reads as dead in
            # any post-hoc check
            self.recorder.record(
                "run_summary", duration_s=time.perf_counter() - tm0,
                steps=pushed, seq=self.seq, worker_id=self.worker_id,
            )
            self.recorder.flush()
        return pushed

    def deregister(self) -> None:
        """Voluntary leave (the drain path): the roster shrinks without
        burning respawn budget; ``health`` reads the drain, not a death."""
        # protocol: ps request DEREGISTER
        protocol.send_request(
            self.comm, protocol.OP_DEREGISTER, seq=self.seq
        )
        log.info(
            f"actor worker-id {self.worker_id} (rank {self.rank}) "
            f"deregistered after push seq {self.seq}"
        )
        if self.recorder.enabled:
            self.recorder.record(
                "member_drain", worker_id=self.worker_id,
                rank_slot=self.rank, seq=self.seq,
            )
            self.recorder.flush()

    def close(self) -> None:
        if self.comm is not None:
            self.comm.close()
            self.comm = None


def run_actor(args, rank: int, worker_id: int | None = None,
              rejoin: bool = False):
    """One actor process.  ``rejoin`` only gates chaos replay (a
    respawned incarnation must not re-fire the deterministic lifetime
    fault that killed its predecessor) - the JOIN path is identical for
    every actor."""
    from pytorch_distributed_rnn_tpu.obs import MetricsRecorder
    from pytorch_distributed_rnn_tpu.param_server.runner import (
        _build_model_and_flat_params,
        _load_datasets,
    )
    from pytorch_distributed_rnn_tpu.resilience import FaultSchedule
    from pytorch_distributed_rnn_tpu.resilience.membership import (
        DrainRequested,
        DrainSignal,
    )

    logging.basicConfig(level=args.log)
    families.require_family(args, ("rnn", "char"), "streaming")
    drain = DrainSignal().install()
    faults = FaultSchedule.resolve(args, rank=rank)
    if rejoin and faults is not None:
        faults = faults.for_rejoin()
    training_set, _, _ = _load_datasets(args)
    model, _, _ = _build_model_and_flat_params(
        args, training_set, args.seed
    )
    recorder = MetricsRecorder.resolve(
        args, rank=rank, meta={"role": "actor", "rejoin": rejoin}
    )
    plane = None
    if recorder.enabled:
        from pytorch_distributed_rnn_tpu.obs.live import LivePlane
        from pytorch_distributed_rnn_tpu.obs.watchdog import (
            install_stack_dump_handler,
        )

        install_stack_dump_handler(recorder.path)
        plane = LivePlane.resolve(
            args, recorder, rank=rank, role="actor", faults=faults
        )
    actor = None
    try:
        actor = StreamingActor(
            args, model, training_set, rank=rank,
            worker_id=worker_id if worker_id is not None else rank,
            drain_signal=drain, faults=faults, recorder=recorder,
        )
        try:
            return actor.run()
        except DrainRequested:
            actor.deregister()
            log.warning(
                f"actor {rank} drained on SIGTERM (exit "
                f"{DRAIN_EXIT_CODE})"
            )
            return None
    finally:
        if actor is not None:
            actor.close()
        recorder.close()
        if plane is not None:
            plane.close()
