"""``python -m pytorch_distributed_rnn_tpu.serving
{serve,loadgen,router} ...`` - the module form of the ``pdrnn-serve``
/ ``pdrnn-loadgen`` / ``pdrnn-router`` console scripts (the drills
spawn processes through this form so it works from a source checkout
without an installed entry point)."""

from __future__ import annotations

import sys

from pytorch_distributed_rnn_tpu.serving.cli import loadgen_main, serve_main


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if not argv or argv[0] not in ("serve", "loadgen", "router"):
        print(
            "usage: python -m pytorch_distributed_rnn_tpu.serving "
            "{serve,loadgen,router} [options]",
            file=sys.stderr,
        )
        return 2
    if argv[0] == "serve":
        return serve_main(argv[1:])
    if argv[0] == "router":
        from pytorch_distributed_rnn_tpu.serving.fleet.cli import (
            router_main,
        )

        return router_main(argv[1:])
    return loadgen_main(argv[1:])


if __name__ == "__main__":
    sys.exit(main())
