"""The continuous-batching core: pure request/slot bookkeeping.

This module is deliberately jax-free and thread-unaware: the engine
serializes calls under its own lock and runs the device work.  Keeping
the scheduling DECISIONS (admission, shedding, FIFO slot assignment,
join/leave at step boundaries) in plain Python makes the core a pure
unit - ``tests/test_serving_scheduler.py`` drives thousands of
scheduling decisions without touching a device.

Invariants (tested):

- admission is FIFO and shedding is tail-drop: a request is either
  queued in arrival order or rejected immediately (``admit`` returns
  False past ``max_queue``) - never silently dropped later;
- joins happen only through :meth:`take_joins` - the engine calls it at
  step boundaries, so a request can never enter mid-step;
- slot assignment is starvation-free: free slots are filled strictly
  from the queue head, so the wait of the oldest queued request is
  bounded by the remaining tokens of the requests already decoding;
- a slot is reused only after :meth:`release`.
"""

from __future__ import annotations

import itertools
from collections import deque
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class ServeRequest:
    """One generation request plus its lifecycle bookkeeping.

    Timing fields are monotonic stamps (``time.perf_counter``) set by
    the engine; the scheduler never reads a clock.
    """

    prompt: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    seed: int = 0
    id: str = ""
    stream: bool = False
    # engine-facing callbacks (server wires the connection here)
    on_token: Callable | None = None
    on_done: Callable | None = None
    # distributed-tracing context (obs/tracectx.TraceContext) - set by
    # the server only when the request arrived traced AND the engine
    # records; None everywhere else (the zero-overhead-off contract)
    trace: object | None = None
    # lifecycle
    status: str = "queued"  # queued | active | done | shed | error
    error: str | None = None
    tokens: list[int] = field(default_factory=list)
    slot: int | None = None
    bucket: int | None = None
    seq: int | None = None  # admission order, engine-assigned
    arrival_tm: float | None = None
    service_tm: float | None = None  # joined a slot
    prefill_done_tm: float | None = None
    first_token_tm: float | None = None
    done_tm: float | None = None

    @property
    def queue_wait_s(self) -> float | None:
        if self.arrival_tm is None or self.service_tm is None:
            return None
        return self.service_tm - self.arrival_tm

    @property
    def latency_s(self) -> float | None:
        if self.arrival_tm is None or self.done_tm is None:
            return None
        return self.done_tm - self.arrival_tm

    @property
    def ttft_s(self) -> float | None:
        if self.arrival_tm is None or self.first_token_tm is None:
            return None
        return self.first_token_tm - self.arrival_tm

    @property
    def finished(self) -> bool:
        return len(self.tokens) >= self.max_new_tokens


class ContinuousBatcher:
    """Slot/queue bookkeeping for a fixed batch of decode slots."""

    def __init__(self, num_slots: int, max_queue: int = 64):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        self.num_slots = int(num_slots)
        self.max_queue = int(max_queue)
        self._pending: deque[ServeRequest] = deque()
        self._slots: list[ServeRequest | None] = [None] * self.num_slots
        self._seq = itertools.count()
        # observability counters (the engine folds them into run_summary)
        self.admitted = 0
        self.shed = 0
        self.completed = 0

    # -- queue side ----------------------------------------------------------

    def admit(self, request: ServeRequest) -> bool:
        """Queue ``request`` (FIFO) or shed it when the backlog is
        full.  Returns whether it was admitted; a shed request is
        marked so the caller can answer immediately.

        The admission budget is ``max_queue`` PLUS the currently free
        slots: requests destined for an idle slot are not "queued" in
        any meaningful sense (they join at the next step boundary), so
        ``max_queue=0`` means direct-to-slot admission with no waiting
        line - not a server that sheds everything."""
        if len(self._pending) >= self.max_queue + len(self.free_slots()):
            request.status = "shed"
            self.shed += 1
            return False
        request.seq = next(self._seq)
        request.status = "queued"
        self._pending.append(request)
        self.admitted += 1
        return True

    @property
    def queue_depth(self) -> int:
        return len(self._pending)

    @property
    def active_count(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def has_work(self) -> bool:
        return bool(self._pending) or self.active_count > 0

    # -- slot side (engine calls, at step boundaries only) -------------------

    def free_slots(self) -> list[int]:
        return [i for i, r in enumerate(self._slots) if r is None]

    def take_joins(self) -> list[tuple[int, ServeRequest]]:
        """Pop queued requests into free slots, FIFO into ascending slot
        ids.  Called by the engine BETWEEN decode steps - the only path
        from queue to slot, so joins always land on step boundaries."""
        joins = []
        for slot in self.free_slots():
            if not self._pending:
                break
            request = self._pending.popleft()
            request.slot = slot
            request.status = "active"
            self._slots[slot] = request
            joins.append((slot, request))
        return joins

    def active(self) -> list[tuple[int, ServeRequest]]:
        return [
            (i, r) for i, r in enumerate(self._slots) if r is not None
        ]

    def release(self, slot: int) -> ServeRequest:
        """Free ``slot`` after its request finished (or errored); the
        next :meth:`take_joins` may refill it."""
        request = self._slots[slot]
        if request is None:
            raise ValueError(f"slot {slot} is not occupied")
        self._slots[slot] = None
        request.slot = None
        self.completed += 1
        return request

    def abort_pending(self, error: str) -> list[ServeRequest]:
        """Fail every queued request (shutdown path); active slots are
        the engine's to finish or fail."""
        aborted = []
        while self._pending:
            request = self._pending.popleft()
            request.status = "error"
            request.error = error
            aborted.append(request)
        return aborted
