"""Continuous-batching decode engine: jitted programs + slot execution.

The engine owns a fixed batch of ``num_slots`` decode slots and three
jitted programs built ONCE at construction (the PD104 contract - no jit
in the serve loop):

- ``prefill``: one request's bucket-padded prompt -> per-sequence state
  (traced once per prompt bucket);
- ``join``: splice a prefilled sequence into a batch slot at a traced
  slot index (one trace total);
- ``step``: advance every slot one token - split per-slot PRNG keys,
  sample (per-slot temperature, greedy at 0), run the family adapter's
  decode step (one trace total).

After :meth:`warmup` the jit caches hold exactly ``len(buckets) + 2``
programs and the request mix can never add another -
:meth:`retraces_since` asserts that, and the serving tests pin zero
retraces across a mixed-length stream.

Per-slot PRNG keys follow ``generate``'s split-then-sample schedule, so
a request's sampled tokens equal its single-request
``model.generate(..., key=PRNGKey(seed))`` decode exactly (satellite:
per-request keys threaded end to end).

Telemetry rides the existing ``obs/`` recorder: per-decode-step
``step`` events (dispatch/fenced wall time, pre-step wait as
``data_wait_s``, queue depth), ``prefill`` spans, a ``request`` event
per completion, and a ``run_summary`` carrying request-latency/TTFT
percentiles, queue-depth percentiles and tokens/sec - so
``pdrnn-metrics summarize`` / ``timeline`` / ``health`` read serving
runs with the training analysis code unchanged.

Chaos (``resilience/faults.py``) plugs in as on a trainer: ``stall``
faults hold the decode loop (latency grows, the queue sheds),
``nan`` corrupts the in-flight logits - the engine detects non-finite
logits per slot and fails those requests cleanly instead of streaming
garbage - ``exc`` is absorbed as a logged fault, ``kill`` preempts the
process.  The server survives all of them; the SLO drill measures the
degradation window.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pytorch_distributed_rnn_tpu.obs.live import (
    RATE_HORIZON_S,
    RollingWindow,
    request_latency_histogram,
)
from pytorch_distributed_rnn_tpu.obs.recorder import NULL_RECORDER
from pytorch_distributed_rnn_tpu.obs.summary import percentile
from pytorch_distributed_rnn_tpu.resilience.faults import ChaosError
from pytorch_distributed_rnn_tpu.serving.buckets import BucketSpec
from pytorch_distributed_rnn_tpu.serving.scheduler import (
    ContinuousBatcher,
    ServeRequest,
)
from pytorch_distributed_rnn_tpu.utils import threadcheck

log = logging.getLogger(__name__)

_IDLE_WAIT_S = 0.05


# percentile windows: a long-lived server must not grow host memory
# with its request history, so latency/TTFT/queue stats cover the most
# RECENT observations (ample for an SLO view; totals stay exact)
_REQUEST_WINDOW = 4096
_DEPTH_WINDOW = 16384


def decode_step_program(adapter, state, model_params):
    """The batched decode step - the program ``pdrnn-serve`` runs per
    token, registered in ``lint/trace_registry.py`` so the jaxpr deep
    pass covers serving like every trainer step.

    Per slot: split the PRNG key, sample from the CURRENT logits
    (``generate``'s schedule - temperature 0 is greedy argmax), run the
    family adapter's decode step, and flag slots whose logits went
    non-finite (chaos NaN faults / poisoned checkpoints fail their
    request instead of streaming garbage).  Returns
    ``(new_state, tok (B,), ok (B,))``.
    """
    keys, logits = state["keys"], state["logits"]
    temps, pos = state["temps"], state["pos"]
    ks = jax.vmap(jax.random.split)(keys)
    k_next, k_samp = ks[:, 0], ks[:, 1]
    safe_t = jnp.where(temps > 0, temps, 1.0)
    sampled = jax.vmap(jax.random.categorical)(
        k_samp, logits / safe_t[:, None]
    )
    tok = jnp.where(
        temps > 0, sampled, jnp.argmax(logits, axis=-1)
    ).astype(jnp.int32)
    model, new_logits = adapter.step(model_params, state["model"], tok, pos)
    ok = jnp.all(jnp.isfinite(new_logits), axis=-1) & jnp.all(
        jnp.isfinite(logits), axis=-1
    )
    new_state = {
        "model": model, "logits": new_logits, "keys": k_next,
        "pos": pos + 1, "temps": temps,
    }
    return new_state, tok, ok


class ServingEngine:
    """Continuous-batching executor for one model family."""

    def __init__(self, adapter, params, *, num_slots: int = 4,
                 bucket_spec: BucketSpec | None = None,
                 max_new_tokens: int = 64, max_queue: int = 64,
                 recorder=NULL_RECORDER, faults=None):
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        self.adapter = adapter
        self.params = params
        self.buckets = bucket_spec or BucketSpec()
        self.max_new_tokens = int(max_new_tokens)
        if adapter.max_context is not None:
            budget = self.buckets.max_prompt_len + self.max_new_tokens
            if budget > adapter.max_context:
                raise ValueError(
                    f"largest prompt bucket ({self.buckets.max_prompt_len})"
                    f" + max_new_tokens ({self.max_new_tokens}) exceeds the"
                    f" {adapter.family} family's context bound "
                    f"{adapter.max_context}"
                )
        self.batcher = ContinuousBatcher(num_slots, max_queue)
        self.recorder = recorder
        self.faults = faults
        if faults is not None and getattr(recorder, "enabled", False):
            faults.recorder = recorder
        self._work = threading.Condition(
            threadcheck.lock(threading.Lock(), "engine.work"))
        self._closed = False

        # jit construction happens HERE, never in the serve loop; the
        # trace-time counters (bumped when a program body is traced, not
        # when it runs) are the ground truth retraces_since() reads
        self._trace_counts = {"prefill": 0, "step": 0, "join": 0}

        def prefill_fn(model_params, prompt, length):
            self._trace_counts["prefill"] += 1
            return self.adapter.prefill(model_params, prompt, length)

        def join_fn(state, seq_state, seq_logits, key, length, temp, slot):
            self._trace_counts["join"] += 1
            model = jax.tree.map(
                lambda full, one: lax.dynamic_update_slice_in_dim(
                    full, one, slot, axis=0),
                state["model"], seq_state,
            )
            return {
                "model": model,
                "logits": lax.dynamic_update_slice_in_dim(
                    state["logits"], seq_logits, slot, axis=0),
                "keys": lax.dynamic_update_slice_in_dim(
                    state["keys"], key[None], slot, axis=0),
                "pos": state["pos"].at[slot].set(length),
                "temps": state["temps"].at[slot].set(temp),
            }

        def step_fn(state, model_params):
            self._trace_counts["step"] += 1
            return decode_step_program(self.adapter, state, model_params)

        self._prefill = jax.jit(prefill_fn)
        self._join = jax.jit(join_fn, donate_argnums=(0,))
        self._step = jax.jit(step_fn, donate_argnums=(0,))
        self.state = self._fresh_state()

        # serving statistics (windowed deques: bounded memory for a
        # long-lived server; counters stay exact totals)
        self._steps = 0
        self._tokens_out = 0
        self._requests_done = 0
        self._started_tm = time.perf_counter()
        # guards the stat deques AND the scalar counters: the engine
        # thread mutates while connection threads read in stats() (an
        # unguarded deque raises "mutated during iteration" mid-sort;
        # unguarded counters tear a snapshot across a step)
        self._stats_lock = threadcheck.lock(threading.Lock(), "engine.stats")  # guards: _latencies, _ttfts, _queue_waits, _queue_depths, _steps, _tokens_out, _requests_done, _requests_failed, _chaos_exceptions
        self._latencies: deque[float] = deque(maxlen=_REQUEST_WINDOW)
        self._ttfts: deque[float] = deque(maxlen=_REQUEST_WINDOW)
        self._queue_waits: deque[float] = deque(maxlen=_REQUEST_WINDOW)
        self._queue_depths: deque[int] = deque(maxlen=_DEPTH_WINDOW)
        self._requests_failed = 0
        self._chaos_exceptions = 0
        # time-bounded rate windows (obs/live.py RollingWindow - THE
        # windowing implementation, shared with the live exporter):
        # completions observe the request's token count (so one window
        # yields both req/s and tokens/s), sheds observe 1
        self._completions = RollingWindow(RATE_HORIZON_S)
        self._sheds = RollingWindow(RATE_HORIZON_S)
        # request-latency histogram behind the aggregator's
        # pdrnn_request_latency_seconds series; traced completions stamp
        # their bucket's exemplar with their trace_id.  Constructed via
        # the SHARED spec (obs/live.request_latency_histogram) so the
        # router's buckets and the store's quantile sketches line up.
        self._latency_hist = request_latency_histogram()

    # -- construction helpers ------------------------------------------------

    def _fresh_state(self):
        batch = self.batcher.num_slots
        return {
            "model": self.adapter.state_template(self.params, batch),
            "logits": jnp.zeros(
                (batch, self.adapter.vocab_size), jnp.float32),
            "keys": jnp.zeros((batch, 2), jnp.uint32),
            "pos": jnp.zeros((batch,), jnp.int32),
            "temps": jnp.zeros((batch,), jnp.float32),
        }

    def warmup(self):
        """Trace every program the serve loop can need: one prefill per
        prompt bucket, one join, one step.  Steady-state serving then
        never compiles - the zero-retrace contract."""
        state = self.state
        for bucket in self.buckets.prompt_buckets:
            prompt = jnp.zeros((1, bucket), jnp.int32)
            seq_state, logits = self._prefill(
                self.params, prompt, jnp.ones((1,), jnp.int32)
            )
            state = self._join(
                state, seq_state, logits, jnp.zeros((2,), jnp.uint32),
                jnp.int32(1), jnp.float32(0.0), jnp.int32(0),
            )
        state, tok, _ = self._step(state, self.params)
        jax.block_until_ready(tok)
        # warmup ran on the live state tree (donated through each call);
        # reset to blank slots for serving
        self.state = self._fresh_state()

    # -- retrace accounting --------------------------------------------------

    def retrace_snapshot(self) -> dict:
        return dict(self._trace_counts)

    def retraces_since(self, snapshot: dict) -> dict:
        """Programs traced since ``snapshot`` (empty dict = none)."""
        return {
            name: count - snapshot.get(name, 0)
            for name, count in self._trace_counts.items()
            if count != snapshot.get(name, 0)
        }

    # -- request side (any thread) -------------------------------------------

    def submit(self, request: ServeRequest) -> bool:
        """Queue ``request``; False = shed (queue full) or rejected
        (malformed), with ``request.status``/``error`` set."""
        try:
            request.bucket = self.buckets.bucket_for(len(request.prompt))
        except ValueError as exc:
            request.status = "error"
            request.error = str(exc)
            return False
        if not 1 <= request.max_new_tokens <= self.max_new_tokens:
            request.status = "error"
            request.error = (
                f"max_new_tokens must be in [1, {self.max_new_tokens}], "
                f"got {request.max_new_tokens}"
            )
            return False
        if request.temperature < 0:
            request.status = "error"
            request.error = "temperature must be >= 0"
            return False
        # PRNGKey takes a C-long seed; an unchecked client bigint would
        # raise OverflowError ON THE ENGINE THREAD at join time
        if not -(2 ** 63) <= request.seed < 2 ** 63:
            request.status = "error"
            request.error = "seed must fit in a signed 64-bit integer"
            return False
        if request.arrival_tm is None:
            request.arrival_tm = time.perf_counter()
        with self._work:
            admitted = self.batcher.admit(request)
            if admitted:
                self._work.notify_all()
        if not admitted and request.status == "shed":
            self._sheds.observe(1.0)
        return admitted

    # -- serve loop (one thread) ---------------------------------------------

    def run_step(self, wait_s: float = _IDLE_WAIT_S) -> bool:
        """One scheduler iteration: join waiting requests into free
        slots, advance the batch one decode step, deliver tokens and
        retire finished sequences.  Blocks up to ``wait_s`` for work
        when idle; returns whether a decode step ran."""
        wait_t0 = time.perf_counter()
        with self._work:
            if not self.batcher.has_work:
                self._work.wait(timeout=wait_s)
            joins = self.batcher.take_joins()
        for slot, request in joins:
            self._do_join(slot, request)
        with self._work:
            active = self.batcher.active()
        if not active:
            return False

        with self._stats_lock:
            step_index = self._steps
            self._steps += 1
        if self.faults is not None:
            self._apply_faults(step_index)
        t0 = time.perf_counter()
        self.state, tok, ok = self._step(self.state, self.params)
        toks = np.asarray(tok)  # blocks: serving needs the values
        ok = np.asarray(ok)
        step_s = time.perf_counter() - t0

        rec = self.recorder
        if rec.enabled:
            depth = self.batcher.queue_depth
            with self._stats_lock:
                self._queue_depths.append(depth)
            rec.record(
                "step", step=step_index, dispatch_s=step_s,
                fenced_s=step_s if rec.is_sample_step(step_index) else None,
                # pre-dispatch wait: idle + joins (prefill is serving's
                # input pipeline, so it lands in the data phase)
                data_wait_s=max(0.0, t0 - wait_t0), tm=t0,
                queue_depth=depth, active=len(active),
            )
            rec.note_progress(step_index)
        else:
            with self._stats_lock:
                self._queue_depths.append(self.batcher.queue_depth)

        now = time.perf_counter()
        for slot, request in active:
            if not ok[slot]:
                self._finish(
                    slot, request, now,
                    error="non-finite logits during decode (chaos fault "
                          "or poisoned checkpoint)",
                )
                continue
            token = int(toks[slot])
            request.tokens.append(token)
            if request.first_token_tm is None:
                request.first_token_tm = now
            if request.on_token is not None:
                request.on_token(request, token)
            if request.finished:
                self._finish(slot, request, now)
        return True

    def _do_join(self, slot: int, request: ServeRequest):
        t0 = time.perf_counter()
        request.service_tm = t0
        padded = self.buckets.pad(request.prompt)
        seq_state, logits = self._prefill(
            self.params, jnp.asarray(padded),
            jnp.asarray([len(request.prompt)], jnp.int32),
        )
        key = jax.random.PRNGKey(request.seed)
        self.state = self._join(
            self.state, seq_state, logits, key,
            jnp.int32(len(request.prompt)),
            jnp.float32(request.temperature), jnp.int32(slot),
        )
        if self.recorder.enabled:
            tm_done = time.perf_counter()
            request.prefill_done_tm = tm_done
            # traced requests thread their context into the span so the
            # cross-process assembler (obs/trace.py) can re-join it
            extra = ({} if request.trace is None
                     else request.trace.child().span_fields())
            self.recorder.emit_span(
                "prefill", t0, tm_done - t0, cat="serving",
                request=request.id or request.seq, bucket=request.bucket,
                prompt_len=len(request.prompt), slot=slot, **extra,
            )

    def _finish(self, slot: int, request: ServeRequest, now: float,
                error: str | None = None):
        with self._work:
            self.batcher.release(slot)
        request.done_tm = now
        if error is not None:
            request.status = "error"
            request.error = error
        else:
            request.status = "done"
        self._completions.observe(len(request.tokens))
        with self._stats_lock:
            if error is not None:
                self._requests_failed += 1
            self._requests_done += 1
            self._tokens_out += len(request.tokens)
            if request.latency_s is not None:
                self._latencies.append(request.latency_s)
            if request.ttft_s is not None:
                self._ttfts.append(request.ttft_s)
            if request.queue_wait_s is not None:
                self._queue_waits.append(request.queue_wait_s)
        if request.latency_s is not None:
            self._latency_hist.observe(
                request.latency_s,
                trace_id=None if request.trace is None
                else request.trace.trace_id,
            )
        if self.recorder.enabled:
            self.recorder.record(
                "request", request=request.id or request.seq,
                status=request.status, tokens=len(request.tokens),
                latency_s=request.latency_s, ttft_s=request.ttft_s,
                queue_s=request.queue_wait_s, bucket=request.bucket,
                error=request.error,
            )
        if request.trace is not None:
            self._emit_trace_spans(request, now)
        if request.on_done is not None:
            request.on_done(request)

    def _emit_trace_spans(self, request: ServeRequest, now: float):
        """The replica's lifecycle spans of one TRACED request, emitted
        at completion as children of the router's dispatch-attempt span
        (``request.trace``): queue_wait (admission -> slot), decode
        (prefill end -> done; prefill itself is the cat="serving" span
        ``_do_join`` stamps with its own child context), and stream_emit
        (first token -> done) under decode for streamed requests.
        Only reachable when the request arrived traced AND the engine
        records, so the untraced path allocates nothing."""
        ctx = request.trace
        ident = request.id or request.seq
        if request.arrival_tm is not None \
                and request.service_tm is not None:
            self.recorder.emit_span(
                "queue_wait", request.arrival_tm,
                request.service_tm - request.arrival_tm, cat="trace",
                request=ident, **ctx.child().span_fields(),
            )
        decode_start = (request.prefill_done_tm
                        if request.prefill_done_tm is not None
                        else request.service_tm)
        if decode_start is not None:
            decode_ctx = ctx.child()
            self.recorder.emit_span(
                "decode", decode_start, max(0.0, now - decode_start),
                cat="trace", request=ident, slot=request.slot,
                tokens=len(request.tokens), status=request.status,
                **decode_ctx.span_fields(),
            )
            if request.stream and request.first_token_tm is not None:
                self.recorder.emit_span(
                    "stream_emit", request.first_token_tm,
                    max(0.0, now - request.first_token_tm), cat="trace",
                    request=ident, tokens=len(request.tokens),
                    **decode_ctx.child().span_fields(),
                )

    def _apply_faults(self, step_index: int):
        """Trainer-style chaos hooks on the decode loop: stall holds the
        loop, exc is absorbed (the server must survive), nan poisons the
        in-flight logits (caught per slot next step), kill preempts."""
        try:
            self.faults.on_producer_item(step_index)
        except ChaosError as exc:
            with self._stats_lock:
                self._chaos_exceptions += 1
            log.warning(f"serving: absorbed injected failure: {exc}")
        if self.faults.has_step_events:
            logits, _ = self.faults.corrupt_batch(
                step_index, (self.state["logits"], None)
            )
            if logits is not self.state["logits"]:
                self.state = {**self.state, "logits": logits}
        self.faults.maybe_kill(step=step_index)

    def serve_forever(self, stop_event: threading.Event):
        """The engine loop, hardened: one request's failure must fail
        THAT request, never the serve thread - a dead engine behind a
        live TCP front end would hang every future client."""
        while not stop_event.is_set():
            try:
                self.run_step()
            except Exception:
                log.exception(
                    "serving: decode loop error; failing the in-flight "
                    "batch and continuing"
                )
                self._recover()

    def _recover(self):
        """Fail every active request and reset the batch state (a loop
        exception may have left it partially updated or donated-away);
        queued requests are untouched and decode next."""
        now = time.perf_counter()
        with self._work:
            active = self.batcher.active()
        for slot, request in active:
            self._finish(
                slot, request, now,
                error="internal decode error (see server log)",
            )
        self.state = self._fresh_state()

    def drain(self):
        """Run until queue and slots are empty (tests, shutdown)."""
        while self.batcher.has_work:
            self.run_step(wait_s=0.0)

    # -- shutdown / stats ----------------------------------------------------

    def stats(self) -> dict:
        with self._stats_lock:
            lat = sorted(self._latencies)
            ttft = sorted(self._ttfts)
            waits = sorted(self._queue_waits)
            depths = sorted(self._queue_depths)
            steps = self._steps
            requests_done = self._requests_done
            requests_failed = self._requests_failed
            tokens_out = self._tokens_out
            chaos_absorbed = self._chaos_exceptions
        elapsed = time.perf_counter() - self._started_tm
        return {
            "steps": steps,
            "requests": requests_done,
            "requests_shed": self.batcher.shed,
            # every errored completion: non-finite logits, decode-loop
            # recovery, shutdown mid-decode
            "requests_failed": requests_failed,
            "queue_depth": self.batcher.queue_depth,
            "active": self.batcher.active_count,
            "tokens_out": tokens_out,
            "tokens_per_s": tokens_out / elapsed if elapsed > 0
            else None,
            # rolling-window rates (last RATE_HORIZON_S seconds, honest
            # early in the run: the divisor is the window's actual age)
            "req_per_s_60s": self._completions.count_rate(),
            "tokens_per_s_60s": self._completions.sum_rate(),
            "shed_per_s_60s": self._sheds.count_rate(),
            "latency_s_p50": percentile(lat, 0.50) if lat else None,
            "latency_s_p95": percentile(lat, 0.95) if lat else None,
            "ttft_s_p50": percentile(ttft, 0.50) if ttft else None,
            "ttft_s_p95": percentile(ttft, 0.95) if ttft else None,
            "queue_s_p50": percentile(waits, 0.50) if waits else None,
            "queue_s_p95": percentile(waits, 0.95) if waits else None,
            "queue_depth_p50": percentile(depths, 0.50) if depths
            else None,
            "queue_depth_p95": percentile(depths, 0.95) if depths
            else None,
            "queue_depth_max": depths[-1] if depths else None,
            "chaos_absorbed": chaos_absorbed,
            "trace_counts": dict(self._trace_counts),
        }

    def live_source(self) -> dict:
        """Digest contribution for the live exporter
        (``LiveExporter.add_source``): the serving gauge block behind
        the aggregator's ``pdrnn_serving_*`` Prometheus series and the
        watchdog's SLO detector - the same numbers the ``stats`` op
        serves, under one ``serving`` key."""
        stats = self.stats()
        block = {
            k: stats.get(k) for k in (
                "requests", "requests_shed", "requests_failed",
                "tokens_out", "queue_depth", "active",
                "req_per_s_60s", "tokens_per_s_60s", "shed_per_s_60s",
                "latency_s_p50", "latency_s_p95",
                "ttft_s_p50", "ttft_s_p95",
            )
        }
        # slot count rides the digest so the store can derive slot
        # utilization and size the fleet (recommended_replicas)
        block["num_slots"] = self.batcher.num_slots
        hist = self._latency_hist.snapshot()
        if hist is not None:
            block["latency_hist"] = hist
        return {"serving": block}

    def close(self):
        """Abort queued AND in-flight requests (their clients get an
        error event, not a dead socket), emit the run summary;
        idempotent."""
        if self._closed:
            return
        self._closed = True
        with self._work:
            aborted = self.batcher.abort_pending("server shutting down")
            active = self.batcher.active()
        for request in aborted:
            if request.on_done is not None:
                request.on_done(request)
        now = time.perf_counter()
        for slot, request in active:
            self._finish(slot, request, now,
                         error="server shut down mid-decode")
        if self.recorder.enabled:
            stats = self.stats()
            # the repo's one RSS definition (utils/profiling.py): the
            # trainers sample it around a bounded run; a long-lived
            # server reports the close-time reading
            from pytorch_distributed_rnn_tpu.utils.profiling import _rss_mb

            self.recorder.record(
                "run_summary",
                duration_s=time.perf_counter() - self._started_tm,
                memory_mb=_rss_mb() or None,
                **{k: v for k, v in stats.items()
                   if k not in ("queue_depth", "active", "trace_counts")},
            )
            self.recorder.flush()


# ---------------------------------------------------------------------------
# trace-registry provider (lint deep pass)

# abstract serving shapes for the deep pass: a small batch and one
# prompt bucket is enough - the rules are shape-generic
_TRACE_SLOTS = 4
_TRACE_BUCKET = 16


def _trace_model(family: str):
    from pytorch_distributed_rnn_tpu.models import AttentionLM, CharRNN, MoELM

    if family == "char":
        return CharRNN(vocab_size=256, embed_dim=32, hidden_dim=32,
                       layer_dim=2, impl="scan")
    if family == "attention":
        return AttentionLM(vocab_size=256, dim=32, depth=2, num_heads=4,
                           max_len=64)
    return MoELM(vocab_size=256, embed_dim=32, hidden_dim=32, layer_dim=2)


def declare_trace_entries(register):
    """Serving decode/prefill entry points for ``pdrnn-lint --deep``:
    the continuous-batching step per family plus the bucket-padded
    prefill - abstract specs only, single-device (no mesh)."""
    from pytorch_distributed_rnn_tpu.lint.trace_registry import (
        abstract_init,
        prng_spec,
        sds,
    )

    def abstract_setup(family: str):
        import jax
        import jax.numpy as jnp

        from pytorch_distributed_rnn_tpu.serving.adapters import adapter_for

        model = _trace_model(family)
        adapter = adapter_for(model)
        params = abstract_init(model.init, prng_spec())
        state = jax.eval_shape(
            lambda p: {
                "model": adapter.state_template(p, _TRACE_SLOTS),
                "logits": jnp.zeros(
                    (_TRACE_SLOTS, adapter.vocab_size), jnp.float32),
                "keys": jnp.zeros((_TRACE_SLOTS, 2), jnp.uint32),
                "pos": jnp.zeros((_TRACE_SLOTS,), jnp.int32),
                "temps": jnp.zeros((_TRACE_SLOTS,), jnp.float32),
            },
            params,
        )
        return adapter, params, state

    def build_step(family: str):
        def build():
            import functools

            adapter, params, state = abstract_setup(family)
            return functools.partial(decode_step_program, adapter), (
                state, params,
            )

        return build

    def build_prefill(family: str):
        def build():
            import jax.numpy as jnp

            adapter, params, _ = abstract_setup(family)
            return adapter.prefill, (
                params,
                sds((1, _TRACE_BUCKET), jnp.int32),
                sds((1,), jnp.int32),
            )

        return build

    for family in ("char", "attention", "moe"):
        register(
            name=f"serving.{family}_decode_step",
            family="serving",
            path="pytorch_distributed_rnn_tpu/serving/engine.py",
            build=build_step(family),
            kind="forward",
            donate=(0,),
        )
    register(
        name="serving.char_prefill",
        family="serving",
        path="pytorch_distributed_rnn_tpu/serving/adapters.py",
        build=build_prefill("char"),
        kind="forward",
    )
