"""Poisson load generator + latency/SLO report for the serving endpoint.

Deterministic in ``seed``: arrival gaps draw from an exponential
distribution (Poisson process at ``rate`` req/s), prompt lengths and
decode lengths draw uniformly from configured ranges, prompts are
random in-vocab ids (or, against byte-vocab models, any ``--text``
corpus slice the CLI passes).  Each request runs on its own thread and
connection at its scheduled arrival offset - the server's continuous
batching, not the client, provides the concurrency.

The report aggregates per-request outcomes into SLO-facing numbers
(p50/p95/p99 latency, TTFT, throughput, shed/error counts) plus a
per-second timeline used by the chaos SLO drill: a second is DEGRADED
when requests were shed, failed, or finished above the latency SLO in
it, and the drill asserts the degradation window opens under the
injected fault and closes after it - graceful degradation, not an
outage.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import numpy as np

from pytorch_distributed_rnn_tpu.obs.summary import percentile
from pytorch_distributed_rnn_tpu.obs.tracectx import (
    TraceContext,
    should_sample,
)
from pytorch_distributed_rnn_tpu.serving.protocol import (
    ProtocolError,
    ServingClient,
)

# report caps: how many slowest / violating requests the report NAMES
# (ids + trace ids - the handles `pdrnn-metrics trace` pulls)
SLOWEST_NAMED = 5
VIOLATIONS_NAMED = 20


@dataclass(frozen=True)
class LoadConfig:
    host: str = "127.0.0.1"
    port: int = 0
    requests: int = 50
    rate: float = 25.0  # mean Poisson arrivals per second
    prompt_len_min: int = 2
    prompt_len_max: int = 24
    new_tokens_min: int = 4
    new_tokens_max: int = 24
    temperature: float = 0.0
    sampled_fraction: float = 0.5  # share of requests at `temperature`
    seed: int = 0
    stream: bool = False
    timeout_s: float = 120.0
    connect_timeout_s: float = 5.0
    low_priority_fraction: float = 0.0  # share tagged priority=low
    deadline_ms: float | None = None  # server-side QoS deadline field
    slo_p95_ms: float = 2000.0
    slo_ttft_p95_ms: float | None = None
    # head-sample this fraction of requests into distributed traces
    # (deterministic, RNG-free: sampling must not shift the seeded plan)
    trace_sample: float = 0.0


@dataclass
class RequestOutcome:
    index: int
    arrival_s: float  # offset from load start
    priority: str = "normal"
    status: str = "pending"  # done | shed | error
    latency_ms: float | None = None
    ttft_ms: float | None = None
    queue_ms: float | None = None
    tokens: int = 0
    error: str | None = None
    done_at_s: float | None = None
    request_id: str | None = None
    # loadgen-minted (--trace-sample) or router-assigned trace id - the
    # handle the report prints for pdrnn-metrics trace
    trace_id: str | None = None
    _reply: dict | None = field(default=None, repr=False)


def _percentile(sorted_values, q: float) -> float | None:
    """The shared nearest-rank convention (``obs/summary.py``), mapped
    to None-on-empty for clean JSON reports."""
    return percentile(sorted_values, q) if sorted_values else None


def plan_requests(cfg: LoadConfig, vocab_size: int,
                  max_prompt_len: int, max_new_tokens: int) -> list[dict]:
    """The deterministic request schedule: arrival offsets + payloads,
    clamped to the server's advertised limits."""
    rng = np.random.RandomState(cfg.seed)
    # priorities draw from their OWN stream: turning the QoS mix on or
    # off must not shift the base plan (arrivals/prompts/seeds), which
    # tests and cross-run comparisons pin by cfg.seed
    prio_rng = np.random.RandomState(cfg.seed + 104729)
    gaps = rng.exponential(1.0 / max(cfg.rate, 1e-9), size=cfg.requests)
    arrivals = np.cumsum(gaps)
    plen_hi = min(cfg.prompt_len_max, max_prompt_len)
    plen_lo = min(cfg.prompt_len_min, plen_hi)
    ntok_hi = min(cfg.new_tokens_max, max_new_tokens)
    ntok_lo = min(cfg.new_tokens_min, ntok_hi)
    plan = []
    for i in range(cfg.requests):
        plen = int(rng.randint(plen_lo, plen_hi + 1))
        plan.append({
            "arrival_s": float(arrivals[i]),
            "prompt": rng.randint(0, vocab_size, size=plen).tolist(),
            "max_new_tokens": int(rng.randint(ntok_lo, ntok_hi + 1)),
            "temperature": (
                cfg.temperature
                if rng.random_sample() < cfg.sampled_fraction else 0.0
            ),
            "seed": int(rng.randint(0, 2 ** 31 - 1)),
            "priority": (
                "low"
                if prio_rng.random_sample() < cfg.low_priority_fraction
                else "normal"
            ),
        })
    return plan


def run_load(cfg: LoadConfig, progress=None) -> dict:
    """Fire the configured request mix at the server; returns the
    report dict (see :func:`build_report`)."""
    with ServingClient(cfg.host, cfg.port, timeout_s=10.0) as probe:
        info = probe.ping()
    plan = plan_requests(
        cfg, int(info["vocab_size"]), int(info["max_prompt_len"]),
        int(info["max_new_tokens"]),
    )
    outcomes = [
        RequestOutcome(index=i, arrival_s=p["arrival_s"],
                       priority=p.get("priority", "normal"))
        for i, p in enumerate(plan)
    ]
    t0 = time.perf_counter()

    def fire(i: int):
        spec = plan[i]
        out = outcomes[i]
        out.request_id = str(i)
        # trace minting at the loadgen edge: deterministic head
        # sampling (no RNG - the seeded request plan must not shift
        # when tracing turns on)
        ctx = None
        if cfg.trace_sample > 0.0 \
                and should_sample(i + 1, cfg.trace_sample):
            ctx = TraceContext.mint(qos=spec.get("priority"))
            out.trace_id = ctx.trace_id
        try:
            # connect bounded separately from reads (a vanished target
            # fails the dial in seconds), and deadline_s caps the WHOLE
            # request - a stream dribbling tokens resets the per-read
            # timeout forever and would pin this worker without it
            with ServingClient(
                cfg.host, cfg.port, timeout_s=cfg.timeout_s,
                connect_timeout_s=cfg.connect_timeout_s,
            ) as client:
                reply = client.generate(
                    prompt=spec["prompt"],
                    max_new_tokens=spec["max_new_tokens"],
                    temperature=spec["temperature"], seed=spec["seed"],
                    stream=cfg.stream, request_id=str(i),
                    priority=(spec["priority"]
                              if cfg.low_priority_fraction > 0 else None),
                    deadline_ms=cfg.deadline_ms,
                    deadline_s=cfg.timeout_s,
                    trace=ctx,
                )
        except (OSError, ProtocolError) as exc:
            out.status = "error"
            out.error = str(exc)
            out.done_at_s = time.perf_counter() - t0
            return
        out.done_at_s = time.perf_counter() - t0
        out._reply = reply
        # a router tracing via --trace-sample echoes ITS minted trace
        # id on the final payload - adopt it so the report names a
        # pullable trace even when the loadgen sent none
        if reply.get("trace_id"):
            out.trace_id = str(reply["trace_id"])
        if reply.get("event") == "done":
            out.status = "done"
            out.latency_ms = reply.get("latency_ms")
            out.ttft_ms = reply.get("ttft_ms")
            out.queue_ms = reply.get("queue_ms")
            out.tokens = int(reply.get("token_count", 0))
        else:
            out.status = "shed" if reply.get("shed") else "error"
            out.error = reply.get("error")
        if progress is not None:
            progress(out)

    # dispatcher spawns each worker AT its arrival time, so live thread
    # count tracks in-flight requests - never the whole plan (a 10k-
    # request low-rate run must not reserve 10k thread stacks up front)
    threads: list[threading.Thread] = []
    for i in range(len(plan)):
        delay = t0 + plan[i]["arrival_s"] - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        thread = threading.Thread(target=fire, args=(i,), daemon=True,
                                  name=f"pdrnn-loadgen-{i}")
        thread.start()
        threads.append(thread)
    for thread in threads:
        thread.join(timeout=cfg.timeout_s + 30.0)
    wall_s = time.perf_counter() - t0
    # a worker still running past its join timeout is a LOST request;
    # leaving it 'pending' would drop it from done/shed/errors and let
    # the report claim SLO-pass with requests unaccounted for
    for out in outcomes:
        if out.status == "pending":
            out.status = "error"
            out.error = f"no response within {cfg.timeout_s + 30.0:.0f}s"
            out.done_at_s = wall_s
    return build_report(cfg, outcomes, wall_s)


def build_report(cfg: LoadConfig, outcomes: list[RequestOutcome],
                 wall_s: float) -> dict:
    """Aggregate outcomes into the SLO report."""
    done = [o for o in outcomes if o.status == "done"]
    shed = [o for o in outcomes if o.status == "shed"]
    errored = [o for o in outcomes if o.status == "error"]
    lat = sorted(o.latency_ms for o in done if o.latency_ms is not None)
    ttft = sorted(o.ttft_ms for o in done if o.ttft_ms is not None)
    queue = sorted(o.queue_ms for o in done if o.queue_ms is not None)
    tokens = sum(o.tokens for o in done)

    # per-second timeline: what the chaos drill reads the degradation
    # window from (keyed by COMPLETION second)
    seconds: dict[int, dict] = {}
    for o in outcomes:
        if o.done_at_s is None:
            continue
        bucket = seconds.setdefault(
            int(o.done_at_s), {"done": 0, "shed": 0, "error": 0,
                               "latencies_ms": []},
        )
        bucket[o.status] = bucket.get(o.status, 0) + 1
        if o.status == "done" and o.latency_ms is not None:
            bucket["latencies_ms"].append(o.latency_ms)
    timeline = []
    for second in sorted(seconds):
        bucket = seconds[second]
        lats = sorted(bucket.pop("latencies_ms"))
        p95 = _percentile(lats, 0.95)
        degraded = bool(
            bucket["shed"] or bucket["error"]
            or (p95 is not None and p95 > cfg.slo_p95_ms)
        )
        timeline.append({
            "second": second, **bucket, "p95_ms": p95,
            "degraded": degraded,
        })
    degraded_seconds = [t["second"] for t in timeline if t["degraded"]]

    # per-QoS-class breakdown: the fleet drill's shed-ordering check
    # (low must shed first) reads these
    by_priority: dict[str, dict] = {}
    for o in outcomes:
        bucket = by_priority.setdefault(
            o.priority, {"requests": 0, "done": 0, "shed": 0,
                         "errors": 0},
        )
        bucket["requests"] += 1
        key = "errors" if o.status == "error" else o.status
        bucket[key] = bucket.get(key, 0) + 1

    # name the handles a failed drill needs: the slowest completions
    # and every SLO-violating request, each with the trace id (when
    # traced) that pdrnn-metrics trace pulls
    def _named(o: RequestOutcome, **extra) -> dict:
        return {
            "request_id": (o.request_id if o.request_id is not None
                           else str(o.index)),
            "trace_id": o.trace_id, **extra,
        }

    ranked = sorted((o for o in done if o.latency_ms is not None),
                    key=lambda o: -o.latency_ms)
    slowest = [_named(o, latency_ms=o.latency_ms)
               for o in ranked[:SLOWEST_NAMED]]
    violations = []
    for o in done:
        if o.latency_ms is not None and o.latency_ms > cfg.slo_p95_ms:
            violations.append(
                _named(o, reason="latency", latency_ms=o.latency_ms))
        elif cfg.slo_ttft_p95_ms is not None and o.ttft_ms is not None \
                and o.ttft_ms > cfg.slo_ttft_p95_ms:
            violations.append(
                _named(o, reason="ttft", ttft_ms=o.ttft_ms))

    p95 = _percentile(lat, 0.95)
    ttft_p95 = _percentile(ttft, 0.95)
    slo = {
        "p95_ms": cfg.slo_p95_ms,
        "p95_ok": p95 is not None and p95 <= cfg.slo_p95_ms,
    }
    if cfg.slo_ttft_p95_ms is not None:
        slo["ttft_p95_ms"] = cfg.slo_ttft_p95_ms
        slo["ttft_p95_ok"] = (
            ttft_p95 is not None and ttft_p95 <= cfg.slo_ttft_p95_ms
        )
    return {
        "requests": len(outcomes),
        "done": len(done),
        "shed": len(shed),
        "errors": len(errored),
        "error_samples": sorted({o.error for o in errored if o.error})[:5],
        "wall_s": wall_s,
        "tokens": tokens,
        "tokens_per_s": tokens / wall_s if wall_s > 0 else None,
        "requests_per_s": len(done) / wall_s if wall_s > 0 else None,
        "latency_ms": {
            "p50": _percentile(lat, 0.50), "p95": p95,
            "p99": _percentile(lat, 0.99),
            "max": lat[-1] if lat else None,
        },
        "ttft_ms": {
            "p50": _percentile(ttft, 0.50), "p95": ttft_p95,
        },
        "queue_ms": {
            "p50": _percentile(queue, 0.50),
            "p95": _percentile(queue, 0.95),
        },
        "slo": slo,
        "slowest": slowest,
        "slo_violations": violations,
        "by_priority": by_priority,
        "timeline": timeline,
        "degraded_seconds": degraded_seconds,
        "degradation_window_s": (
            [degraded_seconds[0], degraded_seconds[-1]]
            if degraded_seconds else None
        ),
    }


def format_report(report: dict) -> str:
    """Human-readable report (the CLI's default output)."""
    lines = [
        f"requests {report['requests']}: {report['done']} done, "
        f"{report['shed']} shed, {report['errors']} errors "
        f"in {report['wall_s']:.2f}s",
        f"throughput: {report['tokens']} tokens "
        f"({report['tokens_per_s']:.1f} tok/s, "
        f"{report['requests_per_s']:.2f} req/s)"
        if report["tokens_per_s"] is not None else "throughput: n/a",
    ]
    lat, ttft = report["latency_ms"], report["ttft_ms"]
    if lat["p50"] is not None:
        lines.append(
            f"latency ms: p50 {lat['p50']:.1f}  p95 {lat['p95']:.1f}  "
            f"p99 {lat['p99']:.1f}  max {lat['max']:.1f}"
        )
    if ttft["p50"] is not None:
        lines.append(
            f"ttft ms:    p50 {ttft['p50']:.1f}  p95 {ttft['p95']:.1f}"
        )
    slo = report["slo"]
    verdict = "PASS" if slo.get("p95_ok") else "FAIL"
    lines.append(f"SLO p95 <= {slo['p95_ms']:g}ms: {verdict}")
    if "ttft_p95_ok" in slo:
        verdict = "PASS" if slo["ttft_p95_ok"] else "FAIL"
        lines.append(f"SLO ttft p95 <= {slo['ttft_p95_ms']:g}ms: {verdict}")

    def _handle(entry: dict) -> str:
        trace = entry.get("trace_id")
        return (f"request {entry['request_id']}"
                + (f"  trace {trace}" if trace else ""))

    slowest = report.get("slowest") or []
    if slowest:
        lines.append("slowest (pull with pdrnn-metrics trace "
                     "--request ID):")
        for entry in slowest:
            lines.append(
                f"  {entry['latency_ms']:8.1f}ms  {_handle(entry)}")
    violations = report.get("slo_violations") or []
    if violations:
        lines.append(f"SLO violations ({len(violations)}):")
        for entry in violations[:VIOLATIONS_NAMED]:
            value = entry.get("latency_ms", entry.get("ttft_ms"))
            lines.append(
                f"  {value:8.1f}ms  {entry['reason']:<7s} "
                f"{_handle(entry)}")
        if len(violations) > VIOLATIONS_NAMED:
            lines.append(
                f"  ... and {len(violations) - VIOLATIONS_NAMED} more")
    window = report["degradation_window_s"]
    if window:
        lines.append(
            f"DEGRADED seconds {report['degraded_seconds']} "
            f"(window {window[0]}..{window[1]}s)"
        )
    else:
        lines.append("no degraded seconds")
    return "\n".join(lines)
